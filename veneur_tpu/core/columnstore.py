"""The device column store: metric keys are rows, samples are batches.

This replaces the reference's per-worker map-of-samplers hot path
(reference worker.go:59-176, WorkerMetrics.Upsert and the per-type maps)
with four device-resident tables:

  counters  (K,)      f32 accumulators
  gauges    (K,)      f32 last-write-wins + set mask
  histos    (K, C)    t-digest centroid grids + per-key stats
  sets      (K, 16k)  HLL registers

A host dictionary interns MetricKey (by 64-bit fnv1a digest) to a row id;
names/tags/scopes never leave the host. Samples append into pinned numpy
batch buffers and are applied to device arrays in fixed-size padded batches
(one scatter/sort kernel per batch), so the device sees a few large
dispatches per second instead of one per packet.

State is interval-scoped: flush snapshots the device arrays and zeroes them
(the map-swap trick of reference worker.go:470-489); the key dictionary
persists so steady-state ingest never re-interns.

Capacity management: row capacity doubles on demand (device arrays are
padded and the jitted kernels recompile once per capacity, amortized to
zero); batch buffers are fixed-size so kernels compile once per (capacity,
batch) shape.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from functools import lru_cache, partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from veneur_tpu.ops import (batch_hll, batch_llhist, batch_tdigest,
                            hll_ref, llhist_ref, scalars)
from veneur_tpu.samplers import metrics as m
from veneur_tpu.samplers.metrics import MetricScope, UDPMetric

logger = logging.getLogger("veneur_tpu.core.columnstore")

# pending-buffer padding marker: any out-of-range row is dropped by the
# scatter kernels (mode="drop"), independent of table capacity
PAD_ROW = np.int32(2**31 - 1)


@partial(jax.jit, donate_argnums=0)
def _zeros_like_donated(tree):
    """Zero a drained interval generation IN PLACE (buffer donation —
    the SNIPPETS pjit donation vectors): the returned fresh generation
    aliases the donated input's buffers, so the double-buffered flush
    ping-pongs two device allocations per family instead of allocating
    a new interval state every flush."""
    return jax.tree.map(jnp.zeros_like, tree)


def _state_device(tree):
    return next(iter(jax.tree.leaves(tree)[0].devices()))


@lru_cache(maxsize=None)
def _zeros_like_donated_on(device):
    """Per-device reset variant for the sharded histo/set spare lists.
    The reset's output carries no data dependence on the donated input,
    so without an explicit out_sharding XLA commits it to the DEFAULT
    device — every entry of a per-device spare list would silently land
    on device 0 and the next flush's cross-shard stack would reject the
    duplicate placement."""
    return jax.jit(
        lambda tree: jax.tree.map(jnp.zeros_like, tree),
        donate_argnums=0,
        out_shardings=jax.sharding.SingleDeviceSharding(device))


def _zeros_like_spare(captured):
    """Donate-and-zero one captured generation — a state pytree, or a
    per-device list of them (the sharded histo/set tables), which must
    zero per device because one jit call cannot mix committed devices."""
    if isinstance(captured, list):
        return [_zeros_like_donated_on(_state_device(st))(st)
                for st in captured]
    return _zeros_like_donated(captured)


@partial(jax.jit, donate_argnums=0)
def _reset_tdigest_donated(state):
    """Donated t-digest generation reset: rebuilds init_state's values
    (±inf min/max, zero grids) in the donated buffers."""
    return batch_tdigest.init_state(state["wv"].shape[0])


@lru_cache(maxsize=None)
def _reset_tdigest_donated_on(device):
    # same device pin as _zeros_like_donated_on: init_state's values
    # are constants, so the output needs an explicit placement
    return jax.jit(
        lambda st: batch_tdigest.init_state(st["wv"].shape[0]),
        donate_argnums=0,
        out_shardings=jax.sharding.SingleDeviceSharding(device))


def _reset_tdigest_spare(captured):
    if isinstance(captured, list):
        return [_reset_tdigest_donated_on(_state_device(st))(st)
                for st in captured]
    return _reset_tdigest_donated(captured)


@dataclass
class RowMeta:
    """Host-side identity of a row (never touches the device)."""

    name: str
    tags: List[str]
    joined_tags: str
    digest32: int
    scope: MetricScope
    wire_type: str  # counter/gauge/histogram/timer/set/status
    # per-row cache of rendered flush-metric names ("x.max",
    # "x.99percentile", ...): metas persist across intervals, so the
    # flusher's hot loop renders each name once per key lifetime instead
    # of once per flush
    flush_names: dict = None
    # per-row cache of the metricpb wire prefix/suffix (serialized
    # fields 1-3 and field 9) used by the native forward encoder —
    # identity-only, so it too lives for the row's lifetime
    pb_frame: tuple = None


class _BaseTable:
    """Row interning + touched tracking + capacity doubling, shared by all
    device families.

    Lock discipline (double-buffered hot path — the device-side analog of
    the reference's map-swap, worker.go:470-489):

      * ``lock`` (buffer lock) protects the pending sample columns, the
        row dictionary, meta, and touched masks. Reader threads hold it
        only for memcpy-scale work.
      * ``apply_lock`` protects the device-resident ``state``. It is
        always acquired while still holding ``lock`` (which fixes batch
        application order to buffer-swap order — load-bearing for gauge
        last-write-wins) but is held WITHOUT ``lock`` during the actual
        kernel dispatch, so readers filling the fresh buffer never block
        on a device call.
      * Order: ``lock`` then ``apply_lock``; never the reverse.

    Invariant: a row's touched flag may only be set in the same ``lock``
    hold that makes its value visible to a flush (appended to a pending
    buffer, or applied to state while ``apply_lock`` was acquired under
    ``lock``). Setting it earlier lets a concurrent snapshot clear the
    flag before the value exists (the value is later reset un-emitted);
    setting it later lets a snapshot emit a touched-but-valueless row.
    """

    # family label for self-telemetry rows and the cardinality
    # accountant's shed classes; overwritten per instance by ColumnStore
    family = "unknown"

    def __init__(self, capacity: int = 1024, batch_cap: int = 8192,
                 max_rows: int = 0):
        self.capacity = capacity
        self.batch_cap = batch_cap
        self.max_rows = max_rows  # hard cardinality cap (0 = unlimited)
        self.rows: Dict[int, int] = {}  # digest64 -> row
        self.meta: List[RowMeta] = []
        self.touched = np.zeros(capacity, bool)
        self.lock = threading.Lock()
        self.apply_lock = threading.Lock()
        # cardinality observatory (core/cardinality.py): duck-typed
        # accountant consulted on every mint (admit_mint/note_mint) and
        # fed evictions; None = unlimited, account nothing
        self.cardinality = None
        # flow ledger (core/ledger.py): every sample this table accepts
        # stamps agg.applied, every mint-gate rejection agg.rejected —
        # the out-side of the ingest conservation identity. The ledger
        # lock is a leaf, so stamping under this table's locks is safe.
        self.ledger = None
        # capacity/churn accounting, exported by ColumnStore.telemetry_rows
        # and /debug/cardinality: every counter below is monotonic and
        # mutated only under `lock` (resize/recompile under apply rules
        # documented at the mutation sites)
        self.minted_total = 0
        self.tombstoned_total = 0
        self.recycled_total = 0
        self.dispatch_total = 0
        self.resize_total = 0
        self.resize_seconds_total = 0.0
        self.resize_last_seconds = 0.0
        self.recompile_seconds_total = 0.0
        self.recompile_last_seconds = 0.0
        self._recompile_pending = False
        # on_resize(family, old_capacity, new_capacity, seconds) — the
        # server's flight-recorder hook. Fired while holding the buffer
        # lock, so it must not emit statsd (an internal-loopback
        # self-metric would re-enter this very table's lock); recording
        # a telemetry event (its own lock only) is safe.
        self.on_resize = None
        # idle-row reclamation state (the TPU build's answer to the
        # reference's per-interval map swap, worker.go:470-489: row
        # IDENTITY persists here for fast-path reuse, so under key churn
        # it must be reclaimed or host memory grows without bound).
        # Rows are tombstoned (dict entry + native intern mapping
        # removed) once idle for N flushes, then recycled one further
        # flush later so in-flight native chunks can no longer reference
        # them.
        self._generation = 0
        self._last_touched = np.zeros(capacity, np.int64)
        self._tombstone_gen = np.full(capacity, -1, np.int64)
        self._has_meta = np.zeros(capacity, bool)
        self._dict_key_of: List[int] = []  # row -> rows-dict key
        self._free_rows: List[int] = []
        self.keys_dropped = 0
        # vectorized-flush row caches (core/flusher.py batch assembly):
        # per-row scope code for mask math, and per-row rendered flush
        # names / tag-list refs so steady keysets format strings once per
        # row lifetime, not once per flush. Entries are invalidated when
        # a recycled row is re-interned (row_for) — safe against in-flush
        # races because recycling a row emitted by flush N cannot happen
        # before flush N+1 (reclaim's two-phase contract above), and
        # flushes are serialized by the server's flush lock.
        self.scope_code = np.full(capacity, -1, np.int8)
        self._tags_cache = np.empty(capacity, object)
        self._flush_name_cache: Dict[object, np.ndarray] = {}
        # double-buffered flush: the recycled (already-zeroed) device
        # generation the next swap_out installs, and the capacity it was
        # shaped for (a resize in between invalidates it). Guarded by
        # apply_lock.
        self._spare = None
        self._spare_cap = -1
        # capacities whose kernels the shape-ladder prewarmer has
        # already compiled (core/flushexec.py): the post-resize
        # recompile probe reads this to tag the round prewarmed
        self._prewarmed_caps = set()
        # device observatory (core/deviceobs.py): duck-typed HBM-ledger
        # + kernel-registry sink, None = unregistered. The three token
        # slots track this table's generations through the double-buffer
        # lifecycle (live -> inflight -> spare -> live ...); all three
        # are guarded by apply_lock.
        self._deviceobs = None
        self._devobs_live = None
        self._devobs_spare = None
        self._devobs_inflight = None
        self._init_arrays()

    # subclasses define _init_arrays / _grow_arrays / _apply_cols / reset

    def _swap_locked(self):
        """Copy out and reset the pending columns (caller holds ``lock``).
        Returns the column copies, or None when nothing is pending. The
        whole buffer is copied; rows beyond the fill point are PAD_ROW and
        dropped by the scatter kernels."""
        if self._n == 0:
            return None
        cols = tuple(c.copy() for c in self._pcols)
        self._prow[: self._n] = PAD_ROW
        self._n = 0
        return cols

    def intern(self, metric: UDPMetric) -> int:
        """Intern a metric's row WITHOUT marking it touched — used by
        callers that batch values themselves (ordered gauge replay-merge
        in core.ingest). Touched must only be set once the value is in a
        pending buffer or the state, else a concurrent flush would emit a
        touched-but-valueless row (a fabricated 0.0)."""
        with self.lock:
            return self.row_for(metric)

    def _dispatch_pending_locked(self):
        """Swap the pending buffer out under ``lock`` and apply it to the
        device state with ``lock`` released (``apply_lock`` held). Caller
        holds ``lock`` on entry and on return."""
        cols = self._swap_locked()
        if cols is None:
            return
        self.apply_lock.acquire()
        self.lock.release()
        try:
            if self._recompile_pending:
                # first batch apply after a capacity doubling: the jit
                # kernels retrace+recompile for the new shape here. Time
                # it (block once — compile is the cost being measured)
                # so the TPU-specific resize tax is attributable.
                self._recompile_pending = False
                t0 = time.perf_counter()
                self._apply_cols(cols)
                # sharded tables keep per-device state in `states`
                dev_state = getattr(self, "state",
                                    getattr(self, "states", None))
                if dev_state is not None:
                    try:
                        jax.block_until_ready(jax.tree.leaves(dev_state))
                    except Exception:
                        logger.exception(
                            "post-resize recompile sync failed")
                elapsed = time.perf_counter() - t0
                self.recompile_last_seconds = elapsed
                self.recompile_seconds_total += elapsed
                obs = self._deviceobs
                if obs is not None:
                    obs.note_compile(self.family, elapsed)
                    obs.note_kernel("apply", self.family, elapsed)
                hook = self.on_resize
                if hook is not None:
                    try:
                        hook(self.family, self.capacity, self.capacity,
                             elapsed, kind="recompile",
                             prewarmed=self.capacity in self._prewarmed_caps)
                    except Exception:
                        logger.exception("resize hook failed")
            else:
                obs = self._deviceobs
                if obs is not None:
                    t0 = time.perf_counter()
                    self._apply_cols(cols)
                    obs.note_kernel("apply", self.family,
                                    time.perf_counter() - t0)
                else:
                    self._apply_cols(cols)
            self.dispatch_total += 1
        finally:
            self.apply_lock.release()
            self.lock.acquire()

    # -- two-phase flush: critical-path swap / background readout --------
    #
    # The flush used to be one synchronous pass: swap pending columns,
    # dispatch the readout kernels, sync, transfer — all on the flush
    # loop's critical path, with ingest applies blocked on apply_lock
    # for the full dispatch window (~1.7s of `dispatch_s` at the 100k
    # shape, BENCH_r05). The split below makes the interval boundary a
    # pure generation swap:
    #
    #   swap_out()   O(1) under the table locks: swap the pending
    #                columns out, capture touched/meta, capture the live
    #                device generation and install a fresh one (the
    #                recycled spare when capacity still matches). NO
    #                device dispatch — ingest continues into the fresh
    #                generation the moment the locks drop.
    #   readout()    lock-free on the CAPTURED generation (it is private
    #                to the snapshot): apply the final pending columns,
    #                dispatch the readout kernels. Runs on the server's
    #                background flush executor when `flush_async` is on.
    #   snapshot_finish()  transfer + host assembly (unchanged).
    #   recycle()    after the transfer: donate the drained generation
    #                to the zeroing kernel and park it as the spare —
    #                the second buffer of the double-buffer.

    def swap_out(self, **kw) -> dict:
        """Critical-path flush half: swap this table's interval out with
        no device work. Extra kwargs ride into the snap (family readout
        parameters: ps, need_export, need_bins)."""
        snap = dict(kw)
        with self.lock:
            if self._idle_swap_locked(snap):
                return snap
            snap["cols"] = self._swap_locked()
            with self.apply_lock:
                self._note_generation_locked()
                snap["touched"] = self.touched.copy()
                snap["meta"] = list(self.meta)
                self.touched[:] = False
                self._swap_extras_locked(snap)
                snap["state"] = self._swap_device_locked()
                snap["cap"] = self._state_capacity()
                # flush-inflight ledger token rides the snap; recycle()
                # retags it spare or drops it when the generation dies
                snap["_devobs"] = self._devobs_inflight
                self._devobs_inflight = None
        return snap

    def _idle_swap_locked(self, snap: dict) -> bool:
        """Family-specific idle fast path (caller holds ``lock``):
        return True to skip the generation swap entirely (the llhist
        table skips its capacity-proportional readout when untouched)."""
        return False

    def _swap_extras_locked(self, snap: dict) -> None:
        """Capture family-specific host-side interval state into the
        snap and reset it (caller holds ``lock`` + ``apply_lock``)."""

    def _swap_device_locked(self):
        """Capture the live device generation and install a fresh one
        (caller holds ``apply_lock``). The recycled spare is used when
        its capacity still matches — a resize in between falls back to
        a fresh allocation."""
        captured = self.state
        spare, self._spare = self._spare, None
        used_spare = (spare is not None
                      and self._spare_cap == self._state_capacity())
        if used_spare:
            self.state = spare
        else:
            self.state = self._fresh_state()
        self._devobs_swap_locked(used_spare)
        return captured

    def _devobs_state(self):
        """The live device generation pytree for HBM-ledger
        registration. Sharded per-device tables keep it in `states`;
        the host-only status table has neither and registers nothing."""
        state = getattr(self, "state", None)
        if state is None:
            state = getattr(self, "states", None)
        return state

    def _devobs_swap_locked(self, used_spare: bool) -> None:
        """HBM-ledger bookkeeping for a generation swap (caller holds
        ``apply_lock``; the new live state is already bound): the old
        live token goes flush-inflight, and the spare token — when its
        generation was the one installed — becomes the new live token
        (conserving its bytes); otherwise the fresh allocation registers
        anew and any stale spare token (capacity mismatch dropped its
        generation) is unregistered."""
        obs = self._deviceobs
        if obs is None:
            return
        tok, self._devobs_live = self._devobs_live, None
        if tok is not None:
            obs.retag(tok, "inflight")
            self._devobs_inflight = tok
        spare_tok, self._devobs_spare = self._devobs_spare, None
        if used_spare and spare_tok is not None:
            obs.retag(spare_tok, "live")
            self._devobs_live = spare_tok
        else:
            obs.drop(spare_tok)
            self._devobs_live = obs.note_generation(
                self.family, "live", self._devobs_state())

    def _state_capacity(self) -> int:
        """Key-axis capacity the device state is shaped for (the set
        table's dense bank rides its own slot ladder)."""
        return self.capacity

    def _reset_state_donated(self, captured):
        """Donate the drained generation into a kernel that rewrites its
        buffers to the family's INIT values. Zeros for most families;
        the t-digest table overrides (its min/max fields initialize to
        ±inf, which zeros would corrupt into fabricated 0.0 extrema)."""
        return _zeros_like_spare(captured)

    def _fresh_state(self):
        return self._fresh_state_at(self._state_capacity())

    def _fresh_state_at(self, capacity: int):
        raise NotImplementedError

    def readout(self, snap: dict) -> dict:
        """Background flush half: apply the snap's final pending columns
        to the captured generation and dispatch its readout kernels.
        Touches no live table state beyond monotonic telemetry counters,
        so it needs no locks and may run concurrently with ingest."""
        if "state" not in snap:
            return snap  # idle fast path: nothing was swapped
        state = snap.pop("state")
        cols = snap.pop("cols")
        if cols is not None:
            state = self._readout_apply(state, cols, snap)
        self._readout_device(state, snap)
        return snap

    def _readout_apply(self, state, cols, snap: dict):
        return self._apply_cols_state(state, cols)

    def _readout_device(self, state, snap: dict) -> None:
        raise NotImplementedError

    def _finish_and_recycle(self, snap: dict):
        """snapshot_finish + recycle in the order the donation protocol
        requires (transfer first, then donate the drained generation) —
        the one place the invariant lives for every snapshot_and_reset."""
        out = self.snapshot_finish(snap)
        self.recycle(snap)
        return out

    def recycle(self, snap: dict) -> None:
        """Donate the drained snapshot's device generation back as the
        next spare (call only after snapshot_finish — the zeroing kernel
        consumes the buffers the transfer just read). Sharded merges
        produce an already-zeroed generation (`_spare`) from their fused
        merge+reset kernel; everything else zero-donates the captured
        state (`_recycle`)."""
        cap = snap.pop("cap", -1)
        spare = snap.pop("_spare", None)
        captured = snap.pop("_recycle", None)
        tok = snap.pop("_devobs", None)
        obs = self._deviceobs
        if spare is None and captured is not None:
            t0 = time.perf_counter()
            try:
                spare = self._reset_state_donated(captured)
            except Exception:
                logger.exception("%s generation recycle failed",
                                 self.family)
                if obs is not None:
                    obs.drop(tok)
                return
            if obs is not None:
                obs.note_kernel("reset", self.family,
                                time.perf_counter() - t0)
        if spare is None:
            # generation not recyclable (sparse set readout consumed
            # it): its ledger token dies with it
            if obs is not None:
                obs.drop(tok)
            return
        with self.apply_lock:
            if cap == self._state_capacity() and self._spare is None:
                self._spare = spare
                self._spare_cap = cap
                if obs is not None:
                    obs.retag(tok, "spare")
                    self._devobs_spare = tok
                    tok = None
        # resized-under-flush or spare slot already occupied: the
        # zeroed generation is discarded, unregister its bytes
        if obs is not None and tok is not None:
            obs.drop(tok)

    # -- live-query capture: read-only snapshot between flushes ----------
    #
    # The query plane (core/query.py) reads the LIVE generation without
    # swapping it: no reset, no generation advance, no recycle. Safety
    # rests on two invariants the flush path already establishes:
    #
    #   * jax arrays are immutable — capturing `self.state` by reference
    #     under apply_lock yields a consistent point-in-time view even
    #     while ingest keeps rebinding the live attribute to new arrays;
    #   * every DONATING kernel on the readout path is either avoided
    #     (sharded tables override _query_readout_device with the
    #     non-reset collective merges) or fed a private copy (the sparse
    #     set table's hot-COO fold).
    #
    # Pending columns fold into the live state first through the normal
    # dispatch path (donation-safe: the donated input is the OLD live
    # buffer, replaced by the kernel's output), so absent further ingest
    # the captured generation is exactly what the next swap_out would
    # capture — the bit-identity the consistency pin asserts. Under
    # sustained ingest the fold retries a bounded number of rounds;
    # anything still pending after that is the query's (bounded)
    # staleness, one batch_cap of samples at most per round lost.

    _CAPTURE_FOLD_ROUNDS = 8

    def capture_readonly(self, **kw) -> dict:
        """Read-only counterpart of swap_out: capture the live device
        generation plus touched/meta/extras WITHOUT swapping or
        resetting anything, and dispatch the readout kernels over it.
        Extra kwargs ride into the snap exactly as for swap_out (ps,
        need_export, need_bins).

        The readout DISPATCH happens here, under apply_lock, and that
        placement is load-bearing: the next pending apply DONATES the
        live buffers, deleting the captured references — a dispatch
        after the lock releases would race that deletion. Dispatch is
        asynchronous (no device sync under the lock); its result
        buffers are fresh, so later donation cannot touch them. The
        sync itself happens in query_readout(), off the table locks."""
        snap = dict(kw)
        with self.lock:
            if self._idle_capture_locked(snap):
                return snap
            for _ in range(self._CAPTURE_FOLD_ROUNDS):
                if self._n == 0:
                    break
                self._dispatch_pending_locked()  # may release/reacquire
            # residual pending samples after the bounded fold ARE the
            # query's staleness — surfaced to the caller, never lost
            # (they fold into the live state on the next dispatch)
            snap["stale_pending"] = self._n
            with self.apply_lock:
                snap["touched"] = self.touched.copy()
                snap["meta"] = list(self.meta)
                self._capture_extras_locked(snap)
                self._query_readout_device(
                    self._capture_device_locked(), snap)
                # the snap must NEVER reach recycle(): the state it read
                # IS the live generation
                for key in ("_recycle", "_spare", "cap"):
                    snap.pop(key, None)
        return snap

    def _idle_capture_locked(self, snap: dict) -> bool:
        """Family-specific idle fast path for queries (caller holds
        ``lock``): mirrors _idle_swap_locked but advances nothing."""
        return False

    def _capture_extras_locked(self, snap: dict) -> None:
        """Read-only counterpart of _swap_extras_locked: COPY family
        host-side interval state into the snap without resetting it
        (caller holds ``lock`` + ``apply_lock``)."""

    def _capture_device_locked(self):
        """Reference to the live device generation (caller holds
        ``apply_lock``). A reference, not a copy: the arrays are
        immutable, and later applies rebind the live attribute without
        touching the captured value."""
        return self.state

    def query_readout(self, snap: dict) -> dict:
        """The device-sync half of a query: wait for the result buffers
        capture_readonly dispatched. Runs lock-free on the server's
        supervised flush executor, so query syncs serialize with the
        in-flight flush readout instead of colliding with it."""
        import jax
        jax.block_until_ready(
            {k: v for k, v in snap.items() if k != "meta"})
        return snap

    def _query_readout_device(self, state, snap: dict) -> None:
        """Family hook for the query readout. The default is safe only
        when the flush readout stores nothing but fresh kernel outputs
        into the snap (histogram/llhist). Families whose flush readout
        captures the state by reference (counter/gauge transfer rows),
        donates it (the sharded fused merge+reset kernels), or writes
        into it (the sparse set fold) override this — a query reads the
        LIVE generation, which stays exposed to later donating applies."""
        self._readout_device(state, snap)

    # -- shape-ladder prewarm --------------------------------------------

    def prewarm_rung(self, capacity: int, percentiles=(),
                     need_export: bool = True) -> bool:
        """Compile this family's batch-apply, readout, and zeroing
        kernels for a FUTURE capacity rung against a throwaway state
        (background thread; never touches live state or locks). The jit
        caches — and the persistent compilation cache — are
        process-global, so the first post-resize dispatch at this
        capacity finds them warm instead of retracing on the hot path.
        Returns True when the rung was compiled."""
        cols = self._prewarm_cols()
        if cols is None:
            return False
        obs = self._deviceobs
        t0 = time.perf_counter()
        state = self._fresh_state_at(capacity)
        # the throwaway rung state is real HBM while the compile runs;
        # ledger it as a transient `prewarm` generation
        tok = obs.note_generation(self.family, "prewarm", state) \
            if obs is not None else None
        try:
            state = self._prewarm_apply(state, cols, capacity)
            out = self._prewarm_readout(state, capacity,
                                        tuple(percentiles), need_export)
            jax.block_until_ready([leaf for leaf in jax.tree.leaves(out)
                                   if leaf is not None])
        finally:
            if obs is not None:
                obs.drop(tok)
        self._prewarmed_caps.add(capacity)
        if obs is not None:
            elapsed = time.perf_counter() - t0
            obs.note_kernel("prewarm", self.family, elapsed)
            obs.note_compile(self.family, elapsed)
        return True

    def _prewarm_cols(self):
        """An all-padding pending batch with the live buffer dtypes
        (None = family has no batch apply to prewarm)."""
        pcols = getattr(self, "_pcols", None)
        if not pcols:
            return None
        return (np.full(self.batch_cap, PAD_ROW, np.int32),) + tuple(
            np.zeros(self.batch_cap, c.dtype) for c in pcols[1:])

    def _prewarm_apply(self, state, cols, capacity: int):
        return self._apply_cols_state(state, cols)

    def _prewarm_readout(self, state, capacity: int, ps: tuple,
                         need_export: bool):
        """Dispatch the family's flush-readout + zeroing kernels for the
        rung; returns device handles to block on. Base: the zeroing
        kernel only (scalar families read out by pure transfer)."""
        return _zeros_like_spare(state)

    def _apply_cols_state(self, state, cols):
        """Pure batch apply: fold one swapped pending-column batch into
        `state` and return it. The live path (`_apply_cols`) targets
        self.state; the flush readout targets the captured generation."""
        raise NotImplementedError

    def _apply_cols(self, cols):
        self.state = self._apply_cols_state(self.state, cols)

    def row_for(self, metric: UDPMetric) -> int:
        # scope is part of row identity: the reference keeps separate maps
        # per scope variant (worker.go:59-102), so one MetricKey may hold
        # state in two scopes at once
        dict_key = (metric.digest64 << 2) | int(metric.scope)
        row = self.rows.get(dict_key)
        if row is None:
            # cardinality watermark rung: a NEW key consults the
            # accountant's per-name mint budget before any allocation.
            # Existing rows never come through here, so a storm can only
            # starve its own new keys — pre-existing series keep
            # updating. The accountant counts every rejection
            # (ingest.shed_total reason:cardinality*).
            card = self.cardinality
            if card is not None and not card.admit_mint(
                    self.family, metric.key.name, metric.tags):
                if self.ledger is not None:
                    self.ledger.note("agg.rejected", 1, key=self.family)
                return -1
            meta = RowMeta(
                name=metric.key.name, tags=list(metric.tags),
                joined_tags=metric.key.joined_tags, digest32=metric.digest,
                scope=metric.scope, wire_type=metric.key.type)
            if self._free_rows:
                row = self._free_rows.pop()
                self.meta[row] = meta
                self._dict_key_of[row] = dict_key
                self._last_touched[row] = self._generation
                self._has_meta[row] = True
                # recycled row: drop the previous occupant's cached
                # flush names/tags before the new key's first flush
                self._tags_cache[row] = None
                for arr in self._flush_name_cache.values():
                    arr[row] = None
            elif self.max_rows and len(self.rows) >= self.max_rows:
                # hard cardinality cap: protects host memory during a
                # within-interval key flood; the sample is dropped and
                # counted (keys_dropped self-metric)
                self.keys_dropped += 1
                if self.ledger is not None:
                    self.ledger.note("agg.rejected", 1, key=self.family)
                return -1
            else:
                row = len(self.meta)
                if row >= self.capacity:
                    self._grow()
                self.meta.append(meta)
                self._dict_key_of.append(dict_key)
                self._has_meta[row] = True
                # stamp creation as activity: without this a row interned
                # (but not yet touched) late in life would read as idle
                # since generation 0 and tombstone on its first flush
                self._last_touched[row] = self._generation
            self.scope_code[row] = int(metric.scope)
            self._note_minted(row, metric)
            self.rows[dict_key] = row
            self.minted_total += 1
            if card is not None:
                card.note_mint(self.family, metric.key.name)
        return row

    def _note_minted(self, row: int, metric: UDPMetric) -> None:
        """Mint hook, fired once per fresh/recycled row assignment under
        the buffer lock. The sharded tables (core/sharded_tables.py)
        record the row's digest-derived home shard here; the base table
        does nothing."""

    def _note_applied(self, n: int) -> None:
        """Stamp n samples accepted into this family (flow ledger)."""
        led = self.ledger
        if led is not None and n:
            led.note("agg.applied", n, key=self.family)

    def _note_generation_locked(self) -> None:
        """Advance the flush generation and stamp rows touched this
        interval (caller holds ``lock``, before clearing ``touched``)."""
        self._generation += 1
        self._last_touched[self.touched] = self._generation

    def reclaim_idle(self, idle_intervals: int):
        """Two-phase idle-row reclamation, run after each flush.

        Phase 1 (tombstone): rows idle for >= idle_intervals flushes
        lose their rows-dict entry now; the caller must also erase their
        native intern mappings (the returned rows) so no NEW native
        samples can reference them.

        Phase 2 (recycle): rows tombstoned at least one flush ago and
        untouched since go to the free list. A tombstoned row that was
        touched in the gap (an in-flight chunk straggler, emitted
        normally) has its tombstone re-stamped and waits another flush.

        Returns the list of rows tombstoned in this call."""
        if idle_intervals <= 0:
            return []
        evicted_names: List[str] = []
        with self.lock:
            gen = self._generation
            n = len(self.meta)
            if n == 0:
                return []
            last = self._last_touched[:n]
            tomb = self._tombstone_gen[:n]
            # phase 2. A currently-set touched flag counts as activity
            # even though _last_touched is only stamped at snapshot time:
            # a straggler chunk landing between snapshot_and_reset and
            # this call has touched[row]=True and its value in the NEW
            # pending buffer — recycling now would orphan that value (or
            # credit it to whatever key re-interns the row).
            rearm = (tomb >= 0) & ((last > tomb) | self.touched[:n])
            if rearm.any():
                tomb[rearm] = gen
            recycle = (tomb >= 0) & (gen > tomb) & (last <= tomb)
            for row in np.nonzero(recycle)[0]:
                row = int(row)
                tomb[row] = -1
                self.meta[row] = None
                self._has_meta[row] = False
                self._free_rows.append(row)
                self.recycled_total += 1
            # phase 1
            cand = ((tomb < 0) & (gen - last >= idle_intervals)
                    & self._has_meta[:n])
            evicted = [int(r) for r in np.nonzero(cand)[0]]
            for row in evicted:
                self.rows.pop(self._dict_key_of[row], None)
                tomb[row] = gen
                meta = self.meta[row]
                if meta is not None:
                    evicted_names.append(meta.name)
            self.tombstoned_total += len(evicted)
        # live-row accounting outside the buffer lock: the eviction list
        # can be large under churn, and the accountant only needs names
        if evicted_names and self.cardinality is not None:
            self.cardinality.note_evicted(self.family, evicted_names)
        return evicted

    def flush_names(self, key, rows: np.ndarray, meta_list,
                    render) -> np.ndarray:
        """Rendered flush-name object array for `rows` (row ids), cached
        for the row's lifetime under `key` (a suffix string or percentile).
        Misses render via `render(meta)` against the caller's SNAPSHOT
        meta list, so a concurrent re-intern can never leak another key's
        name into this flush.

        Cache-dict mutation (new key, grow-replacement) happens under the
        buffer lock: row_for iterates .values() to invalidate recycled
        rows and _grow re-lays-out every entry, both under that lock.
        Element fills stay lock-free — a fill can only target a row that
        is live in this snapshot, which the two-phase reclaim contract
        keeps un-recyclable until the next flush, so the worst concurrent
        outcome is a fill landing in an orphaned (pre-grow) array: a lost
        cache entry, re-rendered next flush."""
        with self.lock:
            arr = self._flush_name_cache.get(key)
            if arr is None:
                arr = self._flush_name_cache[key] = np.empty(
                    max(self.capacity, len(self.meta)), object)
            elif arr.shape[0] < len(self.meta):
                grown = np.empty(self.capacity, object)
                grown[: arr.shape[0]] = arr
                arr = self._flush_name_cache[key] = grown
        sel = arr[rows]
        miss = np.flatnonzero(np.equal(sel, None))
        for j in miss.tolist():
            row = int(rows[j])
            sel[j] = arr[row] = render(meta_list[row])
        return sel

    def flush_tags(self, rows: np.ndarray, meta_list) -> np.ndarray:
        """Per-row tag-list refs for `rows`, cached like flush_names.
        Consumers must copy before mutating (InterMetric materialization
        does)."""
        with self.lock:  # a concurrent _grow replaces the array
            arr = self._tags_cache
        sel = arr[rows]
        miss = np.flatnonzero(np.equal(sel, None))
        for j in miss.tolist():
            row = int(rows[j])
            sel[j] = arr[row] = meta_list[row].tags
        return sel

    def _grow(self):
        t0 = time.perf_counter()
        new_cap = self.capacity * 2
        pad = new_cap - self.capacity
        self.touched = np.concatenate(
            [self.touched, np.zeros(pad, bool)])
        self._last_touched = np.concatenate(
            [self._last_touched, np.zeros(pad, np.int64)])
        self._tombstone_gen = np.concatenate(
            [self._tombstone_gen, np.full(pad, -1, np.int64)])
        self._has_meta = np.concatenate(
            [self._has_meta, np.zeros(pad, bool)])
        self.scope_code = np.concatenate(
            [self.scope_code, np.full(pad, -1, np.int8)])
        self._tags_cache = np.concatenate(
            [self._tags_cache, np.empty(pad, object)])
        for key, arr in self._flush_name_cache.items():
            self._flush_name_cache[key] = np.concatenate(
                [arr, np.empty(pad, object)])
        # _grow_arrays re-lays-out the device state, so it needs the state
        # lock; caller already holds the buffer lock (correct lock order)
        with self.apply_lock:
            # the recycled spare generation is shaped for the OLD
            # capacity; drop it rather than let a stale swap install it
            self._spare = None
            self._spare_cap = -1
            obs = self._deviceobs
            if obs is not None:
                obs.drop(self._devobs_spare)
                self._devobs_spare = None
            self._grow_arrays(new_cap)
            # the live generation was re-laid-out at the new capacity:
            # re-register its (doubled) footprint
            if obs is not None:
                obs.drop(self._devobs_live)
                self._devobs_live = obs.note_generation(
                    self.family, "live", self._devobs_state())
                obs.note_resize()
        old_cap, self.capacity = self.capacity, new_cap
        # capacity doublings are permanent HBM growth AND a pending jit
        # recompile (every kernel specializes on capacity; the retrace
        # lands on the next batch apply, timed in _dispatch_pending_locked)
        elapsed = time.perf_counter() - t0
        self.resize_total += 1
        self.resize_last_seconds = elapsed
        self.resize_seconds_total += elapsed
        self._recompile_pending = True
        logger.info("%s table capacity %d -> %d (%.3fs relayout)",
                    self.family, old_cap, new_cap, elapsed)
        hook = self.on_resize
        if hook is not None:
            try:
                hook(self.family, old_cap, new_cap, elapsed, kind="resize")
            except Exception:
                logger.exception("resize hook failed")

    def _append_batch(self, columns, touch_rows=None) -> None:
        """Vectorized append of parallel sample columns into the typed
        pending buffers (the native-parser fast path), dispatching whenever
        full. Caller holds self.lock; rows must already be interned.

        Touched flags are set PER CHUNK, in the same lock hold that puts
        the chunk into the pending buffer. Marking all rows up front
        would race the dispatch below: it releases the lock while
        applying a full buffer, and a concurrent snapshot then clears
        the flags of samples not yet buffered — their values later land
        in the next interval's state untouched and are reset without
        ever being emitted (observed as lost samples under the
        concurrency stress suite). touch_rows defaults to the row
        column; tables whose buffers carry device slots (the set table)
        pass the table rows explicitly."""
        if touch_rows is None:
            touch_rows = columns[0]
        n = len(columns[0])
        i = 0
        while i < n:
            take = min(self.batch_cap - self._n, n - i)
            for buf, data in zip(self._pcols, columns):
                buf[self._n:self._n + take] = data[i:i + take]
            self.touched[touch_rows[i:i + take]] = True
            self._n += take
            i += take
            if self._n >= self.batch_cap:
                self._dispatch_pending_locked()

    @property
    def num_rows(self) -> int:
        return len(self.meta)


def _pad_cap(state_leaf, new_cap):
    pad = new_cap - state_leaf.shape[0]
    widths = [(0, pad)] + [(0, 0)] * (state_leaf.ndim - 1)
    return jnp.pad(state_leaf, widths)


class CounterTable(_BaseTable):
    def _init_arrays(self):
        self.state = scalars.init_counters(self.capacity)
        self._prow = np.full(self.batch_cap, PAD_ROW, np.int32)
        self._pval = np.zeros(self.batch_cap, np.float32)
        self._prate = np.ones(self.batch_cap, np.float32)
        self._pcols = (self._prow, self._pval, self._prate)
        self._n = 0
        self._import_acc = np.zeros(self.capacity, np.float64)

    def _grow_arrays(self, new_cap):
        self.state = jax.tree.map(lambda a: _pad_cap(a, new_cap), self.state)

    def add(self, metric: UDPMetric):
        with self.lock:
            row = self.row_for(metric)
            if row < 0:
                return
            self.touched[row] = True
            self._note_applied(1)
            n = self._n
            self._prow[n] = row
            self._pval[n] = metric.value
            self._prate[n] = max(metric.sample_rate, 1e-9)
            self._n = n + 1
            if self._n >= self.batch_cap:
                self._dispatch_pending_locked()

    def _apply_cols_state(self, state, cols):
        # cols are copies: execution is async and jax may alias numpy
        # buffers zero-copy, while the live buffers are refilled immediately
        rows, vals, rates = cols
        return scalars.apply_counters(state, rows, vals, rates)

    def _fresh_state_at(self, capacity: int):
        return scalars.init_counters(capacity)

    def apply_pending(self):
        with self.lock:
            self._dispatch_pending_locked()

    def add_batch(self, rows, vals, rates) -> None:
        """Native-parser fast path: pre-interned rows, parallel columns."""
        with self.lock:
            self._note_applied(len(rows))
            self._append_batch((rows, vals, rates))

    def merge_batch(self, stubs: List[UDPMetric], values) -> None:
        """Import-path merge: intern + touch + accumulate atomically, so a
        concurrent flush never sees touched-but-valueless rows. Values
        accumulate host-side in f64 because forwarded counters are exact
        int64 sums that f32 would quantize."""
        with self.lock:
            rows = []
            vals = []
            for stub, value in zip(stubs, values):
                row = self.row_for(stub)
                if row < 0:  # cardinality cap
                    continue
                self.touched[row] = True
                rows.append(row)
                vals.append(value)
            self._note_applied(len(rows))
            if self._import_acc.shape[0] < self.capacity:
                grown = np.zeros(self.capacity, np.float64)
                grown[: self._import_acc.shape[0]] = self._import_acc
                self._import_acc = grown
            np.add.at(self._import_acc, rows, np.asarray(vals, np.float64))

    def _swap_extras_locked(self, snap: dict) -> None:
        snap["import_acc"] = self._import_acc
        self._import_acc = np.zeros(self.capacity, np.float64)

    def _capture_extras_locked(self, snap: dict) -> None:
        # copy, not reference: merge_batch mutates the accumulator in
        # place (np.add.at), so a live reference could tear mid-read
        snap["import_acc"] = self._import_acc.copy()

    def _readout_device(self, state, snap: dict) -> None:
        """Counter readout is a pure transfer of the Kahan pair; the
        sharded table overrides this with the collective merge. The
        captured generation is recycled after the transfer."""
        snap["dev"] = (state["sum"], state["comp"])
        snap["_recycle"] = state

    def _query_readout_device(self, state, snap: dict) -> None:
        # the flush readout stores the Kahan pair BY REFERENCE — safe
        # there because the swapped-out generation is exclusive. A query
        # reads the LIVE pair, which the next pending apply DONATES, so
        # snapshot fresh buffers with an async copy kernel instead.
        snap["dev"] = (jnp.copy(state["sum"]), jnp.copy(state["comp"]))

    def snapshot_begin(self) -> dict:
        """Dispatch half of snapshot_and_reset: swap + readout, but do
        NOT transfer. The flusher begins every table first, then pays
        the device sync once for all of them (over a remote device link
        the per-table sync was a serialized round-trip each)."""
        return self.readout(self.swap_out())

    @staticmethod
    def snapshot_finish(snap: dict
                        ) -> Tuple[np.ndarray, np.ndarray, List[RowMeta]]:
        # f64 readout recovers the exact total from the Kahan pair
        values = (np.asarray(snap["dev"][0], np.float64)
                  - np.asarray(snap["dev"][1], np.float64))
        import_acc = snap["import_acc"]
        values[: import_acc.shape[0]] += import_acc
        return values, snap["touched"], snap["meta"]

    def snapshot_and_reset(self) -> Tuple[np.ndarray, np.ndarray, List[RowMeta]]:
        return self._finish_and_recycle(self.snapshot_begin())


class GaugeTable(_BaseTable):
    def _init_arrays(self):
        self.state = scalars.init_gauges(self.capacity)
        self._prow = np.full(self.batch_cap, PAD_ROW, np.int32)
        self._pval = np.zeros(self.batch_cap, np.float32)
        self._pcols = (self._prow, self._pval)
        self._n = 0

    def _grow_arrays(self, new_cap):
        self.state = jax.tree.map(lambda a: _pad_cap(a, new_cap), self.state)

    def add(self, metric: UDPMetric):
        with self.lock:
            row = self.row_for(metric)
            if row < 0:
                return
            self.touched[row] = True
            self._note_applied(1)
            n = self._n
            self._prow[n] = row
            self._pval[n] = metric.value
            self._n = n + 1
            if self._n >= self.batch_cap:
                self._dispatch_pending_locked()

    def _apply_cols_state(self, state, cols):
        rows, vals = cols
        return scalars.apply_gauges(state, rows, vals)

    def _fresh_state_at(self, capacity: int):
        return scalars.init_gauges(capacity)

    def apply_pending(self):
        with self.lock:
            self._dispatch_pending_locked()

    def add_batch(self, rows, vals) -> None:
        """Native-parser fast path; buffer order preserves last-write-wins."""
        with self.lock:
            self._note_applied(len(rows))
            self._append_batch((rows, vals))

    def merge_batch(self, stubs: List[UDPMetric], values) -> None:
        """Import-path merge: overwrite. Interning is atomic under the
        buffer lock; the state update rides the apply ticket so it orders
        after any already-swapped local batches."""
        with self.lock:
            rows = np.fromiter(
                (self.row_for(s) for s in stubs), np.int32, len(stubs))
            ok = rows >= 0  # cardinality-capped stubs drop out
            rows = rows[ok]
            self.touched[rows] = True
            self._note_applied(int(rows.size))
            self.apply_lock.acquire()
        try:
            self.state = scalars.merge_gauges(
                self.state, rows, np.asarray(values, np.float32)[ok])
        finally:
            self.apply_lock.release()

    def _readout_device(self, state, snap: dict) -> None:
        """Gauge readout is a pure transfer of the LWW values; the
        sharded table overrides this with the collective merge."""
        snap["dev"] = state["value"]
        snap["_recycle"] = state

    def _query_readout_device(self, state, snap: dict) -> None:
        # see CounterTable: the live LWW column gets donated by the
        # next pending apply — a query must capture a fresh copy
        snap["dev"] = jnp.copy(state["value"])

    def snapshot_begin(self) -> dict:
        """Dispatch-only snapshot half; see CounterTable.snapshot_begin."""
        return self.readout(self.swap_out())

    @staticmethod
    def snapshot_finish(snap: dict):
        return np.asarray(snap["dev"]), snap["touched"], snap["meta"]

    def snapshot_and_reset(self):
        return self._finish_and_recycle(self.snapshot_begin())


class HistoTable(_BaseTable):
    """Histograms and timers, all scopes, one digest grid.

    Batches rank-park raw samples into the digest staging grid (O(batch)
    per apply, exact); the host tracks a conservative per-key staged
    bound (sum of per-batch max row counts) and runs the mean-sorted
    `compact` — the only capacity-proportional pass — before any key
    could overflow its C staging slots, and always at snapshot. This
    mirrors the reference's amortized temp-buffer merge
    (merging_digest.go:115-140): sparse keys stage dozens of batches
    per compact, dense keys compact about once per batch."""

    def _init_pending(self):
        self._prow = np.full(self.batch_cap, PAD_ROW, np.int32)
        self._pval = np.zeros(self.batch_cap, np.float32)
        self._pwt = np.zeros(self.batch_cap, np.float32)
        self._pcols = (self._prow, self._pval, self._pwt)
        self._n = 0
        self._applies = 0
        # exact per-key staging-slot occupancy since the last compact
        self._staged_counts = np.zeros(self.capacity, np.int32)


    # when True (tpu.pallas_tdigest_flush) the flush's post-sort
    # interpolation runs through the fused Pallas kernel; any failure
    # latches the jnp path for the process (pallas_hll's safety model)
    pallas_flush = False

    def _init_arrays(self):
        self._init_pending()
        self.state = batch_tdigest.init_state(self.capacity)

    def _use_pallas(self) -> bool:
        if not self.pallas_flush:
            return False
        from veneur_tpu.ops import pallas_tdigest
        # off-TPU only interpret mode exists (parity tests); production
        # flushes take the jnp path there
        platform = jax.devices()[0].platform
        return (platform in ("tpu", "axon")
                and pallas_tdigest.available(self.capacity))

    def _flush_packed(self, ps, state=None, fold_staging=True):
        st = self.state if state is None else state
        if self._use_pallas():
            try:
                # realize inside the try: a device-side kernel fault
                # surfaces at blocking, and it must latch the fallback
                # rather than crash every subsequent flush
                return jax.block_until_ready(
                    batch_tdigest.flush_quantiles_packed_pallas(
                        st, ps, fold_staging))
            except Exception:
                self._latch_pallas_off()
        return batch_tdigest.flush_quantiles_packed(
            st, ps, fold_staging=fold_staging)

    def _flush_export(self, ps, state=None):
        st = self.state if state is None else state
        if self._use_pallas():
            try:
                return jax.block_until_ready(
                    batch_tdigest.flush_export_packed_pallas(st, ps))
            except Exception:
                self._latch_pallas_off()
        return batch_tdigest.flush_export_packed(st, ps)

    def _latch_pallas_off(self):
        from veneur_tpu.ops import pallas_tdigest
        pallas_tdigest._State.failed = True
        logger.exception(
            "pallas t-digest flush failed; jnp path latched")

    def _grow_arrays(self, new_cap):
        old = self.state
        new = batch_tdigest.init_state(new_cap)
        grown = {}
        for k in new:
            grown[k] = jax.lax.dynamic_update_slice(
                new[k], old[k], (0,) * new[k].ndim)
        self.state = grown
        extended = np.zeros(new_cap, np.int32)
        extended[: self._staged_counts.shape[0]] = self._staged_counts
        self._staged_counts = extended

    def add(self, metric: UDPMetric):
        with self.lock:
            row = self.row_for(metric)
            if row < 0:
                return
            self.touched[row] = True
            self._note_applied(1)
            n = self._n
            self._prow[n] = row
            self._pval[n] = metric.value
            self._pwt[n] = 1.0 / max(metric.sample_rate, 1e-9)
            self._n = n + 1
            if self._n >= self.batch_cap:
                self._dispatch_pending_locked()

    def _apply_cols(self, cols):
        self.state = self._apply_cols_state(self.state, cols,
                                            self._staged_counts)
        self._applies += 1

    def _apply_cols_state(self, state, cols, staged_counts):
        """Pure batch apply over an explicit (state, staging-occupancy)
        pair: the live path passes the table's own, the flush readout
        passes the captured generation's."""
        rows, vals, wts = cols
        slots, overflow = batch_tdigest.host_slots(
            rows, vals, wts, staged_counts)
        if overflow:
            state = batch_tdigest.compact(state)
            staged_counts[:] = 0
            slots, _ = batch_tdigest.host_slots(
                rows, vals, wts, staged_counts)
        return batch_tdigest.apply_batch(state, rows, vals, wts, slots)

    def _fresh_state_at(self, capacity: int):
        return batch_tdigest.init_state(capacity)

    def _prewarm_apply(self, state, cols, capacity: int):
        return self._apply_cols_state(state, cols,
                                      np.zeros(capacity, np.int32))

    def apply_pending(self):
        with self.lock:
            self._dispatch_pending_locked()

    def add_batch(self, rows, vals, weights) -> None:
        """Native-parser fast path: weights are 1/sample_rate."""
        with self.lock:
            self._note_applied(len(rows))
            self._append_batch((rows, vals, weights))

    def merge_batch(self, stubs: List[UDPMetric], in_means, in_weights,
                    in_min, in_max, in_recip) -> None:
        """Import-path digest merge; interning atomic under the buffer
        lock, state update ordered via the apply ticket."""
        with self.lock:
            rows = np.fromiter(
                (self.row_for(s) for s in stubs), np.int32, len(stubs))
            ok = rows >= 0  # cardinality-capped stubs drop out
            rows = rows[ok]
            self.touched[rows] = True
            self._note_applied(int(rows.size))
            self.apply_lock.acquire()
        try:
            self.state = batch_tdigest.merge_centroid_rows(
                self.state, rows,
                np.asarray(in_means, np.float32)[ok],
                np.asarray(in_weights, np.float32)[ok],
                np.asarray(in_min, np.float32)[ok],
                np.asarray(in_max, np.float32)[ok],
                np.asarray(in_recip, np.float32)[ok])
            # the merge folds staging for every row with staged weight
            # (merge_centroid_rows touches staged rows too), so the whole
            # occupancy map resets
            self._staged_counts[:] = 0
        finally:
            self.apply_lock.release()

    def snapshot_and_reset(self, percentiles: Tuple[float, ...],
                           need_export: bool = True):
        """Returns (flush outputs dict of np arrays, centroid export,
        touched, meta).

        need_export=False (a global server: nothing downstream consumes
        the serialized digests) skips the centroid export entirely — the
        (K, C) weight/mean tables never cross the device link and the
        pre-export compact is elided (flush_quantiles folds staging
        itself); the flush then transfers a single packed (K, P+10)
        array instead of ~50 MB of centroids at K=100k."""
        return self._finish_and_recycle(
            self.snapshot_begin(percentiles, need_export))

    def _swap_extras_locked(self, snap: dict) -> None:
        snap["staged"] = self._staged_counts
        self._staged_counts = np.zeros(self.capacity, np.int32)
        self._applies = 0

    def _readout_apply(self, state, cols, snap: dict):
        return self._apply_cols_state(state, cols, snap.pop("staged"))

    def _readout_device(self, state, snap: dict) -> None:
        ps = snap["ps"]
        if snap.pop("need_export"):
            # fused forwarding flush: one dispatch, one sort, and
            # two device->host transfers (the packed flush and the
            # packed export) instead of compact+flush+export
            packed, export_packed = self._flush_export(ps, state)
        else:
            packed = self._flush_packed(ps, state)
            export_packed = None
        snap["packed"] = packed
        snap["export_packed"] = export_packed
        snap["_recycle"] = state

    def _reset_state_donated(self, captured):
        return _reset_tdigest_spare(captured)

    def _prewarm_readout(self, state, capacity: int, ps: tuple,
                         need_export: bool):
        if need_export:
            out = self._flush_export(ps, state)
        else:
            out = self._flush_packed(ps, state)
        return (out, self._reset_state_donated(state))

    def snapshot_begin(self, percentiles: Tuple[float, ...],
                       need_export: bool = True) -> dict:
        """Dispatch-only snapshot half; see CounterTable.snapshot_begin."""
        return self.readout(self.swap_out(
            ps=tuple(percentiles), need_export=need_export))

    @staticmethod
    def snapshot_finish(snap: dict):
        out = batch_tdigest.unpack_flush(snap["packed"], len(snap["ps"]))
        export = (batch_tdigest.unpack_export(snap["export_packed"])
                  if snap["export_packed"] is not None else None)
        return out, export, snap["touched"], snap["meta"]


class _SetRegisters:
    """Lazy per-row dense register view over the hybrid set state:
    promoted rows slice the (D, M) device readout; sparse rows
    materialize 16 KB only when a caller (the forward exporter) actually
    asks — the point of the sparse representation is that most rows
    never do both."""

    def __init__(self, dev_regs, slot_of, sparse_rows, sparse_idx,
                 sparse_rho):
        # (nslots, M) int8 — a DEVICE array, or None. Transferred to
        # host lazily on the first promoted-row access: a global server
        # never reads registers, and eagerly pulling the dense bank was
        # up to 16 KB x nslots per flush across the device link for
        # nothing.
        self._dev = dev_regs
        self._dev_np = None
        self._slot_of = slot_of
        # sparse COO sorted by row; boundaries found by searchsorted
        self._rows = sparse_rows
        self._idx = sparse_idx
        self._rho = sparse_rho

    @classmethod
    def dense(cls, state, capacity: int) -> "_SetRegisters":
        """All-dense provider: every row maps 1:1 to a device slot (the
        sparse tier is empty). Used by the non-sparse and sharded set
        tables."""
        empty = np.zeros(0, np.int32)
        return cls(state, np.arange(capacity, dtype=np.int32),
                   empty, empty, empty)

    def __getitem__(self, row: int) -> np.ndarray:
        slot = int(self._slot_of[row]) if row < self._slot_of.shape[0] else -1
        if slot >= 0 and self._dev is not None:
            if self._dev_np is None:
                self._dev_np = np.asarray(self._dev)
            return self._dev_np[slot]
        regs = np.zeros(batch_hll.M, np.int8)
        lo = np.searchsorted(self._rows, row, side="left")
        hi = np.searchsorted(self._rows, row, side="right")
        if hi > lo:
            np.maximum.at(regs, self._idx[lo:hi],
                          self._rho[lo:hi].astype(np.int8))
        return regs


class SetTable(_BaseTable):
    """Sets with a two-tier HLL representation (the reference's vendored
    hyperloglog likewise keeps small sets sparse, sparse.go): samples
    for a key accumulate as host-side COO (register, rho) pairs until
    the key crosses PROMOTE_SAMPLES within the interval, at which point
    it is promoted to a row of the dense (D, 16384) device table and its
    stream flows through the scatter-max kernel. At flush, promoted
    rows' early backlog folds into the device table, small rows estimate
    on host with the same LogLog-Beta math (vectorized over the sorted
    COO), and registers materialize per row only on demand. A 100k-key
    set workload with mostly small sets therefore costs megabytes of
    host COO instead of 1.6 GB of device registers.

    `sparse=False` (the sharded table) keeps the original all-dense
    device path: every row maps 1:1 to a device slot."""

    MAX_DEV_SLOTS = 65536  # HBM guard: 16 KB/slot -> 1 GB at the cap

    def __init__(self, capacity: int = 256, batch_cap: int = 8192,
                 sparse: bool = True, max_rows: int = 0,
                 promote_samples: int = 0, max_dev_slots: int = 0):
        self._sparse = sparse
        # 0 = auto, resolved lazily at the first promotion decision (the
        # backend probe must not run in the constructor: scratch stores
        # and tools build tables before — or without — a healthy device)
        self._promote_samples = promote_samples
        if max_dev_slots > 0:
            self.MAX_DEV_SLOTS = max_dev_slots
        super().__init__(capacity, batch_cap, max_rows=max_rows)

    @property
    def PROMOTE_SAMPLES(self) -> int:
        """Tier-crossover threshold. Auto policy: on a real accelerator
        the dense scatter tier is the fast path, so promote early and
        let the host tier carry only the cold tail (the per-flush sparse
        sort is the sustained-gate cost). On the CPU backend the
        "device" is this same host core — promoting buys nothing and the
        dense estimate scan is slow, so stay sparse-biased."""
        t = self._promote_samples
        if t <= 0:
            import jax
            try:
                backend = jax.default_backend()
            except Exception:  # backend probe failed; sparse is safe
                backend = "cpu"
            t = self._promote_samples = 2048 if backend == "cpu" else 16
        return t

    def _init_pending(self):
        self._prow = np.full(self.batch_cap, PAD_ROW, np.int32)
        self._pidx = np.zeros(self.batch_cap, np.int32)
        self._prho = np.zeros(self.batch_cap, np.int32)
        self._pcols = (self._prow, self._pidx, self._prho)
        self._n = 0

    def _init_arrays(self):
        self._init_pending()
        if self._sparse:
            self._dev_cap = min(256, self.capacity)
            self._slot_of = np.full(self.capacity, -1, np.int32)
            self._nslots = 0
            self._slot_row: List[int] = []
            self._counts = np.zeros(self.capacity, np.int32)
            self._coo: List[tuple] = []
            self._coo_scalar: tuple = ([], [], [])
        else:
            self._dev_cap = self.capacity
        self.state = batch_hll.init_state(self._dev_cap)

    def _grow_arrays(self, new_cap):
        if self._sparse:
            grown_slots = np.full(new_cap, -1, np.int32)
            grown_slots[: self._slot_of.shape[0]] = self._slot_of
            self._slot_of = grown_slots
            grown_counts = np.zeros(new_cap, np.int32)
            grown_counts[: self._counts.shape[0]] = self._counts
            self._counts = grown_counts
        else:
            self._dev_cap = new_cap
            self.state = _pad_cap(self.state, new_cap)

    def prewarm_dense(self) -> int:
        """Promote every currently-interned row (up to MAX_DEV_SLOTS) so
        the device slot ladder — and each dev-cap shape's scatter and
        estimate compiles — is climbed NOW rather than inside a live
        interval. Benchmark/warmup helper; the next snapshot resets slot
        assignments but _dev_cap persists, so steady state never
        recompiles. Returns the promoted-slot count. No-op for dense
        tables."""
        if not self._sparse:
            return 0
        with self.lock:
            for row in range(min(len(self.meta), self.MAX_DEV_SLOTS)):
                if self._slot_of[row] < 0:
                    self._promote_locked(row)
            return self._nslots

    @property
    def _slot_limit(self) -> int:
        """How many device slots may be ASSIGNED: the HBM guard clamped
        to the current row capacity (slots beyond the table's rows are
        unreachable). Shared by _promote_locked and the add_batch
        promotion-scan gate — they must agree or the scan skip would
        drop count accumulation while promotion is still possible."""
        return min(self.MAX_DEV_SLOTS, self.capacity)

    def _promote_locked(self, row: int) -> None:
        """Assign a device slot (caller holds the buffer lock). A no-op
        at the slot limit — the key stays on the host tier (callers
        re-read _slot_of and route accordingly)."""
        if self._nslots >= self._slot_limit:
            return
        if self._nslots >= self._dev_cap:
            with self.apply_lock:
                # Device-cap growth stays ON THE 8x LADDER, bounded only
                # by the HBM guard — never clamped to capacity: sparse
                # _grow_arrays touches no device state, so a dev cap
                # tracking capacity doublings would pay a fresh
                # scatter/estimate shape compile per doubling on the
                # live ingest path (blocking under apply_lock). Ladder
                # shapes are <= 4 total; slots past the row capacity
                # simply idle (<= 8x overshoot, <= the guard).
                self._dev_cap = min(self._dev_cap * 8, self.MAX_DEV_SLOTS)
                self.state = _pad_cap(self.state, self._dev_cap)
        self._slot_of[row] = self._nslots
        self._slot_row.append(row)
        self._nslots += 1

    def add(self, metric: UDPMetric):
        member = metric.value if isinstance(metric.value, bytes) else str(
            metric.value).encode()
        h = hll_ref.hash_member(member)
        idx, rho = hll_ref.pos_val(h)
        with self.lock:
            row = self.row_for(metric)
            if row < 0:
                return
            self.touched[row] = True
            self._note_applied(1)
            if self._sparse:
                self._counts[row] += 1
                slot = self._slot_of[row]
                if slot < 0 and self._counts[row] >= self.PROMOTE_SAMPLES:
                    self._promote_locked(row)
                    slot = self._slot_of[row]
                if slot < 0:
                    # per-sample sparse path: cheap list appends, turned
                    # into COO arrays at snapshot
                    self._coo_scalar[0].append(row)
                    self._coo_scalar[1].append(idx)
                    self._coo_scalar[2].append(rho)
                    return
                row = int(slot)
            n = self._n
            self._prow[n] = row
            self._pidx[n] = idx
            self._prho[n] = rho
            self._n = n + 1
            if self._n >= self.batch_cap:
                self._dispatch_pending_locked()

    def _apply_cols_state(self, state, cols):
        rows, idxs, rhos = cols
        return batch_hll.apply_batch(state, rows, idxs, rhos)

    def _state_capacity(self) -> int:
        return self._dev_cap

    def _fresh_state_at(self, capacity: int):
        return batch_hll.init_state(capacity)

    def prewarm_rung(self, capacity: int, percentiles=(),
                     need_export: bool = True) -> bool:
        """No-op: the set table's device bank rides its own 8x slot
        ladder (`_dev_cap`), deliberately decoupled from row-capacity
        doublings — see _promote_locked — so a capacity resize never
        retraces its kernels (prewarm_dense climbs the slot ladder)."""
        return False

    def apply_pending(self):
        with self.lock:
            self._dispatch_pending_locked()

    def add_batch(self, rows, reg_idx, rho) -> None:
        """Native-parser fast path: members already hashed to (idx, rho).
        Routes each sample to its key's tier (device slot or host COO)."""
        with self.lock:
            self._note_applied(len(rows))
            if not self._sparse:
                self._append_batch((rows, reg_idx, rho), touch_rows=rows)
                return
            # Route in buffer-sized chunks, re-deriving the slot map for
            # every chunk under the CURRENT lock hold: a dispatch below
            # releases the lock while applying, and a concurrent snapshot
            # resets the slot assignment — slot ids captured before that
            # window would write into the fresh interval's state at
            # stale positions (lost or cross-credited samples).
            start = 0
            total = rows.shape[0]
            while start < total:
                free = self.batch_cap - self._n
                if free <= 0:
                    self._dispatch_pending_locked()  # may release lock
                    continue
                sl = slice(start, start + free)
                r, ix, rh = rows[sl], reg_idx[sl], rho[sl]
                start += r.shape[0]
                slots = self._slot_of[r]
                cold = slots < 0
                if self._nslots < self._slot_limit:
                    # (at the slot cap the promotion scan is a
                    # guaranteed no-op; skip its per-chunk cost)
                    self._counts += np.bincount(
                        r, minlength=self._counts.shape[0]).astype(np.int32)
                    hot_rows = np.unique(
                        r[cold & (self._counts[r] >= self.PROMOTE_SAMPLES)])
                    for hr in hot_rows:
                        self._promote_locked(int(hr))
                    if hot_rows.size:
                        slots = self._slot_of[r]
                        cold = slots < 0
                # COO append + touched in the same hold, BEFORE the
                # dense append below can release the lock mid-dispatch
                if cold.any():
                    self.touched[r[cold]] = True
                    self._coo.append((r[cold].copy(), ix[cold].copy(),
                                      rh[cold].copy()))
                if (~cold).any():
                    # fits in the free space by construction, so the
                    # only possible dispatch happens after the chunk is
                    # fully buffered and touched
                    self._append_batch((slots[~cold], ix[~cold],
                                        rh[~cold]), touch_rows=r[~cold])

    def merge_batch(self, stubs: List[UDPMetric], in_regs) -> None:
        """Import-path HLL merge (register max); imported rows arrive
        dense, so they promote immediately in sparse mode. Rows the
        MAX_DEV_SLOTS cap refuses to promote fold into the host COO
        tier instead (nonzero registers -> (idx, rho) pairs) — scattering
        a -1 slot would corrupt the last device row."""
        with self.lock:
            rows = np.fromiter(
                (self.row_for(s) for s in stubs), np.int32, len(stubs))
            ok = rows >= 0  # cardinality-capped stubs drop out
            rows = rows[ok]
            self.touched[rows] = True
            self._note_applied(int(rows.size))
            regs_sel = np.asarray(in_regs, np.int8)[ok]
            if self._sparse:
                for r in rows:
                    if self._slot_of[r] < 0:
                        self._promote_locked(int(r))
                target = self._slot_of[rows]
                capped = target < 0
                if capped.any():
                    for j in np.flatnonzero(capped).tolist():
                        rowregs = regs_sel[j]
                        nz = np.flatnonzero(rowregs)
                        if nz.size:
                            self._coo.append((
                                np.full(nz.size, int(rows[j]), np.int32),
                                nz.astype(np.int32),
                                rowregs[nz].astype(np.int32)))
                    keep = ~capped
                    target, regs_sel = target[keep], regs_sel[keep]
            else:
                target = rows
            self.apply_lock.acquire()
        try:
            if target.size:
                self.state = batch_hll.merge_rows(
                    self.state, target, regs_sel)
        finally:
            self.apply_lock.release()

    def _host_estimates(self, rows, idx, rho):
        """Vectorized LogLog-Beta over row-grouped COO pairs; returns
        (unique_rows, estimates). Dedupe keeps the max rho per (row,
        register), matching the device scatter-max.

        Grouping sorts ONE fused 64-bit key ((row << 14) | register)
        instead of a 3-key lexsort — measured ~3x faster at the
        interval-scale COO volumes the sustained gate produces."""
        if rows.shape[0] == 0:
            return rows, np.zeros(0, np.float32)
        key = (rows.astype(np.int64) << hll_ref.P) | idx.astype(np.int64)
        order = np.argsort(key, kind="stable")
        k, q = key[order], rho[order]
        # max rho per (row, register) via reduceat over group boundaries
        starts = np.flatnonzero(np.r_[True, k[:-1] != k[1:]])
        qmax = np.maximum.reduceat(q, starts)
        kk = k[starts]
        r = (kk >> hll_ref.P).astype(rows.dtype)
        rb = np.flatnonzero(np.r_[True, r[:-1] != r[1:]])
        urows = r[rb]
        nnz = np.diff(np.r_[rb, r.shape[0]])
        pow_sum = np.add.reduceat(
            np.power(2.0, -qmax.astype(np.float64)), rb)
        ez = float(batch_hll.M) - nnz
        s = ez + pow_sum  # zero registers contribute 2^0 each
        # vectorized LogLog-Beta polynomial (hll_ref.beta14 per element)
        zl = np.log(ez + 1.0)
        beta = hll_ref._BETA14_EZ * ez
        for k, c in enumerate(hll_ref._BETA14):
            beta = beta + c * zl ** (k + 1)
        est = np.floor(
            hll_ref._ALPHA * batch_hll.M * (batch_hll.M - ez)
            / (beta + s) + 1.0)
        return urows, est.astype(np.float32)

    def _swap_extras_locked(self, snap: dict) -> None:
        """Capture the sparse tier's interval state (host COO backlog +
        the slot assignment) atomically with the device generation: the
        captured slot map is what makes the captured pending columns'
        slot ids meaningful."""
        if not self._sparse:
            return
        coo, self._coo = self._coo, []
        sc, self._coo_scalar = self._coo_scalar, ([], [], [])
        if sc[0]:
            coo.append((np.asarray(sc[0], np.int32),
                        np.asarray(sc[1], np.int32),
                        np.asarray(sc[2], np.int32)))
        snap["sparse"] = {"coo": coo, "slot_of": self._slot_of,
                          "slot_row": self._slot_row,
                          "nslots": self._nslots}
        self._slot_of = np.full(self.capacity, -1, np.int32)
        self._slot_row = []
        self._nslots = 0
        self._counts[:] = 0

    def _capture_extras_locked(self, snap: dict) -> None:
        """Read-only sparse-tier capture: the COO backlog and slot map
        copied WITHOUT the reset — the live tier keeps accumulating."""
        if not self._sparse:
            return
        coo = list(self._coo)  # entries are append-once, never mutated
        sc = self._coo_scalar
        if sc[0]:
            coo.append((np.asarray(sc[0], np.int32),
                        np.asarray(sc[1], np.int32),
                        np.asarray(sc[2], np.int32)))
        snap["sparse"] = {"coo": coo, "slot_of": self._slot_of.copy(),
                          "slot_row": list(self._slot_row),
                          "nslots": self._nslots}

    def _query_readout_device(self, state, snap: dict) -> None:
        # the sparse readout folds the hot-COO backlog through the
        # DONATING scatter-max kernel — feed it a private copy so the
        # live bank's buffers survive the query (single-device table,
        # so the default-device copy placement is the right one)
        if self._sparse:
            state = jnp.copy(state)
        self._readout_device(state, snap)

    def _readout_device(self, state, snap: dict) -> None:
        """Estimate + register-provider assembly over the captured
        generation. The register provider keeps a live device reference
        (lazy transfer), so the captured generation escapes into the
        snapshot and is NOT recycled."""
        if not self._sparse:
            snap["estimates"] = np.asarray(batch_hll.estimate(state))
            snap["registers"] = _SetRegisters.dense(state, self.capacity)
            return
        sparse = snap.pop("sparse")
        coo = sparse["coo"]
        slot_of = sparse["slot_of"]
        slot_row = sparse["slot_row"]
        nslots = sparse["nslots"]
        # fold promoted rows' pre-promotion backlog into the device
        # table, then split the remaining COO per sparse row
        if coo:
            rows_all = np.concatenate([c[0] for c in coo])
            idx_all = np.concatenate([c[1] for c in coo])
            rho_all = np.concatenate([c[2] for c in coo])
        else:
            rows_all = np.zeros(0, np.int32)
            idx_all = rho_all = rows_all
        pslots = slot_of[rows_all] if rows_all.size else rows_all
        hot = pslots >= 0
        hot_slots = pslots[hot]
        hot_idx, hot_rho = idx_all[hot], rho_all[hot]
        for i in range(0, hot_slots.shape[0], self.batch_cap):
            sl = slice(i, i + self.batch_cap)
            chunk_rows = hot_slots[sl]
            pad = self.batch_cap - chunk_rows.shape[0]
            state = batch_hll.apply_batch(
                state,
                np.concatenate([chunk_rows,
                                np.full(pad, PAD_ROW, np.int32)]),
                np.concatenate([hot_idx[sl], np.zeros(pad, np.int32)]),
                np.concatenate([hot_rho[sl], np.zeros(pad, np.int32)]))

        estimates = np.zeros(self.capacity, np.float32)
        dev_regs = None
        if nslots:
            dev_est = np.asarray(batch_hll.estimate(state))
            dev_regs = state  # device ref; _SetRegisters is lazy
            estimates[np.asarray(slot_row, np.int64)] = dev_est[:nslots]
        s_rows = rows_all[~hot]
        s_idx, s_rho = idx_all[~hot], rho_all[~hot]
        if s_rows.size:
            urows, est = self._host_estimates(s_rows, s_idx, s_rho)
            estimates[urows] = est
            order = np.argsort(s_rows, kind="stable")
            s_rows, s_idx, s_rho = (s_rows[order], s_idx[order],
                                    s_rho[order])
        snap["estimates"] = estimates
        snap["registers"] = _SetRegisters(dev_regs, slot_of, s_rows,
                                          s_idx, s_rho)

    def snapshot_begin(self) -> dict:
        """Dispatch half: swap + estimate readout (the estimate is
        realized eagerly — the set families are host-dominant)."""
        return self.readout(self.swap_out())

    @staticmethod
    def snapshot_finish(snap: dict):
        return (snap["estimates"], snap["registers"], snap["touched"],
                snap["meta"])

    def snapshot_and_reset(self):
        # recycle is a no-op for the sparse tier (its captured bank
        # escapes into the register provider) and real for the sharded
        # dense tier
        return self._finish_and_recycle(self.snapshot_begin())


class LLHistTable(_BaseTable):
    """Circllhist log-linear histograms: a dense (K, BINS) int32
    register table (veneur_tpu.ops.batch_llhist). The host bins values
    (ops/llhist_ref.bin_index — the same code the scalar reference
    runs, so the two can never disagree) into (row, bin, weight)
    triples; the device applies them as one scatter-add per batch.
    Merges — import, carryover, interval — are register additions,
    which is the family's whole point: the forward tier's global
    percentile is bit-identical to a single node that saw every sample.

    Weights are integral (1/sample_rate rounds to the nearest count);
    clamp accounting (values outside the representable magnitude
    window) is surfaced as the llhist.samples/llhist.clamped rows in
    ColumnStore.telemetry_rows."""

    def _init_arrays(self):
        self._prow = np.full(self.batch_cap, PAD_ROW, np.int32)
        self._pbin = np.zeros(self.batch_cap, np.int32)
        self._pwt = np.zeros(self.batch_cap, np.int32)
        self._pcols = (self._prow, self._pbin, self._pwt)
        self._n = 0
        self.state = batch_llhist.init_state(self.capacity)
        # monotonic sample/clamp accounting (mutated under `lock`)
        self.samples_total = 0
        self.clamped_total = 0

    def _grow_arrays(self, new_cap):
        self.state = _pad_cap(self.state, new_cap)

    def add(self, metric: UDPMetric):
        value = float(metric.value)
        bin_idx = int(llhist_ref.bin_index(value))
        # clamp into int32: registers are int32, and an absurd-but-valid
        # sample rate (@1e-10) must saturate, not overflow the buffer
        # assignment (same clamp as bin_batch_host and the C++ parser)
        weight = min(max(1, round(1.0 / max(metric.sample_rate, 1e-9))),
                     2**31 - 1)
        with self.lock:
            row = self.row_for(metric)
            if row < 0:
                return
            self.touched[row] = True
            self._note_applied(1)
            self.samples_total += weight
            if llhist_ref.clamped_mask(value):
                self.clamped_total += weight
            n = self._n
            self._prow[n] = row
            self._pbin[n] = bin_idx
            self._pwt[n] = weight
            self._n = n + 1
            if self._n >= self.batch_cap:
                self._dispatch_pending_locked()

    def _apply_cols_state(self, state, cols):
        rows, bins, wts = cols
        return batch_llhist.apply_batch(state, rows, bins, wts)

    def _fresh_state_at(self, capacity: int):
        return batch_llhist.init_state(capacity)

    def apply_pending(self):
        with self.lock:
            self._dispatch_pending_locked()

    def add_batch(self, rows, vals, weights) -> None:
        """Batch fast path: pre-interned rows, raw values (binned here),
        weights are 1/sample_rate floats."""
        bins, wts = batch_llhist.bin_batch_host(vals, weights)
        with self.lock:
            self._note_applied(len(rows))
            self.samples_total += int(wts.sum())
            self.clamped_total += int(
                wts[llhist_ref.clamped_mask(vals)].sum())
            self._append_batch((np.asarray(rows, np.int32), bins, wts))

    def add_batch_binned(self, rows, bins, wts, clamped: int = 0) -> None:
        """Batch fast path for ALREADY-binned samples — the native (C++)
        batch parser bins the `l` wire type itself (llhist_ref.bin_index
        parity pinned by the ingest fuzz corpus), so the hand-off is
        three int32 columns and no host float work at all. `clamped` is
        the parser's count of weight that fell outside the bin window
        (the accuracy-loss accounting bins alone can't reconstruct)."""
        with self.lock:
            self._note_applied(len(rows))
            self.samples_total += int(np.sum(wts))
            self.clamped_total += int(clamped)
            self._append_batch((np.asarray(rows, np.int32),
                                np.asarray(bins, np.int32),
                                np.asarray(wts, np.int32)))

    def merge_batch(self, stubs: List[UDPMetric], in_bins) -> None:
        """Import-path merge: register add. Interning atomic under the
        buffer lock; the state update rides the apply ticket so it
        orders after any already-swapped local batches."""
        with self.lock:
            rows = np.fromiter(
                (self.row_for(s) for s in stubs), np.int32, len(stubs))
            ok = rows >= 0  # cardinality-capped stubs drop out
            rows = rows[ok]
            self.touched[rows] = True
            self._note_applied(int(rows.size))
            padded = batch_llhist.pad_rows_to_device(
                np.asarray(in_bins)[ok])
            self.samples_total += int(padded.sum())
            self.apply_lock.acquire()
        try:
            if rows.size:
                self.state = batch_llhist.merge_rows(
                    self.state, rows, padded)
        finally:
            self.apply_lock.release()

    def _idle_swap_locked(self, snap: dict) -> bool:
        # idle-family fast path: every mutation path sets touched,
        # so no pending samples + no touched rows means the state
        # is still the all-zero array the last reset left — skip
        # the capacity-proportional readout dispatch, the register
        # gather, and the generation swap entirely. The generation
        # still advances so idle-row reclamation of a gone-quiet
        # keyset keeps working.
        if self._n == 0 and not self.touched.any():
            self._note_generation_locked()
            snap.update(packed=None, bins_dev=None,
                        touched=self.touched.copy(),
                        meta=list(self.meta))
            return True
        return False

    def _idle_capture_locked(self, snap: dict) -> bool:
        # same skip for queries — minus the generation advance (a
        # read-only capture must not perturb idle-row reclamation)
        if self._n == 0 and not self.touched.any():
            snap.update(packed=None, bins_dev=None,
                        touched=self.touched.copy(),
                        meta=list(self.meta))
            return True
        return False

    def snapshot_begin(self, percentiles: Tuple[float, ...],
                       need_bins: bool = True) -> dict:
        """Dispatch-only snapshot half (see CounterTable.snapshot_begin):
        swap+apply pending, dispatch the readout, capture the touched
        rows' raw bins (gathered on device, so only live rows cross the
        link — the full table at 100k keys would be ~2 GB), reset.
        `need_bins=False` (a server that neither forwards nor exports
        buckets) skips the register transfer entirely."""
        return self.readout(self.swap_out(
            ps=tuple(percentiles), need_bins=need_bins))

    def _readout_device(self, state, snap: dict) -> None:
        """Dispatch the readout + bins gather over the captured
        generation. The sharded table overrides this with the
        register-ADD collective merge before the same readout."""
        packed = batch_llhist.flush_packed(state, snap["ps"])
        rows = np.flatnonzero(snap["touched"])
        bins_dev = None
        if snap.pop("need_bins") and rows.size:
            bins_dev = jnp.take(state, jnp.asarray(rows, jnp.int32),
                                axis=0)
        snap["packed"] = packed
        snap["bins_dev"] = bins_dev
        snap["_recycle"] = state

    def _prewarm_readout(self, state, capacity: int, ps: tuple,
                         need_export: bool):
        return (batch_llhist.flush_packed(state, ps),
                _zeros_like_spare(state))

    @staticmethod
    def snapshot_finish(snap: dict):
        """Returns (readout dict of np arrays over all rows, bins int64
        (n_touched, BINS) aligned with the touched rows in ascending
        order, touched, meta)."""
        if snap["packed"] is None:  # idle-family fast path
            return ({}, np.zeros((0, llhist_ref.BINS), np.int64),
                    snap["touched"], snap["meta"])
        out = {k: np.asarray(v) for k, v in snap["packed"].items()}
        if snap["bins_dev"] is not None:
            bins = np.asarray(snap["bins_dev"])[:, :llhist_ref.BINS]
            bins = bins.astype(np.int64)
        else:
            bins = np.zeros((0, llhist_ref.BINS), np.int64)
        return out, bins, snap["touched"], snap["meta"]

    def snapshot_and_reset(self, percentiles: Tuple[float, ...],
                           need_bins: bool = True):
        return self._finish_and_recycle(
            self.snapshot_begin(percentiles, need_bins))


@dataclass
class StatusEntry:
    value: float = 0.0
    message: str = ""
    hostname: str = ""


class StatusTable(_BaseTable):
    """Service checks: last status + message; strings stay on host
    (reference samplers.go:210-231)."""

    def _init_arrays(self):
        self.values: List[StatusEntry] = []

    def _grow_arrays(self, new_cap):
        pass

    def add(self, metric: UDPMetric):
        with self.lock:
            row = self.row_for(metric)
            if row < 0:
                return
            while len(self.values) <= row:
                self.values.append(StatusEntry())
            self.touched[row] = True
            self._note_applied(1)
            self.values[row] = StatusEntry(
                value=float(metric.value), message=metric.message,
                hostname=metric.hostname)

    def apply_pending(self):
        pass

    def snapshot_and_reset(self):
        with self.lock:
            vals = list(self.values)
            self._note_generation_locked()
            touched = self.touched.copy()
            meta = list(self.meta)
            self.values = [StatusEntry() for _ in vals]
            self.touched[:] = False
        return vals, touched, meta


class ColumnStore:
    """All five device families plus host-side status checks.

    With shard_devices > 1 the store becomes a partitioned mesh
    (core/sharded_tables.py): every family's interval state spreads
    across that many local devices, keys routed to a digest-derived
    home shard and flushes merged with collectives. The legacy
    `shard_routing="roundrobin"` mode shards only the HBM-heavy
    histogram/set families (round-robin batches destroy the per-key
    ordering the scalar families need)."""

    def __init__(self, counter_capacity=1024, gauge_capacity=1024,
                 histo_capacity=1024, set_capacity=256, batch_cap=8192,
                 shard_devices=0, max_rows=0, pallas_flush=False,
                 set_promote_samples=0, set_max_dev_slots=0,
                 llhist_capacity=1024, histogram_encoding="tdigest",
                 shard_routing="digest"):
        # histogram_encoding chooses the family DogStatsD histogram/timer
        # samples aggregate in: "tdigest" (reference parity, approximate
        # merges) or "circllhist" (log-linear bins, exact merges).
        # Explicit `|l` samples and OTLP exponential histograms always
        # land in the llhist family regardless.
        if histogram_encoding not in ("tdigest", "circllhist"):
            raise ValueError(
                f"unknown histogram_encoding: {histogram_encoding!r}")
        self.histogram_encoding = histogram_encoding
        self.shard_plane = None
        if shard_devices and shard_devices > 1:
            from veneur_tpu.parallel.sharded_server import build_plane
            self.shard_plane = build_plane(shard_devices, shard_routing)
        plane = self.shard_plane
        digest_routed = plane is not None and plane.routing == "digest"
        if digest_routed:
            from veneur_tpu.core.sharded_tables import (
                ShardedCounterTable, ShardedGaugeTable,
                ShardedLLHistTable)
            self.counters = ShardedCounterTable(
                counter_capacity, batch_cap, max_rows=max_rows,
                plane=plane)
            self.gauges = ShardedGaugeTable(
                gauge_capacity, batch_cap, max_rows=max_rows, plane=plane)
            self.llhists = ShardedLLHistTable(
                llhist_capacity, batch_cap, max_rows=max_rows,
                plane=plane)
        else:
            self.counters = CounterTable(counter_capacity, batch_cap,
                                         max_rows=max_rows)
            self.gauges = GaugeTable(gauge_capacity, batch_cap,
                                     max_rows=max_rows)
            self.llhists = LLHistTable(llhist_capacity, batch_cap,
                                       max_rows=max_rows)
        if plane is not None:
            from veneur_tpu.core.sharded_tables import (
                ShardedHistoTable, ShardedSetTable)
            self.histos = ShardedHistoTable(
                histo_capacity, batch_cap, max_rows=max_rows, plane=plane)
            self.sets = ShardedSetTable(set_capacity, batch_cap,
                                        max_rows=max_rows, plane=plane)
        else:
            self.histos = HistoTable(histo_capacity, batch_cap,
                                     max_rows=max_rows)
            self.sets = SetTable(set_capacity, batch_cap,
                                 max_rows=max_rows,
                                 promote_samples=set_promote_samples,
                                 max_dev_slots=set_max_dev_slots)
        self.histos.pallas_flush = bool(pallas_flush)
        if pallas_flush and histo_capacity % 128:
            # pallas_tdigest.BK tiling: a non-multiple capacity silently
            # takes the jnp path, which would make a kernel A/B
            # measure nothing
            logger.warning(
                "tpu.pallas_tdigest_flush requested but histo_capacity "
                "%d is not a multiple of 128; flushes use the jnp path",
                histo_capacity)
        self.statuses = StatusTable(max_rows=max_rows)
        for family, table in self.tables():
            table.family = family
        self.processed = 0
        self.ledger = None  # set by attach_ledger
        self.deviceobs = None  # set by attach_deviceobs
        self._processed_lock = threading.Lock()

    def tables(self):
        """(family, table) pairs, every device family plus statuses."""
        return (("counter", self.counters), ("gauge", self.gauges),
                ("histogram", self.histos), ("llhist", self.llhists),
                ("set", self.sets), ("status", self.statuses))

    def attach_cardinality(self, accountant) -> None:
        """Wire the cardinality accountant (core/cardinality.py) into
        every table's interning path."""
        for _family, table in self.tables():
            table.cardinality = accountant

    def attach_deviceobs(self, obs) -> None:
        """Wire the device observatory (core/deviceobs.py) into every
        table's generation lifecycle and kernel dispatch paths, register
        the current live generations (and any parked spares) in its HBM
        ledger, and hand it the store for shard-balance scrapes."""
        self.deviceobs = obs
        obs.attach_store(self)
        for family, table in self.tables():
            table._deviceobs = obs
            with table.apply_lock:
                state = table._devobs_state()
                if state is not None and table._devobs_live is None:
                    table._devobs_live = obs.note_generation(
                        family, "live", state)
                if table._spare is not None \
                        and table._devobs_spare is None:
                    table._devobs_spare = obs.note_generation(
                        family, "spare", table._spare)

    def attach_ledger(self, ledger) -> None:
        """Wire the flow ledger (core/ledger.py) into every table's
        apply/reject paths — the out-side of the ingest conservation
        identity (admitted == applied + rejected)."""
        self.ledger = ledger
        for _family, table in self.tables():
            table.ledger = ledger

    def attach_resize_hook(self, hook) -> None:
        """hook(family, old_cap, new_cap, seconds, kind=...) fires on
        every capacity doubling (kind="resize", under the buffer lock —
        see _BaseTable.on_resize for what the hook may safely do) and on
        the first post-resize batch apply (kind="recompile")."""
        for _family, table in self.tables():
            table.on_resize = hook

    def telemetry_rows(self) -> List[tuple]:
        """(name, kind, value, tags) scrape-time rows: per-family row
        capacity/occupancy, batch-buffer state, resize/recompile cost,
        and key-churn counters — the capacity picture that previously
        existed only as in-memory attributes. Reads are lock-free (GIL
        point reads of monotonic counters and gauges; a torn gauge is
        one scrape stale, never corrupt)."""
        rows: List[tuple] = []
        for family, t in self.tables():
            tags = [f"family:{family}"]
            rows.append(("columnstore.row_capacity", "gauge",
                         float(t.capacity), tags))
            rows.append(("columnstore.live_rows", "gauge",
                         float(len(t.rows)), tags))
            rows.append(("columnstore.free_rows", "gauge",
                         float(len(t._free_rows)), tags))
            rows.append(("columnstore.keys_minted_total", "counter",
                         float(t.minted_total), tags))
            rows.append(("columnstore.keys_tombstoned_total", "counter",
                         float(t.tombstoned_total), tags))
            rows.append(("columnstore.keys_recycled_total", "counter",
                         float(t.recycled_total), tags))
            rows.append(("columnstore.keys_dropped_total", "counter",
                         float(t.keys_dropped), tags))
            rows.append(("columnstore.resize_total", "counter",
                         float(t.resize_total), tags))
            rows.append(("columnstore.resize_seconds_total", "counter",
                         t.resize_seconds_total, tags))
            rows.append(("columnstore.resize_last_seconds", "gauge",
                         t.resize_last_seconds, tags))
            rows.append(("columnstore.recompile_seconds_total", "counter",
                         t.recompile_seconds_total, tags))
            rows.append(("columnstore.recompile_last_seconds", "gauge",
                         t.recompile_last_seconds, tags))
            rows.append(("columnstore.batch_dispatch_total", "counter",
                         float(t.dispatch_total), tags))
            pending = getattr(t, "_n", None)
            if pending is not None:  # statuses have no batch buffers
                rows.append(("columnstore.batch_cap", "gauge",
                             float(t.batch_cap), tags))
                rows.append(("columnstore.pending_samples", "gauge",
                             float(pending), tags))
            nslots = getattr(t, "_nslots", None)
            if nslots is not None:  # sparse set table: promoted HBM rows
                rows.append(("columnstore.set_dev_slots", "gauge",
                             float(nslots), tags))
        # llhist accuracy accounting: samples binned, and how many fell
        # outside the representable magnitude window (collapsed to the
        # zero bin / clamped into a top bin)
        rows.append(("llhist.samples_total", "counter",
                     float(self.llhists.samples_total), ()))
        rows.append(("llhist.clamped_total", "counter",
                     float(self.llhists.clamped_total), ()))
        # sharded serving plane: mesh topology + per-shard routed volume
        # (parallel/sharded_server.py), absent on single-device stores
        if self.shard_plane is not None:
            rows.extend(self.shard_plane.telemetry_rows())
        return rows

    def capacity_report(self) -> dict:
        """Per-family capacity/churn snapshot for /debug/cardinality."""
        out = {}
        for family, t in self.tables():
            out[family] = {
                "row_capacity": t.capacity,
                "live_rows": len(t.rows),
                "allocated_rows": len(t.meta),
                "free_rows": len(t._free_rows),
                "minted_total": t.minted_total,
                "tombstoned_total": t.tombstoned_total,
                "recycled_total": t.recycled_total,
                "keys_dropped_total": t.keys_dropped,
                "resize_total": t.resize_total,
                "resize_seconds_total": round(t.resize_seconds_total, 6),
                "resize_last_seconds": round(t.resize_last_seconds, 6),
                "recompile_seconds_total": round(
                    t.recompile_seconds_total, 6),
                "recompile_last_seconds": round(
                    t.recompile_last_seconds, 6),
                "batch_dispatch_total": t.dispatch_total,
            }
        return out

    def live_rows_by_name(self) -> Dict[str, dict]:
        """On-demand exact per-name series accounting: walks every
        table's meta under its buffer lock (pointer-copy only; the
        group-by runs outside the lock). Capacity-proportional — this is
        the /debug/cardinality drill-down path, never the hot path."""
        per_name: Dict[str, dict] = {}
        for family, t in self.tables():
            with t.lock:
                metas = list(t.meta)
                touched = t.touched.copy()
            for row, meta in enumerate(metas):
                if meta is None:
                    continue
                entry = per_name.setdefault(
                    meta.name, {"live_rows": 0, "touched_rows": 0,
                                "families": {}})
                entry["live_rows"] += 1
                entry["families"][family] = \
                    entry["families"].get(family, 0) + 1
                if row < touched.shape[0] and touched[row]:
                    entry["touched_rows"] += 1
        return per_name

    def count_processed(self, n: int) -> None:
        """Locked sample-count increment (readers race on += otherwise)."""
        with self._processed_lock:
            self.processed += n

    def process(self, metric: UDPMetric) -> None:
        """Route one parsed metric to its family table (the equivalent of
        reference worker.go:350-404 ProcessMetric)."""
        t = metric.key.type
        if t == m.COUNTER:
            self.counters.add(metric)
        elif t == m.GAUGE:
            self.gauges.add(metric)
        elif t in (m.HISTOGRAM, m.TIMER):
            if self.histogram_encoding == "circllhist":
                self.llhists.add(metric)
            else:
                self.histos.add(metric)
        elif t == m.LLHIST:
            self.llhists.add(metric)
        elif t == m.SET:
            self.sets.add(metric)
        elif t == m.STATUS:
            self.statuses.add(metric)
        else:
            # unknown wire type: the sample was counted admitted by the
            # caller, so its drop must be explained or the ledger's
            # ingest identity (rightly) flags it
            led = getattr(self, "ledger", None)
            if led is not None:
                led.note("agg.rejected", 1, key="unknown")
            return
        self.count_processed(1)

    def apply_all_pending(self):
        self.counters.apply_pending()
        self.gauges.apply_pending()
        self.histos.apply_pending()
        self.llhists.apply_pending()
        self.sets.apply_pending()

    def unique_timeseries(self) -> int:
        """Timeseries touched this interval. The reference approximates
        this with a per-worker HLL over key digests (worker.go:305-347);
        the column store's touched masks make it exact for free."""
        total = 0
        for table in (self.counters, self.gauges, self.histos,
                      self.llhists, self.sets, self.statuses):
            with table.lock:
                total += int(np.count_nonzero(table.touched))
        return total
