"""The veneur-tpu server: wires config -> column store -> sources -> sinks.

Structural parity with reference server.go (NewFromConfig:462, Start:711,
Flush ticker:837-875, HandleMetricPacket:949, Shutdown:1424) with the
worker pool replaced by the device column store. Ingest threads parse
packets and append samples to batch buffers; the flush ticker runs the
device flush kernels and fans InterMetrics out to sinks in parallel.
"""

from __future__ import annotations

import logging
import os
import queue
import socket
import threading
import time
from typing import Callable, Dict, List, Optional

from veneur_tpu import sinks as sinks_mod
from veneur_tpu.config import Config, SinkConfig
from veneur_tpu.core import networking
from veneur_tpu.core.columnstore import ColumnStore
from veneur_tpu.core.flusher import (
    FlushBatch, ForwardableState, flush_columnstore_batch,
    readout_columnstore, swap_columnstore)
from veneur_tpu.samplers import metrics as m
from veneur_tpu.samplers.metrics import (
    HistogramAggregates, InterMetric, MetricScope, UDPMetric,
)
from veneur_tpu.samplers.parser import ParseError, Parser
from veneur_tpu.util.matcher import SinkRoutingMatcher

logger = logging.getLogger("veneur_tpu.server")

# wire type -> overload shed class (the priority ladder's middle rung;
# counter/gauge/status samples never appear here — they are always kept)
from veneur_tpu.core import overload as overload_mod  # noqa: E402

_SHED_CLASS = {
    m.HISTOGRAM: overload_mod.CLASS_HISTOGRAM,
    m.TIMER: overload_mod.CLASS_HISTOGRAM,
    m.LLHIST: overload_mod.CLASS_HISTOGRAM,
    m.SET: overload_mod.CLASS_SET,
}


class RawSpan:
    """A span still in wire form: the native SSF path already extracted
    its metrics, so decoding (for external span sinks) happens lazily in
    the span worker instead of on the ingest path."""

    __slots__ = ("data",)

    def __init__(self, data: bytes):
        self.data = data


class _SpanSinkWorker:
    """Per-sink span ingest isolation: each external span sink gets a
    bounded buffer and one dedicated thread, so a slow or hung sink drops
    its own spans instead of stalling the shared span workers — the
    TPU-build equivalent of the reference's 9 s per-sink ingest timeout
    (reference worker.go:588-656). Internal sinks (metric extraction) are
    called inline by the span workers and bypass this.

    Spans move in CHUNKS: the span workers submit whole decoded batches
    and this thread swaps the pending list out in one lock window, so
    per-span cost on the shared path is one list-append — at bench rate
    (>100k spans/s) per-span Queue put/get was itself the bottleneck and
    shed half the stream (BENCH_r04: 137,896 drops in 5.7 s). Capacity
    counts SPANS, not chunks, and a chunk that would overflow is dropped
    whole (accounted per-sink)."""

    def __init__(self, sink, capacity: int, observatory=None):
        from veneur_tpu.sinks import SpanSink
        self.sink = sink
        # duck-typed sinks (tests, plugins) may predate the batch API;
        # bind the base default for them (per-span isolate-and-log) so
        # the loop has exactly one delivery path
        self._ingest_many = getattr(
            sink, "ingest_many",
            lambda chunk: SpanSink.ingest_many(sink, chunk))
        self.capacity = max(16, capacity)
        self._pending: list = []  # list of (enqueue_t, chunk) pairs
        self._pending_spans = 0
        # queue-dwell telemetry: per-chunk enqueue->drain latency plus a
        # scrape-time depth gauge (None when the observatory is off)
        self._dwell = None
        if observatory is not None and observatory.enabled:
            qname = f"span_sink:{sink.name()}"
            self._dwell = observatory.queue_hist(qname)
            observatory.register_queue(
                qname, lambda: self._pending_spans, self.capacity)
        self._lock = threading.Lock()
        self._ready = threading.Condition(self._lock)
        self.dropped = 0
        self.ingested = 0
        self._stop = threading.Event()
        self.thread: Optional[threading.Thread] = None

    def start(self) -> None:
        from veneur_tpu.util.crash import guarded
        self.thread = threading.Thread(
            target=guarded(self._loop),
            name=f"span-sink-{self.sink.name()}", daemon=True)
        self.thread.start()

    def submit(self, span) -> None:
        self.submit_many((span,))

    def submit_many(self, spans) -> None:
        n = len(spans)
        if n == 0:
            return
        with self._lock:
            # overflow drops whole chunks, but an empty buffer always
            # accepts one — otherwise a configured capacity below the
            # worker batch size (256) would starve the sink forever
            if self._pending and self._pending_spans + n > self.capacity:
                self.dropped += n
                return
            self._pending.append((time.monotonic(), spans))
            self._pending_spans += n
            self._ready.notify()

    def _loop(self) -> None:
        while True:
            with self._lock:
                while not self._pending:
                    if self._stop.is_set():
                        return
                    self._ready.wait(timeout=0.5)
                chunks, self._pending = self._pending, []
                self._pending_spans = 0
            dwell = self._dwell
            now = time.monotonic() if dwell is not None else 0.0
            for enqueued_t, chunk in chunks:
                if dwell is not None:
                    dwell.observe(now - enqueued_t)
                try:
                    self._ingest_many(chunk)
                    self.ingested += len(chunk)
                except Exception:
                    logger.exception(
                        "span sink %s ingest failed", self.sink.name())

    def stop(self, timeout: float = 2.0) -> None:
        """Signal, then join: the loop drains whatever was already
        submitted before it sees the stop flag on its next empty wait."""
        self._stop.set()
        with self._lock:
            self._ready.notify()
        if self.thread is not None:
            self.thread.join(timeout)


class Server:
    # consecutive flush ticks a background readout may miss its join
    # grace before being dropped: a transient device stall carries the
    # completed interval forward to later ticks instead of losing it,
    # while a truly wedged readout is bounded (and the supervisor's
    # flush-readout deadline escalates it independently)
    READOUT_MISS_LIMIT = 3

    def __init__(self, config: Config,
                 extra_metric_sinks: Optional[List] = None,
                 extra_span_sinks: Optional[List] = None):
        self.config = config
        self.interval = config.interval
        # forward_only: metrics that don't declare a scope become
        # global-only, so a local server aggregates nothing itself and
        # forwards everything (reference server.go:547-552)
        self.parser = Parser(
            extend_tags=config.extend_tags,
            default_scope=(MetricScope.GLOBAL_ONLY if config.forward_only
                           else MetricScope.MIXED))
        self.store = ColumnStore(
            counter_capacity=config.tpu.counter_capacity,
            gauge_capacity=config.tpu.gauge_capacity,
            histo_capacity=config.tpu.histo_capacity,
            set_capacity=config.tpu.set_capacity,
            batch_cap=config.tpu.batch_cap,
            shard_devices=config.tpu.shards,
            max_rows=config.tpu.max_rows_per_family,
            pallas_flush=config.tpu.pallas_tdigest_flush,
            set_promote_samples=config.tpu.set_promote_samples,
            set_max_dev_slots=config.tpu.set_max_dev_slots,
            llhist_capacity=config.tpu.llhist_capacity,
            histogram_encoding=config.histogram_encoding,
            shard_routing=config.tpu.shard_routing)
        self._keys_dropped_reported = 0
        self.aggregates = HistogramAggregates.from_names(config.aggregates)
        self.percentiles = tuple(config.percentiles)

        sinks_mod.register_builtin_sinks()
        self.metric_sinks: List = list(extra_metric_sinks or [])
        for sc in config.metric_sinks:
            factory = sinks_mod.MetricSinkTypes.get(sc.kind)
            if factory is None:
                raise ValueError(f"unknown metric sink kind: {sc.kind}")
            self.metric_sinks.append(factory(sc, config))
        self.span_sinks: List = list(extra_span_sinks or [])
        for sc in config.span_sinks:
            factory = sinks_mod.SpanSinkTypes.get(sc.kind)
            if factory is None:
                raise ValueError(f"unknown span sink kind: {sc.kind}")
            self.span_sinks.append(factory(sc, config))
        # per-sink tag/name filtering config — only sinks with ACTIVE
        # filters, so unfiltered config-declared sinks still take the
        # columnar fast path in _flush_sink_safe (an entry here forces
        # per-metric object materialization)
        self._sink_filters = {
            sc.name or sc.kind: sc for sc in config.metric_sinks
            if (sc.strip_tags or sc.add_tags or sc.max_name_length
                or sc.max_tag_length or sc.max_tags)}

        from veneur_tpu import sources as sources_mod
        sources_mod.register_builtin_sources()
        self.sources: List = []
        for src_cfg in config.sources:
            factory = sources_mod.SourceTypes.get(src_cfg.kind)
            if factory is None:
                raise ValueError(f"unknown source kind: {src_cfg.kind}")
            self.sources.append(factory(src_cfg, config))
        self._source_threads: List[threading.Thread] = []

        self._routing = None
        if config.features.enable_metric_sink_routing:
            self._routing = [SinkRoutingMatcher(rc)
                             for rc in config.metric_sink_routing]

        # events & service-check samples buffered between flushes
        self._other_samples: List = []
        self._other_lock = threading.Lock()

        # latency observatory (core/latency.py): flush dispatch
        # attribution, per-plane sample-age watermarks, and queue
        # dwell/depth telemetry. Created before any bounded hand-off so
        # the queues below can be instrumented at construction.
        from veneur_tpu.core.latency import LatencyObservatory
        self.latency = LatencyObservatory(
            enabled=config.latency_observatory)

        # span pipeline: bounded channel + worker pool (reference
        # server.go:728-736, worker.go:547-686); the metric-extraction
        # sink is always attached (server.go:654-664)
        from veneur_tpu.sinks.ssfmetrics import MetricExtractionSink
        self.metric_extraction = MetricExtractionSink(
            self.ingest_metric, self.parser,
            indicator_timer_name=config.indicator_span_timer_name,
            objective_timer_name=config.objective_span_timer_name)
        self.span_sinks.append(self.metric_extraction)
        self.span_chan: "queue.Queue" = self.latency.instrument_queue(
            "span_channel", maxsize=config.span_channel_capacity)
        self._span_workers: List[threading.Thread] = []
        self._span_sink_workers: List[_SpanSinkWorker] = []
        self.spans_dropped = 0

        self.forwarder: Optional[Callable[[ForwardableState], None]] = None
        self.forward_client = None  # set in start() when forward_address
        self.import_server = None  # set in start() when grpc_address
        self.grpc_ingest_servers: List = []  # per grpc_listen_addresses
        # timestamp-faithful backfill (forward/backfill.py): imports
        # stamped with an interval older than backfill_after_s bucket
        # by ORIGINAL interval and flush with original timestamps.
        # Constructed below once the ledger exists.
        self.backfill = None
        self.backfill_after_s = 0.0
        # the running interval's start (the previous flush boundary):
        # WAL appends stamp it onto every forwardable snapshot
        self._interval_start_unix = time.time()

        # pull-side telemetry: every statsd emission below tees into this
        # registry, and the HTTP API serves it (/metrics, /debug/events,
        # /debug/flush) — the expvar/flight-recorder side of the loop
        from veneur_tpu.core import telemetry as telemetry_mod
        self.telemetry = telemetry_mod.Telemetry()
        self.telemetry.registry.add_collector(self._live_telemetry_rows)
        self.telemetry.registry.add_collector(self._ring_telemetry_rows)
        self.telemetry.registry.add_collector(
            telemetry_mod.device_memory_rows)

        # cross-tier self-trace plane (trace/store.py): the bounded
        # trace store behind /debug/traces, the pre-minted per-interval
        # trace id that exemplar capture and the flush span share, and
        # the sampling decision bounding all of it. Every flight-
        # recorder event and ledger interval is stamped with the active
        # interval's trace id, and /metrics exposition lines pick up
        # OpenMetrics exemplars from the plane.
        from veneur_tpu.trace.store import SelfTracePlane
        self.trace_plane = SelfTracePlane(
            service="veneur-tpu",
            sample_rate=config.trace_self_sample_rate,
            max_traces=config.trace_store_traces,
            max_spans=config.trace_store_spans,
            exemplar_names=config.trace_exemplar_names)
        self.telemetry.registry.add_collector(
            self.trace_plane.telemetry_rows)
        self.telemetry.trace_source = self.trace_plane.active_trace_hex
        self.telemetry.registry.exemplar_source = \
            self.trace_plane.exemplar_for
        # a GLOBAL's next flush adopts the originating local's interval
        # trace (latest fresh import wins); see adopt_flush_trace
        self._adopted_trace = None

        # flow ledger (core/ledger.py): per-interval conservation
        # accounting from socket to sink ack. Declared here so every
        # crossing below (ingest, store, forward, spool) can stamp it;
        # the interval closes at the end of each flush.
        from veneur_tpu.core.ledger import FlowLedger
        self.ledger = FlowLedger(
            enabled=config.ledger_enabled, strict=config.ledger_strict,
            history=config.ledger_history,
            on_event=self.telemetry.record_event)
        # ingested = aggregated + rejected: a sample admitted past
        # admission control must land in a family table or be rejected
        # at the mint gate — anything else is a silent drop
        self.ledger.declare(
            "ingest", inputs=("ingest.admitted",),
            outputs=("agg.applied", "agg.rejected"),
            # migrating digest-range rows captured out of the old
            # topology but not yet merged into the new one (always 0
            # at close — the cutover runs under _flush_lock — so a
            # nonzero closing level is itself a conservation break)
            stocks=("reshard_inflight",))
        # snapshotted = acked + merged-away + shed, with the carryover,
        # the durable spool, and the in-flight send as inventory stocks
        self.ledger.declare(
            "forward", inputs=("forward.snapshot",),
            outputs=("forward.acked", "forward.merged_away",
                     "forward.shed"),
            stocks=("forward_carryover", "forward_spool",
                    "forward_inflight", "spool_quarantine"))
        # backfill plane (forward/backfill.py, receivers only): every
        # metric merged into a historical bucket is retired when its
        # bucket closes, with the open buckets as inventory — WAL
        # replay must not be able to lose state silently either
        self.ledger.declare(
            "backfill", inputs=("backfill.merged",),
            outputs=("backfill.closed",), stocks=("backfill_open",))
        if config.backfill_max_open_intervals > 0:
            # built here (not start()) so a manually-wired ImportServer
            # — the in-process test topology — finds the plane too
            from veneur_tpu.forward.backfill import BackfillPlane
            self.backfill = BackfillPlane(
                percentiles=self.percentiles,
                max_open=config.backfill_max_open_intervals,
                ledger=(self.ledger if self.ledger.enabled else None),
                on_event=self.telemetry.record_event)
            self.backfill_after_s = (config.wal_stale_after_intervals
                                     * self.interval)
            bf = self.backfill
            self.ledger.stock("backfill_open", lambda: bf.open_metrics)
            self.telemetry.registry.add_collector(bf.telemetry_rows)
        # cross-tier reconciliation: what this local acked against what
        # the receiver reports it received/merged (FlowCounts responses)
        self.ledger.declare(
            "forward_tier", inputs=("forward.acked_reported",),
            outputs=("forward.remote_merged", "forward.remote_rejected",
                     "forward.remote_deduped"))
        # the overlapped flush's in-flight snapshot (flush_async): an
        # interval swapped out of the tables but not yet delivered is
        # INVENTORY, not loss — booked as a stock so conservation stays
        # provable through the overlap (it is informational — the
        # ingest/forward identities note at apply/delivery time, which
        # both land inside one ledger interval)
        self.ledger.stock("flush_inflight_snapshot",
                          lambda: float(self._inflight_rows))
        self.latency.ledger = self.ledger if self.ledger.enabled else None
        self.ledger.trace_source = self.trace_plane.active_trace_hex
        self.telemetry.registry.add_collector(self.ledger.telemetry_rows)

        # self-metrics: UDP to stats_address, or internal loopback so they
        # re-enter this server's own pipeline (reference scopedstatsd +
        # NewChannelClient server.go:518-524)
        from veneur_tpu.util.scopedstatsd import NullClient, ScopedClient
        if config.stats_address == "internal":
            # explicit loopback: self-metrics re-enter this server
            self.statsd = ScopedClient(
                packet_cb=self._self_packet,
                scopes=config.veneur_metrics_scopes,
                additional_tags=config.veneur_metrics_additional_tags,
                registry=self.telemetry.registry)
        elif config.stats_address:
            self.statsd = ScopedClient(
                address=config.stats_address,
                scopes=config.veneur_metrics_scopes,
                additional_tags=config.veneur_metrics_additional_tags,
                registry=self.telemetry.registry)
        else:
            self.statsd = NullClient(registry=self.telemetry.registry)

        # self-tracing: every flush is a span through the internal channel
        # client into our own span pipeline (reference flusher.go:27-28);
        # its bounded buffer is instrumented like every other hand-off
        from veneur_tpu import trace as trace_mod
        self.trace_client = trace_mod.Client(
            trace_mod.ChannelBackend(self.ingest_span),
            capacity=config.span_channel_capacity,
            buffer=self.latency.instrument_queue(
                "trace_client", maxsize=config.span_channel_capacity),
            # every self-span also lands (synchronously, when its trace
            # is sampled) in the bounded trace store behind /debug/traces
            tee=self.trace_plane.record_proto)
        self.telemetry.registry.add_collector(self.latency.telemetry_rows)

        self.diagnostics = None
        if config.features.diagnostics_metrics_enabled:
            from veneur_tpu.core.diagnostics import DiagnosticsLoop
            self.diagnostics = DiagnosticsLoop(self.statsd, config.interval)

        # native batch ingest engine (None -> numpy columnar fallback)
        from veneur_tpu.core.ingest import BatchIngester, PyBatchIngester
        self._ingester = (None if config.tpu.disable_native_parser
                          else BatchIngester.create(self))
        # the numpy columnar decoder (core/batchdecode.py): same batch
        # pipeline — intern-table columnar parse, per-family add_batch,
        # batch admission — with the parse step in pure Python, so the
        # ingest speedup survives hosts without the C++ extension
        self._py_ingester = (PyBatchIngester(self)
                             if self._ingester is None else None)

        self.http_api = None  # set in start() when http_address
        self.profiler = None  # set in start() when enable_profiling
        self._warmup_thread = None  # set in start()
        self._listeners: List[networking.Listener] = []
        self._flush_lock = threading.Lock()
        # asynchronous flush pipeline (core/flushexec.py, flush_async):
        # in-flight interval records — swapped out, readouts running on
        # the background executor in submit order, joined+delivered by
        # subsequent flush ticks. Normally at most one deep; a wedged
        # readout lets it grow (bounded) so a transient device stall
        # carries completed intervals forward instead of dropping them.
        # All mutated under _flush_lock (plus shutdown's drain, which
        # flushes under the same lock).
        self._inflight_flushes: List[dict] = []
        self._flush_executor = None  # created on the first async flush
        # touched-row count of the in-flight snapshot: the ledger books
        # the swapped-but-undelivered interval as an inventory stock so
        # the overlap stays visible in /debug/ledger
        self._inflight_rows = 0
        self.prewarmer = None  # set in start() when prewarm_ladder
        # last flush thread per sink: a sink whose previous flush is still
        # running gets skipped — the hard cap is ONE concurrent flush
        # thread per sink, so a permanently hung sink costs one thread,
        # not one per interval
        self._sink_flush_threads: Dict[str, threading.Thread] = {}
        # consecutive skipped intervals per sink (the pileup depth a hung
        # sink would have caused without the cap); logged and exported
        self._sink_skip_depth: Dict[str, int] = {}
        # egress resilience: per-sink circuit breakers (shared
        # util/resilience.py implementation, same knobs as the forward
        # breaker) and the bounded one-interval spill of a failed metric
        # sink's InterMetric batch
        from veneur_tpu.util import chaos as chaos_mod
        from veneur_tpu.util.resilience import CircuitBreaker
        self._breaker_cls = CircuitBreaker
        self._sink_breakers: Dict[str, CircuitBreaker] = {}
        self._sink_spill: Dict[str, List[InterMetric]] = {}
        self.chaos = chaos_mod.Chaos.from_config(config)
        # ingest-side resilience: admission buckets, the ok/degraded/
        # shedding watermark ladder, kernel-drop polling, and the
        # pipeline supervisor (core/overload.py — PR 2's egress layer
        # mirrored onto ingest)
        from veneur_tpu.core.overload import OverloadManager
        self.overload = OverloadManager(
            config, chaos=self.chaos,
            on_transition=self._overload_transition,
            on_stall=self._supervisor_stall)
        self.telemetry.registry.add_collector(self.overload.telemetry_rows)
        # cardinality observatory (core/cardinality.py): heavy-hitter
        # series accounting fed from the column store's interning path,
        # per-tag-key HLL diagnosis of top offenders, and the
        # cardinality rung of the shed ladder (rejected mints land in
        # ingest.shed_total via overload.shed, reason:cardinality*)
        from veneur_tpu.core.cardinality import CardinalityAccountant
        self.cardinality = CardinalityAccountant(
            soft_limit=config.cardinality_soft_limit,
            hard_limit=config.cardinality_hard_limit,
            degraded_keep=config.cardinality_degraded_keep,
            top_k=config.cardinality_top_k,
            hll_names=config.cardinality_hll_names,
            hll_min_mints=config.cardinality_hll_min_mints,
            on_shed=self.overload.shed,
            on_event=self.telemetry.record_event)
        self.store.attach_cardinality(self.cardinality)
        # device observatory (core/deviceobs.py): HBM generation ledger,
        # kernel dispatch/compile registry, shard-balance scrape —
        # served at /debug/device, feeding the overload ladder's device
        # watermark rung and the shard_skew alert rule kind
        from veneur_tpu.core.deviceobs import DeviceObservatory
        self.deviceobs = DeviceObservatory(
            enabled=bool(getattr(config, "device_observatory", True)))
        if self.deviceobs.enabled:
            self.store.attach_deviceobs(self.deviceobs)
            self.telemetry.registry.add_collector(
                self.deviceobs.telemetry_rows)
            self.overload.attach_device_source(self.deviceobs.total_bytes)
        # persistent-compilation-cache probe state: entry counts
        # snapshotted at resize time, compared after the recompile
        self._cache_entries_at_resize: Dict[str, int] = {}
        self.store.attach_resize_hook(self._store_resize)
        self.telemetry.registry.add_collector(self.store.telemetry_rows)
        self.telemetry.registry.add_collector(
            self.cardinality.telemetry_rows)
        # live query plane (core/query.py): consistent read-only
        # captures of the live device generation, served between
        # flushes by GET /query and evaluated every tick by the alert
        # engine (core/alerts.py). Built here, not start(), so
        # in-process test topologies can query without an HTTP listener.
        from veneur_tpu.core.alerts import AlertEngine
        from veneur_tpu.core.query import LiveQueryPlane
        self.query_plane = LiveQueryPlane(self)
        self.telemetry.registry.add_collector(
            self.query_plane.telemetry_rows)
        self.alerts = AlertEngine(self, self.query_plane,
                                  interval_s=config.alerts.interval)
        try:
            self.alerts.configure(config.alerts.rules)
        except Exception:
            # a bad rule table must not keep the server down: start
            # with an empty table, loudly — SIGHUP reloads it once fixed
            logger.exception("invalid alerts.rules; starting with an "
                             "empty rule table")
        self.telemetry.registry.add_collector(self.alerts.telemetry_rows)
        # elastic reshard controller (parallel/reshard.py): live
        # digest-range migration N->M with a WAL-backed exactly-once
        # cutover. Built here (not start()) so in-process topologies
        # can drive begin()/recover() directly.
        from veneur_tpu.parallel.reshard import ReshardController
        self.reshard = ReshardController(self)
        self.ledger.stock("reshard_inflight",
                          self.reshard.inflight_metrics)
        self.telemetry.registry.add_collector(self.reshard.telemetry_rows)
        self._flush_thread: Optional[threading.Thread] = None
        self._watchdog_thread: Optional[threading.Thread] = None
        self._shutdown = threading.Event()
        # set once shutdown() completes, so a CLI embedding this server
        # can exit when /quitquitquit triggered the shutdown internally
        self.shutdown_complete = threading.Event()
        self.last_flush_unix = time.time()
        self.flush_count = 0
        # locked counters: increments arrive from many reader threads
        from veneur_tpu.util.stats import StatCounters
        self.stats = StatCounters(
            "packets_received", "parse_errors", "metrics_flushed",
            "tcp_overlong_dropped", "ssf_undecodable_dropped",
            "batches_dispatched")
        # ledger feeds from counters that already exist: parse errors
        # and the overload shed table surface as informational ingress
        # stages in /debug/ledger (per-interval deltas, folded at close)
        self.store.attach_ledger(self.ledger if self.ledger.enabled
                                 else None)
        self.ledger.probe("ingress.parse_errors",
                          lambda: self.stats["parse_errors"])
        self.ledger.probe_map("ingress.shed", self.overload.shed_snapshot)

    # -- identity --------------------------------------------------------

    @property
    def is_local(self) -> bool:
        return self.config.is_local

    # -- ingest ----------------------------------------------------------

    def handle_packet_batch(self, datagrams) -> None:
        """Fast path: parse a batch of datagrams through the columnar
        batch decoder (native C++, or the numpy fallback) straight into
        the column store. Chaos ingest faults (drop/truncate/duplicate)
        apply here; admission control gates the parsed BATCH — one
        token-bucket take whose cost is the batch's sample count, inside
        the ingester's apply path — and an over-limit batch still parses
        columnar, in essential-only mode (histogram/llhist/set columns
        shed with exact per-class counts, counter/gauge deltas kept)."""
        chaos = self.chaos
        if chaos is not None and chaos.ingest_faults_planned:
            datagrams = chaos.mangle_packets(datagrams)
        # sample-age stamp at the socket-read boundary, one per batch
        self.latency.note_arrival("dogstatsd", len(datagrams))
        ingester = self._ingester or self._py_ingester
        good = []
        for dgram in datagrams:
            if len(dgram) > self.config.metric_max_length:
                self.stats.inc("parse_errors")
            else:
                good.append(dgram)
        if good:
            ingester.ingest_buffer(b"\n".join(good))

    def handle_metric_packet(self, packet: bytes,
                             shed_nonessential: bool = False) -> None:
        """Dispatch one datagram/line (reference server.go:949-1000).
        With `shed_nonessential` (over-limit packet) histogram/set
        samples are shed; counter/gauge deltas are always kept."""
        self.stats.inc("packets_received")
        cb = (self._ingest_metric_essential if shed_nonessential
              else self.ingest_metric)
        try:
            if packet.startswith(b"_sc"):
                metric = self.parser.parse_service_check(packet)
                self.ingest_metric(metric)
            elif packet.startswith(b"_e{"):
                event = self.parser.parse_event(packet)
                with self._other_lock:
                    self._other_samples.append(event)
            else:
                self.parser.parse_metric_fast(packet, cb)
        except ParseError as e:
            self.stats.inc("parse_errors")
            logger.debug("could not parse packet %r: %s", packet[:100], e)

    def handle_packet_buffer(self, buf: bytes,
                             shed_nonessential: bool = False) -> None:
        """Newline-split a multi-metric datagram (server.go:1116-1140)."""
        if len(buf) > self.config.metric_max_length:
            self.stats.inc("parse_errors")
            return
        if not shed_nonessential and not self.overload.admit_statsd_packet():
            shed_nonessential = True
        for line in buf.split(b"\n"):
            if line:
                self.handle_metric_packet(
                    line, shed_nonessential=shed_nonessential)

    def ingest_metric(self, metric: UDPMetric) -> None:
        """The single Python-path chokepoint into the column store: the
        overload shed ladder applies here (histogram/set samples are
        shed under memory pressure; counter/gauge deltas never are).
        Samples that pass admission stamp the flow ledger's
        ingest.admitted — the in-side of the conservation identity the
        column store's applied/rejected stamps must balance. The
        chaos_ledger_leak seam sits between the stamp and the store:
        the deliberate silent drop the ledger must catch."""
        cls = _SHED_CLASS.get(metric.key.type)
        if cls is not None and not self.overload.admit_sample(cls):
            return
        led = self.ledger
        if led.enabled:
            led.note("ingest.admitted", 1, key="python")
            chaos = self.chaos
            if chaos is not None and chaos.leak_sample():
                return  # the drill: vanish with no accounting at all
        # exemplar capture: first sample per heavy-hitter/llhist name
        # per interval, stamped with the pre-minted interval trace id
        # (two set lookups when the name isn't interesting)
        if metric.value is not None:
            self.trace_plane.maybe_capture(
                metric.key.name, metric.value,
                always=metric.key.type == m.LLHIST)
        self.store.process(metric)

    def _ingest_metric_essential(self, metric: UDPMetric) -> None:
        """Essential-only intake for over-limit packets: histogram/set
        samples are shed (counted), counter/gauge deltas admitted."""
        cls = _SHED_CLASS.get(metric.key.type)
        if cls is not None and not self.overload.admit_sample(
                cls, over_limit=True):
            return
        led = self.ledger
        if led.enabled:
            led.note("ingest.admitted", 1, key="python")
        self.store.process(metric)

    def _self_packet(self, packet: bytes) -> None:
        """Loop a self-metric packet straight back into the parse path."""
        try:
            self.parser.parse_metric_fast(packet, self.ingest_metric)
        except ParseError:
            pass

    def _live_telemetry_rows(self):
        """Scrape-time /metrics rows for live counters the registry does
        not own: the locked ingest counters (which otherwise surface only
        as per-flush gauges) and span-pipeline drop totals."""
        rows = [(key if key.startswith("ingest") else f"ingest.{key}",
                 "counter", float(value), ())
                for key, value in self.stats.items()]
        rows.append(("ingest.spans_dropped", "counter",
                     float(self.spans_dropped), ()))
        # the trace CLIENT's silent drops (bounded buffer + buffered
        # backend), distinct from the span channel's ingest-side drops
        rows.append(("trace.spans_dropped", "counter",
                     float(self.trace_client.spans_dropped), ()))
        for worker in self._span_sink_workers:
            tags = [f"sink:{worker.sink.name()}"]
            rows.append(("ingest.span_sink_dropped", "counter",
                         float(worker.dropped), tags))
            rows.append(("ingest.span_sink_ingested", "counter",
                         float(worker.ingested), tags))
        rows.append(("flush.rounds", "counter", float(self.flush_count), ()))
        rows.append(("flush.last_unix_seconds", "gauge",
                     self.last_flush_unix, ()))
        # egress resilience: per-sink breaker state (0 closed / 1 open /
        # 2 half-open), pileup depth behind the 1-thread cap, and the
        # pending spill size
        for key, breaker in list(self._sink_breakers.items()):
            tags = [f"target:{key}"]
            rows.append(("resilience.breaker_state", "gauge",
                         float(breaker.state_code), tags))
            rows.append(("resilience.breaker_opens", "counter",
                         float(breaker.open_total), tags))
        for key, depth in list(self._sink_skip_depth.items()):
            rows.append(("flush.sink_pileup_depth", "gauge", float(depth),
                         [f"sink:{key}"]))
        for key, spill in list(self._sink_spill.items()):
            rows.append(("flush.spill_pending", "gauge", float(len(spill)),
                         [f"sink:{key}"]))
        return rows

    def _ring_telemetry_rows(self):
        """Scrape-time /metrics rows for the ingest SPSC rings: per
        reader, the ready-ring depth/capacity gauges plus sealed-chunk
        and reader-stall counters. (Ring dwell rides the observatory's
        queue.dwell llhists under the same ingest_ring names.)"""
        from veneur_tpu.core.ingest import addr_label
        rows = []
        for listener in list(getattr(self, "_listeners", ()) or ()):
            pump = getattr(listener, "pump", None)
            if pump is None:
                continue
            try:
                depths, caps, sealed, stalls = pump.ring_stats()
            except Exception:
                continue
            for i in range(len(depths)):
                tags = [f"ring:{addr_label(listener.address)}:{i}"]
                rows.append(("ingest.ring.depth", "gauge",
                             float(depths[i]), tags))
                rows.append(("ingest.ring.capacity", "gauge",
                             float(caps[i]), tags))
                rows.append(("ingest.ring.sealed_total", "counter",
                             float(sealed[i]), tags))
                rows.append(("ingest.ring.stalls_total", "counter",
                             float(stalls[i]), tags))
        return rows

    # -- spans -----------------------------------------------------------

    def handle_ssf_packet(self, packet: bytes) -> None:
        """One unframed SSF datagram (reference server.go:1053-1100)."""
        self.latency.note_arrival("ssf")
        self._handle_ssf_packet_stamped(packet)

    def _handle_ssf_packet_stamped(self, packet: bytes) -> None:
        """handle_ssf_packet minus the arrival stamp — the buffer path
        below stamps once per batch and must not re-stamp per packet."""
        from veneur_tpu import protocol
        self.stats.inc("packets_received")
        try:
            span = protocol.parse_ssf(packet)
        except Exception:
            self.stats.inc("parse_errors")
            logger.debug("could not parse SSF packet (%d bytes)", len(packet))
            return
        self.ingest_span(span)

    def handle_ssf_batch(self, packets) -> None:
        """A batch of unframed SSF datagrams; delegates to
        handle_ssf_buffer over their concatenation."""
        import numpy as np
        n = len(packets)
        if not n:
            return
        lens = np.fromiter((len(p) for p in packets), np.int64, n)
        offs = np.zeros(n, np.int64)
        if n > 1:
            np.cumsum(lens[:-1], out=offs[1:])
        self.handle_ssf_buffer(b"".join(packets), offs, lens)

    def handle_ssf_buffer(self, buf, offs, lens) -> None:
        """A batch of unframed SSF datagrams as a contiguous buffer with
        per-packet (offset, length) — the shape the native UDP reader
        produces. With the native library the spans decode and their
        metrics extract in C++ (SURVEY §2 native-components item 6); the
        span objects external sinks need are decoded lazily at worker
        pace (RawSpan), so sink-side decode cost rides the existing
        bounded-queue drop semantics instead of the ingest path."""
        self.latency.note_arrival("ssf", len(offs))
        ing = getattr(self, "_ingester", None)
        if ing is not None and not os.environ.get(
                "VENEUR_TPU_DISABLE_PUMP"):
            try:
                decoded = ing.ingest_ssf_buffer(buf, offs, lens)
            except Exception:
                # the native path may already have applied part of the
                # batch; replaying it through the Python path would
                # double-count, so the remainder is dropped (UDP
                # semantics) and the failure is loud
                logger.exception(
                    "native SSF buffer failed; dropping the batch "
                    "remainder to avoid double-counting")
                self.stats.inc("parse_errors", len(offs))
                return
            if self._span_sink_workers:
                # batch admission decides the span-OBJECT handoff only:
                # the native extraction above already ran, so the
                # counter/gauge deltas embedded in SSF samples are never
                # lost (extraction precedes the span channel on this
                # path, exactly as before admission control existed).
                # Admitting AFTER decode — and only when span sinks
                # exist — keeps tokens and shed counts tied to spans
                # that would actually have been handed off.
                import numpy as np
                idxs = np.nonzero(decoded)[0]
                if len(idxs) and self.overload.admit_spans(len(idxs)):
                    for i in idxs:
                        start = int(offs[i])
                        self.ingest_span(
                            RawSpan(buf[start:start + int(lens[i])]),
                            preadmitted=True)
            return
        for off, ln in zip(offs, lens):
            # already stamped above, once for the whole batch
            self._handle_ssf_packet_stamped(buf[int(off):int(off) + int(ln)])

    def ingest_span(self, span, preadmitted: bool = False) -> None:
        """Enqueue a span for the worker pool; drops (and counts) when the
        channel is saturated rather than blocking ingest. Spans are the
        FIRST rung of the overload shed ladder: any degradation state
        (or an exhausted span-plane token bucket) sheds them here —
        `preadmitted` spans already passed batch admission upstream."""
        if not preadmitted and not self.overload.admit_span():
            return
        try:
            self.span_chan.put_nowait(span)
        except queue.Full:
            self.spans_dropped += 1

    def _span_worker_loop(self) -> None:
        """Fan spans out to every span sink (worker.go:587-662): metric
        extraction runs inline (internal, cannot hang); external sinks
        receive spans through their isolation buffers so one hung sink
        can't stall the pipeline. Spans are drained and fanned out in
        batches — one submit_many per sink per batch instead of per-span
        queue traffic. On shutdown, drains queued spans (which sit ahead
        of the None sentinels) before exiting; the timed get covers the
        case where a full channel swallowed the sentinels."""
        from veneur_tpu import protocol
        beat = self.overload.supervisor.beat
        name = threading.current_thread().name
        while True:
            beat(name)
            try:
                first = self.span_chan.get(timeout=0.5)
            except queue.Empty:
                if self._shutdown.is_set():
                    return
                continue
            if first is None:
                return
            batch = [first]
            done = False
            try:
                while len(batch) < 256:
                    nxt = self.span_chan.get_nowait()
                    if nxt is None:  # consume at most ONE sentinel so
                        done = True  # sibling workers still get theirs
                        break
                    batch.append(nxt)
            except queue.Empty:
                pass
            out = []
            for span in batch:
                if isinstance(span, RawSpan):
                    # metrics were already extracted natively; only
                    # external sinks need the decoded object
                    try:
                        out.append(protocol.parse_ssf(span.data))
                    except Exception:
                        pass  # native decode succeeded; should not happen
                else:
                    try:
                        self.metric_extraction.ingest(span)
                    except Exception:
                        logger.exception("span metric extraction failed")
                    out.append(span)
            if out:
                for worker in self._span_sink_workers:
                    worker.submit_many(out)
            if done:
                return

    # -- lifecycle -------------------------------------------------------

    def enable_compilation_cache(self) -> bool:
        """Point JAX's persistent compilation cache at the configured
        directory (no-op without one): a crash-restart-replay cycle
        (SIGUSR2 handoff, WAL recovery) comes up with warm kernels from
        disk instead of paying the full retrace tax mid-recovery.
        Thresholds zeroed: restart warmth is the point, so every
        compile is worth caching. Returns True when enabled."""
        cache_dir = self.config.jax_compilation_cache_dir
        if not cache_dir:
            return False
        try:
            import jax
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", -1)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0)
            self.telemetry.record_event(
                "compilation_cache_enabled", directory=cache_dir,
                entries=max(0, self._compile_cache_entries()))
            return True
        except Exception:
            logger.exception("could not enable the persistent JAX "
                             "compilation cache")
            return False

    def start(self) -> None:
        from veneur_tpu.util.crash import guarded
        self.enable_compilation_cache()
        for sink in self.metric_sinks + self.span_sinks:
            sink.start(self)
        for sink in self.span_sinks:
            if sink is self.metric_extraction:
                continue
            worker = _SpanSinkWorker(
                sink, self.config.span_sink_queue_capacity,
                observatory=self.latency)
            worker.start()
            self._span_sink_workers.append(worker)
        for i in range(max(1, self.config.num_span_workers)):
            t = threading.Thread(target=guarded(self._span_worker_loop),
                                 name=f"span-worker-{i}", daemon=True)
            self.overload.supervisor.register(t.name)
            t.start()
            self._span_workers.append(t)
        for addr in self.config.statsd_listen_addresses:
            self._listeners.extend(networking.start_statsd(
                addr, self, num_readers=self.config.num_readers,
                rcvbuf=self.config.read_buffer_size_bytes))
        for addr in self.config.ssf_listen_addresses:
            self._listeners.extend(networking.start_ssf(
                addr, self, rcvbuf=self.config.read_buffer_size_bytes))
        if self.config.forward_address and self.forwarder is None:
            from veneur_tpu.forward.client import ForwardClient
            from veneur_tpu.util.grpctls import GrpcTLS
            from veneur_tpu.util.resilience import (Carryover, RetryPolicy)
            fwd_tls = GrpcTLS(
                certificate=self.config.forward_tls_certificate,
                key=(self.config.forward_tls_key.reveal()
                     if self.config.forward_tls_key else ""),
                authority=self.config.forward_tls_authority_certificate)
            cfg = self.config
            # durable carryover spill: with a spool dir configured,
            # carryover past its bound serializes to disk instead of
            # shedding; segments left by a previous process (crash or
            # SIGUSR2 handoff mid-outage) are re-scanned here and drain
            # after the first successful forward
            spool = None
            ledger = self.ledger if self.ledger.enabled else None
            if cfg.carryover_spool_dir:
                from veneur_tpu.util.spool import CarryoverSpool
                spool = CarryoverSpool(
                    cfg.carryover_spool_dir,
                    max_bytes=cfg.carryover_spool_max_bytes,
                    max_segments=cfg.carryover_spool_max_segments,
                    quarantine_max_bytes=(
                        cfg.carryover_spool_quarantine_max_bytes),
                    quarantine_max_segments=(
                        cfg.carryover_spool_quarantine_max_segments),
                    dwell_hist=self.latency.queue_hist("forward_spool"),
                    ledger=ledger)
                self.latency.register_queue(
                    "forward_spool", lambda: spool.depth,
                    cfg.carryover_spool_max_segments)
                self.telemetry.record_event(
                    "spool_attached", directory=cfg.carryover_spool_dir,
                    wal=cfg.forward_wal,
                    replayed_segments=spool.replayed_total)
            replay_limiter = None
            if cfg.forward_wal and cfg.wal_replay_rate_limit > 0:
                from veneur_tpu.core.overload import TokenBucket
                replay_limiter = TokenBucket(
                    cfg.wal_replay_rate_limit,
                    cfg.wal_replay_rate_limit * cfg.wal_replay_burst)
            self.forward_client = ForwardClient(
                cfg.forward_address, deadline=self.interval,
                tls=fwd_tls or None,
                retry=RetryPolicy(
                    max_attempts=cfg.forward_retry_max_attempts,
                    base_delay=cfg.forward_retry_base,
                    max_delay=cfg.forward_retry_max),
                breaker=self._breaker_cls(
                    failure_threshold=cfg.circuit_breaker_failure_threshold,
                    recovery_time=cfg.circuit_breaker_recovery,
                    name="forward", on_transition=self._breaker_transition),
                carryover=Carryover(cfg.carryover_max_intervals,
                                    ledger=ledger),
                chaos=self.chaos, spool=spool, ledger=ledger,
                trace_plane=self.trace_plane,
                wal=cfg.forward_wal, replay_limiter=replay_limiter,
                replay_stale_after=(cfg.wal_stale_after_intervals
                                    * self.interval),
                shards=(self.store.shard_plane.n
                        if self.store.shard_plane is not None else 0))
            self.forwarder = self.forward_client.forward
            self.telemetry.registry.add_collector(
                self.forward_client.telemetry_rows)
            # the forward plane's bounded hand-off: failed intervals
            # queue in the carryover (depth in intervals, not items)
            self.latency.register_queue(
                "forward_carryover",
                lambda: self.forward_client.carryover.depth,
                cfg.carryover_max_intervals)
            # ledger inventory stocks: metrics held in the carryover,
            # on disk in the spool (incl. segments replayed from a dead
            # process — opening stock, not unexplained inflow), and
            # in flight inside a send — so a close landing mid-outage
            # (or mid-send) still balances
            fc = self.forward_client
            self.ledger.stock("forward_carryover",
                              lambda: fc.carryover.pending_metrics)
            self.ledger.stock("forward_inflight",
                              lambda: fc.inflight_metrics)
            if spool is not None:
                self.ledger.stock("forward_spool",
                                  lambda: spool.pending_metrics)
                # quarantined segments are set ASIDE, not shed: the
                # metrics stay booked as inventory until the quarantine
                # bound purges them (explained shed at that point)
                self.ledger.stock("spool_quarantine",
                                  lambda: spool.quarantined_metrics)
        if self.chaos is not None:
            # make the plan visible to the object-less seams (http_post)
            from veneur_tpu.util import chaos as chaos_mod
            chaos_mod.install(self.chaos)
            self.telemetry.registry.add_collector(self.chaos.telemetry_rows)
            self.telemetry.record_event(
                "chaos_enabled", error_rate=self.chaos.error_rate,
                delay_rate=self.chaos.delay_rate,
                seams=sorted(self.chaos.seams))
        for addr in self.config.grpc_listen_addresses:
            from veneur_tpu.core.grpc_ingest import GrpcIngestServer
            gi = GrpcIngestServer(self, addr)
            gi.start()
            self.grpc_ingest_servers.append(gi)
        if self.config.grpc_address:
            from veneur_tpu.forward.server import ImportServer
            from veneur_tpu.util.grpctls import GrpcTLS
            from veneur_tpu.util.matcher import TagMatcher
            ignored = [TagMatcher(kind="prefix", value=t)
                       for t in self.config.tags_exclude]
            grpc_tls = GrpcTLS(
                certificate=self.config.grpc_tls_certificate,
                key=(self.config.grpc_tls_key.reveal()
                     if self.config.grpc_tls_key else ""),
                authority=self.config.grpc_tls_authority_certificate)
            self.import_server = ImportServer(
                self, self.config.grpc_address, ignored_tags=ignored,
                tls=grpc_tls or None)
            # hedge/retry duplicate drops surface in /metrics
            self.telemetry.registry.add_collector(
                self.import_server.telemetry_rows)
            imp = self.import_server
            self.ledger.probe("import.deduped",
                              lambda: imp.duplicates_dropped_total,
                              key="forward")
            self.import_server.start()
        for source in self.sources:
            t = threading.Thread(target=source.start, args=(self,),
                                 name=f"source-{source.name()}", daemon=True)
            t.start()
            self._source_threads.append(t)
        if self.config.http_address:
            from veneur_tpu.core.httpapi import HTTPApi
            self.http_api = HTTPApi(
                self.config, server=self, address=self.config.http_address,
                http_quit=self.config.http_quit, on_quit=self.shutdown)
            self.http_api.start()
        if self.config.enable_profiling:
            # continuous all-threads CPU sampler from startup (reference
            # server.go:1382-1390), readable at /debug/profile/cpu
            from veneur_tpu.core.profiling import StackSampler
            self.profiler = StackSampler()
            self.profiler.start()
        if self.config.profile_server_port:
            from veneur_tpu.core.profiling import start_profile_server
            start_profile_server(self.config.profile_server_port)
        if self.config.block_profile_rate or self.config.mutex_profile_fraction:
            logger.warning(
                "block_profile_rate/mutex_profile_fraction are Go-runtime "
                "knobs with no Python analog; accepted for config compat "
                "only — use /debug/pprof and enable_profiling instead")
        # pre-compile the flush kernels off the ticker path so the first
        # real flush isn't delayed by XLA compilation (~20-40s on TPU);
        # kept as an attribute so callers that pre-load the store (bench,
        # tests) can join it before measuring
        self._warmup_thread = threading.Thread(
            target=self._warmup, name="kernel-warmup", daemon=True)
        self._warmup_thread.start()
        if self.config.prewarm_ladder:
            # shape-ladder prewarmer (core/flushexec.py): compile each
            # family's NEXT capacity rung in the background so resizes
            # never retrace on the hot path; fed by the resize hook
            from veneur_tpu.core.flushexec import ShapeLadderPrewarmer
            self.prewarmer = ShapeLadderPrewarmer(
                self.store, percentiles=self.percentiles,
                need_export=(self.is_local and self.forwarder is not None),
                on_event=self.telemetry.record_event)
            self.telemetry.registry.add_collector(
                self.prewarmer.telemetry_rows)
            self.prewarmer.start()
            self.prewarmer.prewarm_initial()
        if self.diagnostics is not None:
            self.diagnostics.start()
        # replay range segments an interrupted reshard cutover left
        # behind — before the flush loop starts, so the recovered rows
        # land in the first interval and the ledger books them cleanly
        try:
            self.reshard.recover()
        except Exception:
            logger.exception("reshard recovery failed; segments left "
                             "in place for the next start")
        self._flush_thread = threading.Thread(
            target=guarded(self._flush_loop), name="flush-ticker",
            daemon=True)
        # the flush loop beats once per interval, so its deadline must
        # clear the interval no matter how tight the global deadline is
        # — and floors at 60s because a first flush legitimately blocks
        # on XLA compilation for tens of seconds (the flush watchdog and
        # the readiness ladder are the tight-bound wedge detectors for
        # this component; the supervisor is its long-stop)
        self.overload.supervisor.register(
            "flush-loop", deadline=max(
                self.overload.supervisor.deadline, 2.5 * self.interval,
                60.0))
        self._flush_thread.start()
        if self.config.alerts.enabled:
            # alert evaluation loop: supervised like every pipeline
            # thread, with a generous deadline — one tick's capture
            # rides the shared readout executor and can queue behind a
            # seconds-long flush readout
            self.overload.supervisor.register(
                "alert-loop", deadline=max(
                    self.overload.supervisor.deadline,
                    10 * self.alerts.interval_s, 60.0))
            self.alerts.start()
        self.overload.start()
        if self.config.flush_watchdog_missed_flushes > 0:
            self._watchdog_thread = threading.Thread(
                target=self._flush_watchdog, name="flush-watchdog", daemon=True)
            self._watchdog_thread.start()
        # graceful-restart handshake: a parent mid-SIGUSR2 waits for the
        # ready file before it drains — written only now, with every
        # listener bound, so a wedged startup never wins a handoff
        from veneur_tpu.core import restart
        restart.mark_ready()
        startup = {"pid": os.getpid(),
                   "mode": "local" if self.is_local else "global"}
        if self.store.shard_plane is not None:
            # mesh topology in the flight recorder: which devices this
            # store partitioned over, under which routing policy
            startup["mesh"] = self.store.shard_plane.describe()
        self.telemetry.record_event("startup", **startup)

    def local_addr(self, scheme: str = "udp"):
        for listener in self._listeners:
            if listener.scheme == scheme:
                return listener.address
        return None

    def _breaker_transition(self, name: str, old: str, new: str) -> None:
        """Flight-recorder hook for every breaker edge (forward + sinks)."""
        self.telemetry.record_event(
            "breaker_transition", target=name, old=old, new=new)

    def _sink_breaker(self, key: str):
        """Get-or-create the per-sink breaker (same knobs as forward)."""
        breaker = self._sink_breakers.get(key)
        if breaker is None:
            breaker = self._sink_breakers[key] = self._breaker_cls(
                failure_threshold=
                self.config.circuit_breaker_failure_threshold,
                recovery_time=self.config.circuit_breaker_recovery,
                name=key, on_transition=self._breaker_transition)
        return breaker

    def _overload_transition(self, old: str, new: str, rss: int) -> None:
        """Flight-recorder + log hook for every watermark ladder edge."""
        self.telemetry.record_event(
            "overload_state", old=old, new=new, rss_bytes=rss)

    def _supervisor_stall(self, component: str, age: float) -> None:
        """Flight-recorder hook for every freshly-detected stall."""
        self.telemetry.record_event(
            "pipeline_stall", component=component,
            heartbeat_age_s=round(age, 3))

    def _compile_cache_entries(self) -> int:
        """Entry count of the persistent JAX compilation cache dir
        (-1 = cache disabled/unreadable) — the hit/miss probe: a
        recompile that ADDED entries was a miss, one that didn't was
        served from disk."""
        cache_dir = self.config.jax_compilation_cache_dir
        if not cache_dir:
            return -1
        try:
            return sum(1 for name in os.listdir(cache_dir)
                       if name.endswith("-cache"))
        except OSError:
            return -1

    def _store_resize(self, family: str, old_cap: int, new_cap: int,
                      seconds: float, kind: str = "resize",
                      prewarmed: bool = False) -> None:
        """Flight-recorder hook for every column-store capacity doubling
        (kind=resize: the array re-layout, fired under the table's
        buffer lock — event recording only, never statsd) and for the
        first post-resize batch apply (kind=recompile: the jit retrace
        the new capacity forces, the TPU-specific cost — or, when the
        shape-ladder prewarmer compiled this rung ahead of time, a warm
        dispatch tagged `prewarmed`)."""
        cache = None
        if kind == "resize":
            self._cache_entries_at_resize[family] = \
                self._compile_cache_entries()
            if self.prewarmer is not None:
                # queue the rung AFTER the one just reached, so the
                # next doubling is already compiled when it lands
                self.prewarmer.note_resize(family, new_cap)
        elif kind == "recompile":
            before = self._cache_entries_at_resize.pop(family, -1)
            after = self._compile_cache_entries()
            if before >= 0 and after >= 0:
                cache = "miss" if after > before else "hit"
            if prewarmed and cache != "hit":
                # the shape ladder compiled this rung ahead of the
                # resize: the timed "recompile" window was a warm
                # dispatch, not a retrace
                cache = "prewarmed"
        self.telemetry.record_event(
            f"columnstore_{kind}", family=family, old_capacity=old_cap,
            new_capacity=new_cap, duration_s=round(seconds, 6),
            **({"compile_cache": cache} if cache else {}),
            **({"prewarmed": True} if prewarmed else {}))
        if kind == "recompile":
            # tag the next flush round's waterfall: recompile cost must
            # be separable from steady-state execute cost (and, with
            # the persistent cache on, whether disk served it)
            self.latency.note_retrace(family, seconds, cache=cache)

    def device_report(self) -> dict:
        """The /debug/device payload: the HBM generation ledger (by
        family / lifecycle state, with forecast and backend
        reconciliation), the kernel dispatch/compile registry, the
        shard-balance observatory, and the overload ladder's device
        watermark rung."""
        out = self.deviceobs.report()
        dw = self.overload.device_watermarks
        out["watermarks"] = {
            "state": dw.state,
            "soft_bytes": dw.soft_bytes,
            "hard_bytes": dw.hard_bytes,
            "last_bytes": dw.last_rss,
            "transitions": dw.transitions,
        }
        return out

    def adopt_flush_trace(self, trace_id: int, parent_span_id: int) -> None:
        """Called by the import server when a fresh (non-duplicate)
        forwarded payload carries trace metadata: this GLOBAL's next
        flush span parents under the originating local's interval trace
        (latest import wins — hedged duplicates were already deduped by
        token before reaching here, so exactly one import per payload
        adopts). Only the latch is written here: the flush itself calls
        set_active() when it consumes the adoption, so an import landing
        DURING a flush can't retarget the trace id that flush's ledger
        close and event stamps are about to read."""
        self._adopted_trace = (int(trace_id), int(parent_span_id))

    def cardinality_report(self, top: int = 20, name: str = "") -> dict:
        """The /debug/cardinality payload. With `name`, a single-name
        drill-down (exact per-family rows + tag-key HLL estimates);
        otherwise the top-N names by live series, per-table capacity/
        churn stats, and the watermark state. The per-name scan is
        capacity-proportional — operator-triggered only."""
        if name:
            detail = self.cardinality.name_report(name)
            exact = self.store.live_rows_by_name().get(name)
            if exact is not None:
                detail.update(exact)
            else:
                detail.setdefault("live_rows", 0)
            return detail
        per_name = self.store.live_rows_by_name()
        tracked = {r["name"]: r for r in self.cardinality.top(top)}
        # candidates = top names by exact live rows UNION the tracker's
        # top by mint activity: a hard-capped storm offender has few
        # ADMITTED rows (the cap is working), but its mint rate is the
        # very thing the operator came to see — ranking by live rows
        # alone would hide it behind any large steady keyset
        by_rows = sorted(
            per_name, key=lambda nm: (per_name[nm]["live_rows"],
                                      per_name[nm]["touched_rows"]),
            reverse=True)[:max(0, top)]
        top_list = []
        for nm in set(by_rows) | set(tracked):
            row = {"name": nm}
            row.update(per_name.get(
                nm, {"live_rows": 0, "touched_rows": 0, "families": {}}))
            rec = tracked.get(nm)
            if rec is not None:
                for field in ("mints_interval", "mints_last_interval",
                              "mint_rate_per_s", "shed_total"):
                    row[field] = rec[field]
            tag_report = self.cardinality.tag_report(nm)
            if tag_report is not None:
                row["tags"] = tag_report
            top_list.append(row)
        top_list.sort(
            key=lambda r: (r["live_rows"] + r.get("mints_interval", 0)
                           + r.get("mints_last_interval", 0)),
            reverse=True)
        del top_list[max(0, top):]
        return {
            "generated_unix": round(time.time(), 3),
            "interval_s": round(self.cardinality.interval_s, 3),
            "total_names": len(per_name),
            "tables": self.store.capacity_report(),
            "top": top_list,
            "limits": self.cardinality.limits_report(),
        }

    def ready_state(self):
        """(ready, reason) for /healthcheck/ready: not ready while the
        overload ladder is shedding, or while the flush watchdog's
        budget is blown (a wedged flush loop means this instance is
        about to abort — orchestrators should stop routing to it)."""
        if self.overload.state == overload_mod.SHEDDING:
            return False, (f"overload state {overload_mod.SHEDDING} "
                           f"(rss {self.overload.watermarks.last_rss} bytes)")
        if self.reshard.past_deadline():
            # a cutover past its deadline means the topology swap is
            # wedged (prewarm hung, device link down) — stop routing
            # to this instance until it completes or is abandoned
            return False, (f"reshard past deadline: state "
                           f"{self.reshard.state}, deadline "
                           f"{self.reshard.deadline_unix:.0f}")
        if self.config.flush_watchdog_missed_flushes > 0:
            allowed = self.config.flush_watchdog_missed_flushes * self.interval
            since = time.time() - self.last_flush_unix
            if since > allowed:
                return False, (f"flush watchdog tripped: no flush for "
                               f"{since:.1f}s (allowed {allowed:.1f}s)")
        return True, ""

    def reload_alerts(self, config_path: Optional[str] = None) -> int:
        """SIGHUP hot-reload of the `alerts:` block: re-read the config
        file (when the process has one), swap the rule table in place —
        in-flight state machines survive for rule ids present in both
        tables — and record the reload in the flight recorder. Returns
        the new rule count; raises (keeping the old table) on a bad
        rule, so a fat-fingered reload can't silence a firing alert."""
        rules = self.config.alerts.rules
        interval_s = self.config.alerts.interval
        if config_path:
            from veneur_tpu.config import read_config
            fresh = read_config(config_path)
            rules = fresh.alerts.rules
            interval_s = fresh.alerts.interval
            self.config.alerts = fresh.alerts
        n = self.alerts.configure(rules, interval_s=interval_s)
        self.telemetry.record_event("alerts_reload", rules=n,
                                    interval_s=round(interval_s, 3))
        logger.info("alerts reloaded: %d rule(s), interval %.3fs",
                    n, interval_s)
        return n

    def shutdown(self) -> None:
        self.telemetry.record_event("shutdown", pid=os.getpid())
        self._shutdown.set()
        # stop supervision first: pipeline threads exiting on the
        # shutdown path must not be flagged (or escalated) as stalls
        self.overload.stop()
        # stop the alert loop before anything drains: its captures ride
        # the shared readout executor the flush path stops below
        self.alerts.stop()
        if self.chaos is not None:
            # only clear the global seam if WE installed this plan (two
            # servers in one test process chaos independently)
            from veneur_tpu.util import chaos as chaos_mod
            if chaos_mod.active() is self.chaos:
                chaos_mod.install(None)
        # stop pull sources first (bound-join) so an in-flight scrape
        # can't ingest after the final flush below
        for source in self.sources:
            source.stop()
        for t in self._source_threads:
            t.join(timeout=2.0)
        # close listeners BEFORE the final flush so everything received
        # up to the moment of shutdown is aggregated and flushed: close()
        # joins the native pump readers, and the bounded thread joins
        # below let the pump dispatcher / Python readers drain their
        # in-flight buffers into the column store
        for listener in self._listeners:
            listener.close()
        for listener in self._listeners:
            for t in listener._threads:
                # generous bound: a pump-dispatcher drain can hit a cold
                # XLA compile; normal exit is well under a second
                t.join(timeout=15.0)
        # sentinels wake idle workers promptly; a full channel is fine —
        # workers also poll the shutdown event every 0.5s
        for _ in self._span_workers:
            try:
                self.span_chan.put_nowait(None)
            except queue.Full:
                break
        # let workers drain in-flight spans before the final flush
        for t in self._span_workers:
            t.join(timeout=2.0)
        for worker in self._span_sink_workers:
            worker.stop()
        if self.config.flush_on_shutdown:
            # full final flush: _flush_locked runs synchronously here
            # (shutdown is set), so the in-flight async readout AND the
            # final partial interval both deliver before exit
            self.flush()
        elif self.config.flush_async:
            # flush_on_shutdown is OFF (the operator opted out of
            # partial-interval emission), but an interval already
            # SWAPPED for async readout is complete, committed data —
            # join and deliver it (WAL append + forward + sinks)
            # without opening a new interval boundary. The SIGUSR2
            # handoff relies on this to stay loss-free. Gated on
            # flush_async itself, not a racy _inflight_flush read: a
            # ticker tick mid-swap right now submits its readout
            # before releasing _flush_lock, and the deliver-only pass
            # serializes behind it there and joins what it submitted.
            with self._flush_lock:
                self._flush_locked(deliver_only=True)
        if self._flush_executor is not None:
            self._flush_executor.stop()
        if self.prewarmer is not None:
            self.prewarmer.stop()
        if self.import_server is not None:
            self.import_server.stop()
        for gi in self.grpc_ingest_servers:
            gi.stop()
        if self.http_api is not None:
            self.http_api.stop()
            self.http_api = None
        if self.profiler is not None:
            self.profiler.stop()
        if self.forward_client is not None:
            self.forward_client.close()
            # retire the forward plane's observatory queues with their
            # owner so /debug/latency reflects only live hand-offs
            self.latency.unregister_queue("forward_carryover")
            self.ledger.unstock("forward_carryover")
            self.ledger.unstock("forward_inflight")
            if self.forward_client.spool is not None:
                self.latency.unregister_queue("forward_spool")
                self.ledger.unstock("forward_spool")
                self.ledger.unstock("spool_quarantine")
        if self.backfill is not None:
            self.ledger.unstock("backfill_open")
        if self.diagnostics is not None:
            self.diagnostics.stop()
        self.trace_client.close()
        self.statsd.close()
        for sink in self.metric_sinks + self.span_sinks:
            sink.stop()
        self.shutdown_complete.set()

    # -- flush -----------------------------------------------------------

    def _tick_delay(self) -> float:
        """Clock-aligned tick (reference server.go:1458 CalculateTickDelay)."""
        interval = self.interval
        now = time.time()
        return interval - (now % interval)

    def _flush_loop(self) -> None:
        beat = self.overload.supervisor.beat
        while not self._shutdown.is_set():
            delay = (self._tick_delay() if self.config.synchronize_with_interval
                     else self.interval)
            if self._shutdown.wait(delay):
                return
            beat("flush-loop")
            try:
                self.flush()
            except Exception:
                logger.exception("flush failed")
            # beat on completion too: a slow-but-finishing flush (cold
            # compile) clears its staleness the moment it lands
            beat("flush-loop")

    def _flush_watchdog(self) -> None:
        """Die loudly if flushes stall (reference server.go:877-919)."""
        allowed = self.config.flush_watchdog_missed_flushes * self.interval
        while not self._shutdown.wait(self.interval):
            since = time.time() - self.last_flush_unix
            self.telemetry.record_event(
                "watchdog_tick", since_last_flush_s=round(since, 3),
                allowed_s=allowed)
            if since > allowed:
                logger.critical(
                    "flush watchdog: no flush for %ds; aborting", allowed)
                self.telemetry.record_event(
                    "watchdog_abort", since_last_flush_s=round(since, 3))
                import faulthandler
                import os
                faulthandler.dump_traceback(all_threads=True)
                os._exit(2)

    def _warmup(self) -> None:
        """Compile the flush kernels against a throwaway store with the same
        array shapes; never touches (or resets) live state."""
        try:
            cfg = self.config
            scratch = ColumnStore(
                counter_capacity=cfg.tpu.counter_capacity,
                gauge_capacity=cfg.tpu.gauge_capacity,
                histo_capacity=cfg.tpu.histo_capacity,
                set_capacity=cfg.tpu.set_capacity,
                batch_cap=cfg.tpu.batch_cap,
                shard_devices=cfg.tpu.shards,
                pallas_flush=cfg.tpu.pallas_tdigest_flush,
                llhist_capacity=cfg.tpu.llhist_capacity,
                histogram_encoding=cfg.histogram_encoding,
                shard_routing=cfg.tpu.shard_routing)
            # collect_forward must match the live flush's value: need_export
            # selects between two distinct JIT specializations (fold_staging
            # is a static arg), and warming the wrong one would leave the
            # first real flush paying the full compile
            flush_columnstore_batch(
                scratch, self.is_local, self.percentiles, self.aggregates,
                collect_forward=self.forwarder is not None)
        except Exception:
            logger.exception("kernel warmup failed")

    def flush(self) -> None:
        """One flush pass (reference flusher.go:26-122)."""
        with self._flush_lock:
            self._flush_locked()

    def _flush_locked(self, deliver_only: bool = False) -> None:
        from veneur_tpu import trace as trace_mod
        from veneur_tpu.trace.store import trace_id_hex
        flush_start = time.perf_counter()
        self.last_flush_unix = time.time()
        # the interval this flush's snapshot covers began at the
        # previous flush boundary: the WAL stamps it onto the
        # forwardable snapshot so a replay lands under THIS interval
        interval_start = self._interval_start_unix
        self._interval_start_unix = self.last_flush_unix
        self.flush_count += 1
        # the flush span IS the interval trace root: a local roots it on
        # the plane's pre-minted interval trace id (the same id ingest-
        # time exemplars stamped all interval), a global parents it
        # under the originating local's trace when a fresh import
        # adopted one this interval — that is what makes local flush ->
        # proxy.route -> import.merge -> global sink ack ONE trace
        plane = self.trace_plane
        adopted, self._adopted_trace = self._adopted_trace, None
        tags = {"mode": "local" if self.is_local else "global",
                "interval": str(self.flush_count)}
        if adopted and not self.is_local:
            flush_span = trace_mod.Span(
                self.trace_client, "flush", "veneur-tpu",
                trace_id=adopted[0], parent_id=adopted[1], tags=tags)
        else:
            flush_span = trace_mod.Span(
                self.trace_client, "flush", "veneur-tpu",
                trace_id=plane.interval_trace_id, tags=tags)
        traced = plane.is_sampled(flush_span.trace_id)
        plane.set_active(flush_span.trace_id if traced else 0)

        if self.config.count_unique_timeseries:
            # exact count of timeseries touched this interval (reference
            # flusher.go:43 flush.unique_timeseries_total)
            self.statsd.count(
                "flush.unique_timeseries_total",
                self.store.unique_timeseries(),
                tags=[f"global_veneur:{str(not self.is_local).lower()}"])

        with self._other_lock:
            samples, self._other_samples = self._other_samples, []
        # events/service checks are delivered inside each sink's bounded
        # flush thread below — flush_other_samples is a vendor network
        # call (e.g. datadog events POST) and used to run inline here,
        # where one hung endpoint stalled the whole flush loop

        # every per-sink flush (span and metric) runs in its own thread and
        # the whole pass is bounded by one interval — the reference's
        # context deadline (server.go:869, flusher.go:553-566). A sink
        # whose previous flush is still running is skipped this interval,
        # so a hung sink costs its own data, never the flush loop or
        # another sink's. Each sink's outcome (duration, error, skipped,
        # timed-out) lands in this round's flight-recorder entry.
        threads: List[threading.Thread] = []
        round_info = {
            "flush": self.flush_count,
            "start_unix": self.last_flush_unix,
            "mode": "local" if self.is_local else "global",
            "sinks": {},
        }
        if traced:
            # cross-link: /debug/flush (and its waterfall view) point at
            # the interval's /debug/traces entry
            round_info["trace_id"] = trace_id_hex(flush_span.trace_id)

        def _start_sink_thread(key: str, target, *args,
                               parent_span=None,
                               span_traced=None) -> bool:
            """Dispatch one sink flush thread; returns False when the
            interval was NOT dispatched (skip or open breaker) so the
            forward path can stash its state into carryover instead of
            dropping it. `parent_span`/`span_traced` re-home the sink's
            child span under the interval trace whose data is being
            delivered (an async round delivers the PREVIOUS interval's
            readout — its spans must parent there, not here)."""
            if parent_span is None:
                parent_span = flush_span
            if span_traced is None:
                span_traced = traced
            prev = self._sink_flush_threads.get(key)
            if prev is not None and prev.is_alive():
                # hard cap: one concurrent flush thread per sink. The
                # depth counts what the pileup WOULD be if each interval
                # re-created a thread against the hung sink.
                depth = self._sink_skip_depth.get(key, 0) + 1
                self._sink_skip_depth[key] = depth
                logger.warning(
                    "sink %s: previous flush still running; skipping "
                    "(pileup depth %d, capped at 1 thread)", key, depth)
                self.statsd.count("flush.sink_skipped_total", 1,
                                  tags=[f"sink:{key}"])
                round_info["sinks"][key] = {"status": "skipped",
                                            "duration_s": 0.0,
                                            "pileup_depth": depth}
                # every skipped interval is a delivery failure the hung
                # thread will never report; feeding the breaker here is
                # what takes a permanently-down sink to OPEN. The
                # forward path is exempt: ForwardClient owns its own
                # breaker (which stashes to carryover instead of
                # dropping), and two breakers on one series would fight
                # over the /metrics gauge.
                if key != "forward":
                    self._sink_breaker(key).record_failure()
                self.telemetry.record_event(
                    "sink_skipped", sink=key, flush=round_info["flush"],
                    pileup_depth=depth)
                return False
            self._sink_skip_depth.pop(key, None)
            if key != "forward" and not self._sink_breaker(key).allow():
                # open breaker: don't even spawn the thread — a sick
                # sink's interval is dropped (counted) until the
                # half-open probe closes it again
                self.statsd.count("flush.sink_breaker_open_total", 1,
                                  tags=[f"sink:{key}"])
                round_info["sinks"][key] = {"status": "breaker_open",
                                            "duration_s": 0.0}
                self.telemetry.record_event(
                    "sink_breaker_open", sink=key,
                    flush=round_info["flush"])
                return False
            t = threading.Thread(
                target=self._timed_sink_flush,
                args=(key, parent_span, span_traced, round_info,
                      target) + args,
                daemon=True, name=f"flush-{key}")
            t.start()
            self._sink_flush_threads[key] = t
            threads.append(t)
            return True

        for sink in self.span_sinks:
            _start_sink_thread(
                f"span:{sink.name()}", self._flush_span_sink_safe, sink)

        # per-phase wall clock for flush-latency attribution; read by
        # the bench's sustained gate (one flush at a time: _flush_lock)
        phases = self.flush_phase_timings = {}
        # sample-age watermarks roll at the same boundary the column
        # store snapshots: everything stamped before this flush's
        # snapshot is aged through to sink ack below
        watermarks = self.latency.take_watermarks()
        # flush_async: swap the interval out (O(1) per table), hand the
        # readout to the background executor, and DELIVER the previous
        # interval's joined readout — dispatch/sync/transfer leave the
        # critical path entirely. Shutdown drains synchronously so the
        # in-flight snapshot and the final interval both land.
        async_on = (bool(self.config.flush_async)
                    and not self._shutdown.is_set()
                    and not deliver_only)
        t_store = time.perf_counter()
        record = None
        if not deliver_only:
            swap = swap_columnstore(
                self.store, self.is_local, self.percentiles,
                collect_forward=self.forwarder is not None,
                timings=phases)
            record = {
                "swap": swap,
                "flush": self.flush_count,
                "interval_start": interval_start,
                "watermarks": watermarks,
                "span": flush_span,
                "traced": traced,
            }
        # join the in-flight readouts, oldest first: the head had a
        # whole interval to finish, so this is normally a no-op wait —
        # the only store wall time left on the critical path. A head
        # that is NOT done (transient device stall) is CARRIED to the
        # next tick after a short grace rather than dropped — its data
        # is a completed, committed interval; only a readout that stays
        # wedged past READOUT_MISS_LIMIT ticks (or fails outright) is
        # dropped, loudly. Shutdown drains with the full timeout.
        from concurrent.futures import TimeoutError as _JoinTimeout
        t_join = time.perf_counter()
        drain = deliver_only or self._shutdown.is_set()
        inflight = self._inflight_flushes
        delivered = []
        while inflight:
            head = inflight[0]
            head["async"] = True
            try:
                head["result"] = head["pending"].result(
                    timeout=(max(self.interval, 60.0) if drain
                             else min(5.0, max(1.0, self.interval / 4))))
            except _JoinTimeout:
                if not drain:
                    misses = head["join_misses"] = \
                        head.get("join_misses", 0) + 1
                    if misses < self.READOUT_MISS_LIMIT:
                        # carry to the next tick; deliver nothing more
                        break
                logger.error(
                    "flush readout for interval %s wedged%s; dropping "
                    "it", head.get("flush"),
                    " at shutdown" if drain else
                    f" for {head['join_misses']} ticks")
                self.statsd.count("flush.readout_failed_total", 1)
                inflight.pop(0)
                continue
            except Exception:
                logger.exception(
                    "in-flight flush readout failed; interval %s lost",
                    head.get("flush"))
                self.statsd.count("flush.readout_failed_total", 1)
                inflight.pop(0)
                continue
            inflight.pop(0)
            delivered.append(head)
        phases["join_s"] = time.perf_counter() - t_join
        inline_device_s = 0.0
        if deliver_only:
            pass  # shutdown drain: no new interval boundary is opened
        elif async_on:
            record["pending"] = self._readout_executor().submit(
                lambda rec=record: self._run_readout(rec))
            inflight.append(record)
        else:
            record["result"] = self._run_readout(record)
            r_phases = record["result"][2]
            # device work that DID run inline this tick — subtracted
            # from the critical-path row below
            inline_device_s = sum(
                r_phases.get(k, 0.0)
                for k in ("dispatch_s", "device_sync_s", "assembly_s"))
            delivered.append(record)
        # the ledger's overlap stock: touched rows across every swapped-
        # but-undelivered interval still in the pipeline
        self._inflight_rows = sum(r["swap"]["rows"] for r in inflight)
        phases["store_flush_s"] = time.perf_counter() - t_store
        phases["preflush_s"] = t_store - flush_start
        round_info["async"] = async_on

        def _deliver_round(rec, other_samples, primary: bool) -> int:
            """Fan one joined/inline readout out to the forward plane
            and the metric sinks; returns its metric count. Only the
            PRIMARY (first) round's readout phases land in this tick's
            series — a drain tick delivering two intervals must not mix
            one interval's phase totals with another's family segments
            in the recorded round."""
            batch, fwd, r_phases = rec["result"]
            rec_span, rec_traced = rec["span"], rec["traced"]
            # readout phases land in this round's series (one interval
            # late under overlap — the bench gate reads distributions)
            if primary:
                for k, v in r_phases.items():
                    if isinstance(v, (int, float)) or k in ("mesh",
                                                            "families"):
                        phases[k] = v
            self.stats.inc("metrics_flushed", len(batch))
            # flush-stage ledger rows (informational): what the
            # delivered interval's snapshot produced
            self.ledger.note("flush.emitted", len(batch))
            self.ledger.note("flush.forward_rows", len(fwd))

            # dispatch even with an empty snapshot when a previous
            # interval's failed state is pending (in carryover OR the
            # durable spool) — otherwise a quiet interval would strand
            # it until new traffic arrives
            pending_carryover = (
                self.forward_client is not None
                and (self.forward_client.carryover.depth > 0
                     or (self.forward_client.spool is not None
                         and self.forward_client.spool.depth > 0)))
            if self.is_local and self.forwarder is not None and (
                    len(fwd) or pending_carryover):
                # flow ledger: everything snapshotted for the forward
                # plane is owed an outcome (ack / merge-away / shed /
                # inventory)
                self.ledger.note("forward.snapshot", len(fwd))
                if not _start_sink_thread(
                        "forward", self._forward_safe, fwd,
                        rec["interval_start"], parent_span=rec_span,
                        span_traced=rec_traced) \
                        and self.forward_client is not None and len(fwd):
                    # undispatched interval (previous forward still
                    # hung): the snapshot is mergeable state, so it
                    # carries over exactly like a failed send instead
                    # of being dropped
                    self.forward_client.carryover.stash(fwd)
                    self.statsd.count("flush.forward_undispatched_total",
                                      1)

            if self._routing is not None:
                # routing annotates per-metric sink sets, so it needs
                # objects; materialize once here and every sink thread
                # shares the list
                for metric in batch.materialize():
                    route = set()
                    for rule in self._routing:
                        route.update(rule.route(metric.name, metric.tags))
                    metric.sinks = route

            for sink in self.metric_sinks:
                key = f"metric:{sink.name()}"
                # per-sink gate: another sink's pending spill must not
                # dispatch this one — a no-op flush would still
                # thread-spawn and (worse) count as a probe against
                # this sink's breaker
                if len(batch) or other_samples or key in self._sink_spill:
                    _start_sink_thread(
                        key, self._flush_sink_safe, key, sink, batch,
                        other_samples, parent_span=rec_span,
                        span_traced=rec_traced)
            return len(batch)

        delivered_metrics = 0
        for i, rec in enumerate(delivered):
            # events/service checks belong to THIS tick: they ride the
            # first delivery round only (a drain tick delivers two)
            delivered_metrics += _deliver_round(
                rec, samples if i == 0 else (), primary=(i == 0))
            if i + 1 < len(delivered):
                # drain tick delivering two intervals: the one-thread-
                # per-sink cap means round 2 must wait for round 1's
                # threads — ONE shared grace across all of them, not a
                # fresh timeout per thread (N wedged sinks would
                # otherwise stall shutdown for N x grace)
                inter_deadline = (time.perf_counter()
                                  + max(self.interval, 30.0))
                for t in threads:
                    remaining = inter_deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    t.join(remaining)
        if not delivered and (samples or self._sink_spill):
            # empty-delivery tick (first async tick, or a failed/timed-
            # out readout join): events/service checks still deliver on
            # time, and sinks with a pending one-interval spill get
            # their retry — an empty tick must not starve either
            empty = FlushBatch(int(self.last_flush_unix), [], [])
            for sink in self.metric_sinks:
                key = f"metric:{sink.name()}"
                if samples or key in self._sink_spill:
                    _start_sink_thread(key, self._flush_sink_safe, key,
                                       sink, empty, samples)

        # bounded wait: one interval from flush start, minus time already
        # spent; stragglers keep running on their daemon threads and are
        # skipped next interval if still alive. The shutdown flush gets a
        # generous grace instead, so the final interval's metrics are
        # delivered before daemon threads die with the process.
        grace = (max(self.interval, 30.0) if self._shutdown.is_set()
                 else self.interval)
        deadline = flush_start + grace
        t_join = time.perf_counter()
        for t in threads:
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                break
            t.join(remaining)
        phases["sink_join_s"] = time.perf_counter() - t_join
        stuck = [t.name for t in threads if t.is_alive()]
        if stuck:
            logger.error(
                "flush exceeded the %.1fs interval; still running: %s",
                self.interval, ", ".join(stuck))
            self.statsd.count("flush.timeout_total", len(stuck))
            for name in stuck:
                key = name[len("flush-"):]
                # the sink thread holds the same outcome dict: if it
                # lands after this round is recorded, its final status
                # overwrites timed_out (flagged `late`)
                entry = round_info["sinks"].setdefault(key, {})
                entry.setdefault("status", "timed_out")
                # a hang is a failure the sink thread will never report
                # itself: feed the breaker here so a permanently-down
                # sink ends at ONE live thread + an OPEN breaker instead
                # of silent per-interval skips (forward exempt: the
                # client's breaker + carryover own that path)
                if key != "forward":
                    self._sink_breaker(key).record_failure()
                self.telemetry.record_event(
                    "sink_timeout", sink=key, flush=round_info["flush"])

        if self.import_server is not None:
            # per-RPC latency/error aggregates (reference proxy/grpcstats)
            self.import_server.rpc_stats.emit(self.statsd, prefix="import.rpc")
        # sink joins are the ack point: everything dispatched this round
        # has been delivered (or timed out, recorded above) — the moment
        # the DELIVERED interval's samples stop aging. Under overlap the
        # delivered watermarks are the previous interval's, so the age
        # honestly includes the pipeline's one-interval delivery delay.
        ack_unix = time.time()
        # retrace tags drain ONCE per tick and land on the first
        # delivered families tree (on a drain tick delivering two
        # intervals, that is the async/previous one — the interval the
        # pending recompile actually preceded)
        retraces = self.latency.drain_retraces()
        families = None
        for rec in delivered:
            self.latency.observe_sample_age(rec["watermarks"], ack_unix)
            if rec["traced"] and rec["watermarks"]:
                # anchor the delivered interval's worst-case staleness
                # to ITS trace: the pipeline.sample_age rows in /metrics
                # carry an OpenMetrics exemplar pointing at that flush
                oldest = min(mark[0] for mark in rec["watermarks"].values())
                self.trace_plane.exemplars.capture(
                    "pipeline.sample_age", max(0.0, ack_unix - oldest),
                    rec["span"].trace_id, ts=ack_unix)
            rec_families = rec["result"][2].get("families")
            if rec_families:
                for family, (secs, cache) in retraces.items():
                    frec = rec_families.get(family)
                    if frec is not None:
                        frec["retrace"] = True
                        frec["recompile_s"] = round(secs, 6)
                        if cache:
                            frec["compile_cache"] = cache
                retraces = {}
                if rec.get("async"):
                    # waterfall: these segments ran on the background
                    # executor — render as the parallel (async) lane
                    for frec in rec_families.values():
                        frec["lane"] = "async"
                # async readout spans still parent under the ORIGINATING
                # interval's flush span, stamped with the readout's own
                # wall-clock base (not this tick's)
                self._record_family_spans(
                    rec["span"], families=rec_families,
                    base_unix=rec.get("readout_start_unix"))
                if families is None:
                    # the round's waterfall tree shows the FIRST
                    # delivered interval's segments (the async one on a
                    # drain tick), paired with its flush id — never a
                    # mix of two intervals' evidence
                    families = rec_families
                    if rec.get("async"):
                        round_info["delivered_flush"] = rec["flush"]
        flush_span.finish()
        duration = time.perf_counter() - flush_start
        # the join-only critical path: total wall minus whatever device
        # readout ran INLINE this tick (zero under flush_async — the
        # acceptance row proving dispatch/sync/transfer left the path)
        critical_path = max(0.0, duration - inline_device_s)
        phases["critical_path_s"] = critical_path
        self.statsd.timing("flush.critical_path_s", critical_path)
        self.statsd.gauge("flush.total_duration_ns", int(duration * 1e9))
        self.statsd.timing("flush.total_duration", duration)
        for phase, secs in phases.items():
            if isinstance(secs, (int, float)):
                self.statsd.timing("flush.phase_duration", secs,
                                   tags=[f"phase:{phase}"])
        self.statsd.count("flush.metrics_total", delivered_metrics)
        round_info["duration_s"] = round(duration, 6)
        round_info["metrics_flushed"] = delivered_metrics
        round_info["phases"] = {k: round(v, 6) for k, v in phases.items()
                                if isinstance(v, (int, float))}
        if families:
            round_info["families"] = _round_family_tree(families)
        self.telemetry.flushes.record(round_info)
        self.telemetry.record_event(
            "flush", flush=round_info["flush"],
            duration_s=round_info["duration_s"],
            metrics=delivered_metrics,
            phases=round_info["phases"],
            sinks={k: v.get("status", "running")
                   for k, v in round_info["sinks"].items()})
        # cumulative process counters emit as gauges (they never reset)
        self.statsd.gauge("worker.metrics_processed_total",
                          int(self.stats["packets_received"]))
        span_sink_drops = 0
        for w in self._span_sink_workers:
            span_sink_drops += w.dropped
            if w.dropped or w.ingested:
                # per-sink shed visibility: drop RATE is the signal that
                # a sink's buffer is undersized for the offered load
                self.statsd.gauge("worker.ssf.sink.dropped_total",
                                  w.dropped,
                                  tags=[f"sink:{w.sink.name()}"])
                self.statsd.gauge("worker.ssf.sink.ingested_total",
                                  w.ingested,
                                  tags=[f"sink:{w.sink.name()}"])
        if self.spans_dropped or span_sink_drops:
            self.statsd.gauge("worker.ssf.spans_dropped_total",
                              self.spans_dropped + span_sink_drops)
        self._reclaim_idle_rows()
        # interval close for the flow ledger: fold the probe deltas,
        # read the inventory stocks, run every conservation check. In
        # strict mode (tests) an imbalance raises out of flush(); in
        # production it exports ledger.imbalance and records an event.
        if self.ledger.enabled:
            ledger_record = self.ledger.close_interval()
            round_info["ledger"] = ledger_record.get("imbalance", {})
        # interval-trace rollover LAST (the ledger close above stamps
        # this interval's trace id): mint the next interval's id, reset
        # the exemplar capture budget, and refresh the watched
        # heavy-hitter names from the cardinality observatory
        self.trace_plane.roll(
            [rec["name"] for rec in self.cardinality.top(16)])

    def _run_readout(self, record: dict):
        """The background half of one flush (runs on the flush-readout
        executor under flush_async, inline otherwise): drain the swapped
        generations — kernel dispatch, device sync, transfer, assembly —
        plus the backfill drain, whose metrics carry their ORIGINAL
        timestamps and so lose nothing by riding the next delivery.
        Returns (batch, fwd, readout_phases)."""
        record["readout_start_unix"] = time.time()
        r_phases: dict = {}
        batch, fwd = readout_columnstore(
            self.store, record["swap"], self.is_local, self.aggregates,
            collect_forward=self.forwarder is not None,
            timings=r_phases, attribute=self.latency.enabled)
        if self.backfill is not None:
            # closed historical buckets flush alongside the live
            # interval, each series timestamped at its ORIGINAL
            # interval start — backfilled history, not a traffic spike
            backfilled = self.backfill.drain()
            if backfilled:
                batch.extras.extend(backfilled)
                self.statsd.count("flush.backfilled_series_total",
                                  len(backfilled))
        if self.is_local and self.forwarder is not None and len(fwd):
            # wire-encode the forward payload HERE, on the readout
            # executor, so serialization overlaps sink delivery — the
            # forward thread later finds fwd.wire pre-built and skips
            # straight to the POST. Carryover merges invalidate it.
            t0 = time.perf_counter()
            from veneur_tpu.forward.convert import forwardable_to_wire
            try:
                fwd.wire = forwardable_to_wire(fwd)
            except Exception:
                fwd.wire = None  # forward thread re-encodes
                logger.exception("forward pre-encode failed")
            r_phases["forward_encode_s"] = time.perf_counter() - t0
        return batch, fwd, r_phases

    def _readout_executor(self):
        """Get-or-create the background flush executor (flush_async),
        supervised like the flush loop itself — a wedged readout (hung
        device link mid-transfer) trips the same stall ladder."""
        if self._flush_executor is None:
            from veneur_tpu.core.flushexec import FlushReadoutExecutor
            self.overload.supervisor.register(
                "flush-readout", deadline=max(
                    self.overload.supervisor.deadline,
                    2.5 * self.interval, 60.0))
            self._flush_executor = FlushReadoutExecutor(
                beat=self.overload.supervisor.beat)
        return self._flush_executor

    def _reclaim_idle_rows(self) -> None:
        """Idle-key reclamation + intern-table self-metrics, once per
        flush: tombstoned rows lose their native intern mappings
        immediately; their ids are recycled by the tables one flush later
        (columnstore._BaseTable.reclaim_idle). Bounds host memory under
        key churn (the reference instead resets ALL sampler state every
        interval, worker.go:470-489)."""
        from veneur_tpu import native

        idle = self.config.tpu.idle_key_intervals
        store = self.store
        tables = (
            (store.counters, native.FAM_COUNTER),
            (store.gauges, native.FAM_GAUGE),
            (store.histos, native.FAM_HISTO),
            (store.sets, native.FAM_SET),
            (store.llhists, native.FAM_LLHIST),
            (store.statuses, None),  # never registered natively
        )
        # intern-table sweep target: the C++ engine, or the numpy
        # fallback decoder (same unregister_rows_multi contract)
        engine = (self._ingester._engine
                  if getattr(self, "_ingester", None) is not None
                  else getattr(self, "_py_ingester", None))
        if idle > 0:
            pairs = []
            for table, family in tables:
                try:
                    evicted = table.reclaim_idle(idle)
                except Exception:
                    logger.exception("idle-row reclamation failed")
                    continue
                if evicted and family is not None:
                    pairs.extend((family, row) for row in evicted)
            if pairs and engine is not None:
                # one combined intern-table sweep per flush: the pump
                # readers block on the shared lock once, not per family
                engine.unregister_rows_multi(pairs)
        self.statsd.gauge(
            "intern.rows_total",
            sum(len(t.rows) for t, _f in tables))
        if engine is not None:
            self.statsd.gauge("intern.native_table_size", engine.size())
        dropped = sum(t.keys_dropped for t, _f in tables)
        if dropped > self._keys_dropped_reported:
            self.statsd.count("intern.keys_dropped_total",
                              dropped - self._keys_dropped_reported)
            self._keys_dropped_reported = dropped
        # interval rollover AFTER reclaim so eviction-driven live-count
        # decrements land in the interval they happened in; this resets
        # the per-name mint budgets (the shed rung's immediate recovery)
        self.cardinality.roll_interval()

    def _record_family_spans(self, flush_span, families: dict,
                             base_unix: float = None) -> None:
        """Matching child spans under the flush span, one per family
        device segment tree: the span's start/end reconstruct the
        measured dispatch->transfer window (the reference ships its own
        observability as SSF spans; so does the waterfall). `base_unix`
        anchors the segment offsets at the READOUT's wall-clock start —
        an async readout runs after its interval's flush span finished,
        and stamping it off this tick's flush time would both misplace
        the segments and parent them under the wrong interval's trace."""
        base = base_unix if base_unix is not None else (
            self.last_flush_unix + self.flush_phase_timings.get(
                "preflush_s", 0.0))
        for family, rec in families.items():
            start_off = rec.get("dispatch_start_s", 0.0)
            end_off = start_off + rec.get("dispatch_s", 0.0)
            dev_start = rec.get("device_start_s")
            if dev_start is not None:
                end_off = dev_start + rec.get("transfer_s", 0.0) + sum(
                    d.get("sync_s", 0.0)
                    for d in rec.get("devices", {}).values())
            tags = {"family": family,
                    "dispatch_s": f"{rec.get('dispatch_s', 0.0):.6f}",
                    "transfer_s": f"{rec.get('transfer_s', 0.0):.6f}"}
            for dev, seg in rec.get("devices", {}).items():
                tags[f"sync_s.{dev}"] = f"{seg.get('sync_s', 0.0):.6f}"
            if rec.get("retrace"):
                tags["retrace"] = "true"
                tags["recompile_s"] = f"{rec.get('recompile_s', 0.0):.6f}"
                if rec.get("compile_cache"):
                    tags["compile_cache"] = rec["compile_cache"]
            child = flush_span.child("flush.family", tags=tags)
            child.proto.start_timestamp = int((base + start_off) * 1e9)
            child.finish(end_time=base + end_off)

    def _timed_sink_flush(self, key: str, parent_span, span_traced,
                          round_info: dict, target, *args) -> None:
        """Body of one per-sink flush thread: a child span under the
        DELIVERED interval's flush span (an async round delivers the
        previous interval's readout — its sink spans parent there),
        wall-clock duration, the sink-outcome row shared with the
        flight recorder, and the per-sink duration self-metric."""
        outcome = round_info["sinks"].setdefault(key, {})
        child = parent_span.child("flush.sink", tags={"sink": key})
        # make this sink's span the ambient parent for the duration of
        # the flush call (each sink thread has its own context): the
        # forward client reads it to inject (trace_id, span_id) gRPC
        # metadata, which is how the interval trace crosses the tier.
        # Gated on the delivered round being traced so unsampled
        # intervals add no metadata downstream.
        ctx_token = None
        if span_traced:
            from veneur_tpu.trace import context as trace_ctx
            ctx_token = trace_ctx._current_span.set(child)
        start = time.perf_counter()
        try:
            ok = target(*args)
        finally:
            if ctx_token is not None:
                from veneur_tpu.trace import context as trace_ctx
                trace_ctx._current_span.reset(ctx_token)
        duration = time.perf_counter() - start
        was_timed_out = outcome.get("status") == "timed_out"
        breaker = self._sink_breakers.get(key)
        # ok is None when the sink was never exercised (nothing to
        # deliver): feeding the breaker then would let a quiet interval
        # reset a sick sink's failure streak or close its half-open
        # breaker without a real probe. A hung flush that finally fails
        # also stays silent — the deadline sweep already counted that
        # delivery failure, and counting it twice would open the breaker
        # after ~threshold/2 sick intervals.
        if breaker is not None and ok is not None:
            if ok:
                # a late success after a timed_out round still closes
                # the breaker — the sink proved it can deliver again
                breaker.record_success()
            elif not was_timed_out:
                breaker.record_failure()
        if ok is False:
            child.error()
        child.finish()
        if was_timed_out:
            # finished after its round was declared over — keep that
            # visible while still landing the real outcome
            outcome["late"] = True
        outcome["status"] = "error" if ok is False else "ok"
        outcome["duration_s"] = round(duration, 6)
        self.statsd.timing(
            "flush.sink_duration", duration,
            tags=[f"sink:{key}", f"status:{outcome['status']}"])
        if ok is False:
            self.telemetry.record_event(
                "sink_error", sink=key, flush=round_info["flush"],
                duration_s=outcome["duration_s"])
        if key == "forward":
            self.telemetry.record_event(
                "forward", status=outcome["status"],
                flush=round_info["flush"],
                duration_s=outcome["duration_s"])

    def _forward_safe(self, fwd: ForwardableState,
                      interval_start: float = 0.0) -> bool:
        try:
            if self._forwarder_takes_interval():
                self.forwarder(fwd, interval_start)
            else:
                # duck-typed forwarder predating the interval stamp
                self.forwarder(fwd)
            return True
        except Exception:
            logger.exception("forward failed")
            return False

    def _forwarder_takes_interval(self) -> bool:
        """Signature-based capability check (NOT a TypeError catch: a
        TypeError from inside the forwarder must never re-invoke it —
        in WAL mode a second call would append the same snapshot under
        a second token and double-merge)."""
        import inspect
        try:
            sig = inspect.signature(self.forwarder)
            params = list(sig.parameters.values())
        except (TypeError, ValueError):
            return True  # builtins/partials: assume the full contract
        if any(p.kind == p.VAR_POSITIONAL for p in params):
            return True
        positional = [p for p in params
                      if p.kind in (p.POSITIONAL_ONLY,
                                    p.POSITIONAL_OR_KEYWORD)]
        return len(positional) >= 2

    def _flush_span_sink_safe(self, sink) -> bool:
        try:
            sink.flush()
            return True
        except Exception:
            logger.exception("span sink %s flush failed", sink.name())
            return False

    def _flush_sink_safe(self, key: str, sink, batch: FlushBatch,
                         other_samples=()) -> Optional[bool]:
        """Returns True/False for a delivery attempt, None when the sink
        was never exercised (nothing to flush) — None must not feed the
        sink's breaker."""
        ok = True
        if other_samples:
            try:
                sink.flush_other_samples(other_samples)
            except Exception:
                logger.exception("sink %s flush_other_samples failed",
                                 sink.name())
                ok = False
        # bounded retry spill: a batch that failed LAST interval gets
        # exactly one more delivery attempt, prepended to this one
        spill = self._sink_spill.pop(key, None)
        if spill:
            self.statsd.count("flush.spill_retry_total", len(spill),
                              tags=[f"sink:{key}"])
        if not len(batch) and not spill:
            return ok if other_samples else None
        name = sink.name()
        sc = self._sink_filters.get(name)
        current: Optional[List[InterMetric]] = None
        try:
            if self.chaos is not None:
                self.chaos.inject("sink_flush")
            if sc is None and self._routing is None and not spill:
                # columnar fast path: no per-sink filtering, no routing
                # annotations, no spill to prepend, so the sink sees the
                # batch directly (the default flush_batch materializes;
                # blackhole and friends never do). getattr: duck-typed
                # sinks that only implement flush() still work.
                fb = getattr(sink, "flush_batch", None)
                if fb is not None:
                    fb(batch)
                else:
                    sink.flush(batch.materialize())
                self.ledger.note("egress.acked", len(batch), key=name)
                return ok
            selected = [mm for mm in batch.materialize()
                        if mm.sinks is None or name in mm.sinks]
            if sc is not None:
                selected = _apply_sink_filters(selected, sc)
            current = selected
            sink.flush(spill + selected if spill else selected)
            self.ledger.note("egress.acked",
                             len(selected) + len(spill or ()), key=name)
            return ok
        except Exception:
            logger.exception("sink %s flush failed", sink.name())
            # keep THIS interval's metrics for one retry next interval;
            # a spill that just failed its retry is shed (loudly) so the
            # buffer never exceeds one interval of data
            if spill:
                self.statsd.count("flush.spill_shed_total", len(spill),
                                  tags=[f"sink:{key}"])
                self.ledger.note("egress.shed", len(spill), key=key)
                logger.error(
                    "sink %s: shedding %d spilled metrics after a failed "
                    "retry (one-interval spill bound)", key, len(spill))
            if current is None:
                # failed before per-sink selection (chaos seam, filter
                # error): spill only this sink's routed+filtered share,
                # or the next interval would deliver it metrics that
                # routing excluded — and double-deliver them elsewhere
                try:
                    current = [mm for mm in batch.materialize()
                               if mm.sinks is None or name in mm.sinks]
                    if sc is not None:
                        current = _apply_sink_filters(current, sc)
                except Exception:
                    logger.exception(
                        "sink %s: selection failed while spilling; "
                        "shedding the interval", key)
                    current = []
            if current:
                self._sink_spill[key] = current
                self.ledger.note("egress.spilled", len(current), key=key)
            return False


def _round_family_tree(families: dict) -> dict:
    """Round the flusher's per-family segment tree for the flight
    recorder / waterfall JSON (floats to µs precision, structure kept)."""
    out = {}
    for family, rec in families.items():
        entry = {k: (round(v, 6) if isinstance(v, float) else v)
                 for k, v in rec.items() if k != "devices"}
        entry["devices"] = {
            dev: {k: round(v, 6) for k, v in seg.items()}
            for dev, seg in rec.get("devices", {}).items()}
        out[family] = entry
    return out


def _apply_sink_filters(metrics: List[InterMetric], sc: SinkConfig
                        ) -> List[InterMetric]:
    """Per-sink filtering: max name/tag limits, strip/add tags
    (reference flusher.go:138-213)."""
    from veneur_tpu.util.matcher import TagMatcher
    strip = [TagMatcher.from_config(t) for t in sc.strip_tags]
    out = []
    for metric in metrics:
        if sc.max_name_length and len(metric.name) > sc.max_name_length:
            continue
        tags = metric.tags
        if strip:
            tags = [t for t in tags
                    if not any(sm.match(t) for sm in strip)]
        if sc.add_tags:
            tags = sorted(set(tags) | {
                f"{k}:{v}" if v else k for k, v in sc.add_tags.items()})
        if sc.max_tag_length and any(len(t) > sc.max_tag_length for t in tags):
            continue
        if sc.max_tags and len(tags) > sc.max_tags:
            continue
        if tags is not metric.tags:
            metric = InterMetric(
                name=metric.name, timestamp=metric.timestamp,
                value=metric.value, tags=tags, type=metric.type,
                message=metric.message, hostname=metric.hostname,
                sinks=metric.sinks, backfilled=metric.backfilled)
        out.append(metric)
    return out
