"""Zero-gap graceful restart: the einhorn SIGUSR2 handoff rebuilt
without a socket master (reference server.go:1404, README.md:170-178).

The reference hands live fds to a replacement through einhorn. Here the
same zero-downtime property comes from SO_REUSEPORT: every UDP/TCP
listener (and the HTTP API) binds with it, so on SIGUSR2 this process
spawns a replacement from its own argv, the replacement binds the same
addresses while the old one still serves, and once the replacement
reports ready (/healthcheck/ready) the old process shuts down — the
listeners close, the native ingest pump drains, and (with
flush_on_shutdown) the partial interval flushes. At no point is there no
listener on the port.

Caveats, by design: UNIX-path listeners rebind with a brief gap
(filesystem binds are exclusive); the handoff interval's counters are
split across two flushes (they sum correctly downstream — same property
as the reference's handoff).
"""

from __future__ import annotations

import logging
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

logger = logging.getLogger("veneur_tpu.restart")

READY_TIMEOUT_S = 60.0
# env var through which the replacement reports "listeners bound": the
# child writes its pid to this path at the end of Server.start(). Used
# when no HTTP readiness endpoint is configured — a merely-alive child
# wedged in startup must NOT win the handoff (draining for it leaves
# the port unserved, worse than refusing the restart).
READY_FILE_ENV = "VENEUR_TPU_READY_FILE"


_in_progress = threading.Lock()


def install(shutdown, http_address: str = "", argv=None) -> None:
    """Handle SIGUSR2 with a spawn-replacement-then-drain handoff.

    Explicit contract (no server duck-typing): `shutdown` is called once
    the replacement is ready; `http_address` is the readiness endpoint
    the replacement will serve. Without an http_address the handoff
    falls back to a ready-file handshake (the replacement writes its pid
    once its listeners are bound, Server.start()); a replacement that
    never reports bound — even one still alive — loses the handoff and
    the old process keeps serving. Must be called from the main thread
    (signal module contract)."""
    if not http_address:
        logger.info(
            "graceful restart installed without a readiness endpoint: "
            "SIGUSR2 handoffs will use the ready-file handshake "
            "(replacement reports once its listeners are bound)")

    def handler(signum, frame):
        if not _in_progress.acquire(blocking=False):
            logger.warning("SIGUSR2 ignored: a handoff is in progress")
            return

        def run():
            try:
                _restart(shutdown, http_address, argv)
            finally:
                _in_progress.release()

        t = threading.Thread(target=run, name="graceful-restart",
                             daemon=True)
        t.start()

    signal.signal(signal.SIGUSR2, handler)


def mark_ready() -> None:
    """Report "listeners bound" to a parent mid-SIGUSR2 handoff: write
    our pid to the ready file it named in the environment. Called by
    Server.start() and the proxy CLI once every listener is up; a no-op
    outside a handoff."""
    ready_file = os.environ.pop(READY_FILE_ENV, "")
    if not ready_file:
        return
    # popped above: the handshake is single-use — inheriting the env var
    # would make descendants re-create the (by then unlinked) /tmp path
    # with open('w') later, the symlink-following TOCTOU the mkstemp in
    # _restart exists to avoid
    try:
        with open(ready_file, "w") as f:
            f.write(str(os.getpid()))
    except OSError:
        logger.exception("could not write restart ready-file")


def respawn_argv(argv=None):
    argv = list(sys.argv if argv is None else argv)
    if argv and os.access(argv[0], os.X_OK) and not argv[0].endswith(".py"):
        return argv  # console-script shim: exec it directly
    # `python -m veneur_tpu.cmd.veneur`: argv[0] is the module FILE, and
    # re-running it as a script would lose the package on sys.path —
    # respawn through -m with the original module name instead
    import __main__
    spec = getattr(__main__, "__spec__", None)
    if spec is not None and spec.name:
        return [sys.executable, "-m", spec.name] + argv[1:]
    return [sys.executable] + argv


def _restart(shutdown, http_address: str, argv) -> None:
    cmd = respawn_argv(argv)
    logger.info("SIGUSR2: spawning replacement process: %s", cmd)
    ready_file = ""
    env = None
    if not http_address:
        import tempfile
        # the mkstemp-owned (0600) file stays in place — unlinking and
        # letting the child re-create the path would hand a
        # world-writable-dir TOCTOU to anyone watching TMPDIR. The file
        # stays empty until the replacement truncate-writes its pid.
        fd, ready_file = tempfile.mkstemp(prefix="veneur-ready-")
        os.close(fd)
        env = dict(os.environ, **{READY_FILE_ENV: ready_file})
    try:
        child = subprocess.Popen(cmd, env=env)
    except Exception:
        logger.exception("replacement spawn failed; keeping this process")
        if ready_file:
            try:
                os.unlink(ready_file)
            except OSError:
                pass
        return
    ok = _wait_ready(http_address, child, ready_file=ready_file)
    if ready_file:
        try:
            os.unlink(ready_file)
        except OSError:
            pass
    if not ok:
        if child.poll() is None:
            logger.error("replacement not ready after %.0fs; keeping "
                         "this process (replacement left running)",
                         READY_TIMEOUT_S)
        else:
            logger.error("replacement exited rc=%s before becoming "
                         "ready; keeping this process", child.returncode)
        return
    logger.info("replacement ready (pid %d); draining and exiting",
                child.pid)
    shutdown()


def _wait_ready(addr: str, child, timeout: float = READY_TIMEOUT_S,
                ready_file: str = "") -> bool:
    if not addr:
        # no readiness endpoint: wait for the ready-file handshake — the
        # replacement writes its pid once Server.start() has bound the
        # listeners. Alive-but-wedged is NOT ready.
        deadline = time.time() + timeout
        while time.time() < deadline:
            if child.poll() is not None:
                return False
            try:
                with open(ready_file) as f:
                    if f.read().strip() == str(child.pid):
                        return True
            except OSError:
                pass
            time.sleep(0.25)
        return False
    host, _, port = addr.rpartition(":")
    url = f"http://{host or '127.0.0.1'}:{port}/healthcheck/ready"
    deadline = time.time() + timeout
    while time.time() < deadline:
        if child.poll() is not None:
            return False
        try:
            with urllib.request.urlopen(url, timeout=2) as resp:
                # the kernel load-balances REUSEPORT connections, so
                # this poll can reach our OWN listener: only a ready
                # answer from another pid counts
                pid = resp.headers.get("X-Veneur-Pid", "")
                if resp.status == 200 and pid not in ("", str(os.getpid())):
                    return True
        except Exception:
            pass
        time.sleep(0.5)
    return False
