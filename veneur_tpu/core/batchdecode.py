"""Numpy columnar DogStatsD batch decoder: the pure-Python fallback for
the native (C++) batch parser.

Hosts without a compiler (or with ``VENEUR_TPU_DISABLE_NATIVE`` set)
used to fall all the way back to the per-packet object path — one
``UDPMetric`` allocation, one dict walk, and one table lock per sample —
which is where the BENCH_r05 ingest knee lives. This decoder keeps the
columnar shape of the native path in pure Python: a whole packet batch
parses into the SAME per-family COO columns (`ParseResult` duck type),
so the apply side (`BatchIngester._ingest`) is byte-for-byte shared with
the native path and pays one ``add_batch`` per family per batch instead
of one lock per sample.

What is vectorized: column assembly, llhist binning
(``llhist_ref.bin_index`` over the whole value array — float64, so bin
parity with the scalar path is definitional), the gauge last-write-wins
ordering merge, and the column-store batch applies. What is not: the
per-token strict-float validation, which deliberately reuses the scalar
parser's ``_strict_float`` so accept/reject behavior can never drift.

Parity contract (same as dogstatsd.cc): any line this decoder cannot
take bit-exactly the way the scalar parser would — events, service
checks, unknown keys, malformed values, non-ASCII set members,
NaN/Inf — is returned in ``unknown`` for the per-packet slow path, and
a malformed segment rolls back the WHOLE line's samples first.
"""

from __future__ import annotations

import math
from typing import Dict, List, Tuple

import numpy as np

from veneur_tpu.ops import hll_ref, llhist_ref
from veneur_tpu.samplers.parser import _strict_float

# family codes, mirroring dogstatsd.cc / veneur_tpu.native (imported
# here as literals so this module never touches the ctypes loader)
FAM_COUNTER = 0
FAM_GAUGE = 1
FAM_HISTO = 2
FAM_SET = 3
FAM_LLHIST = 4


class PyParseResult:
    """Duck-typed ``native.ParseResult``: trimmed per-family COO columns
    plus the deferred raw lines. llhist columns come out pre-binned
    (``l_bins``/``l_wts``/``l_clamped``), matching the native chunk
    layout so the shared apply path has one llhist contract."""

    __slots__ = ("lines", "samples", "c_rows", "c_vals", "c_rates",
                 "g_rows", "g_vals", "g_lines", "h_rows", "h_vals", "h_wts",
                 "s_rows", "s_idx", "s_rho",
                 "l_rows", "l_bins", "l_wts", "l_clamped",
                 "unknown", "unknown_lines")


_EMPTY_I32 = np.empty(0, np.int32)
_EMPTY_F32 = np.empty(0, np.float32)


class ColumnarDecoder:
    """One server's pure-Python intern table + columnar parse.

    The table maps a line's meta-key bytes (name chunk + everything from
    the type pipe onward) to ``(family, row, rate)`` — the same identity
    the C++ engine interns — filled by the slow path via ``register``,
    so each unique timeseries pays the object path exactly once.

    Thread safety: ``register`` may race ``parse`` from other reader
    threads; a plain dict assignment is atomic under the GIL, and a
    parse that misses a just-registered key only defers one more line.
    """

    def __init__(self):
        self.table: Dict[bytes, Tuple[int, int, float]] = {}

    def register(self, meta_key: bytes, family: int, row: int,
                 rate: float) -> None:
        self.table[meta_key] = (family, int(row), float(rate))

    def unregister_rows(self, dead: set) -> None:
        """Drop every mapping pointing at a ``(family, row)`` in `dead`
        — the fallback half of idle-row reclamation (mirrors
        vnt_unregister_rows2's one-sweep contract). list(items()) takes
        an atomic-under-the-GIL snapshot first: reader threads register
        concurrently, and iterating the live dict would raise
        RuntimeError mid-flush."""
        table = self.table
        for key, ent in list(table.items()):
            if (ent[0], ent[1]) in dead:
                table.pop(key, None)

    def size(self) -> int:
        return len(self.table)

    def parse(self, buf: bytes) -> PyParseResult:
        table = self.table
        c_rows: List[int] = []
        c_vals: List[float] = []
        c_rates: List[float] = []
        g_rows: List[int] = []
        g_vals: List[float] = []
        g_lines: List[int] = []
        h_rows: List[int] = []
        h_vals: List[float] = []
        h_wts: List[float] = []
        s_rows: List[int] = []
        s_idx: List[int] = []
        s_rho: List[int] = []
        l_rows: List[int] = []
        l_vals: List[float] = []
        l_wts: List[float] = []
        unknown: List[bytes] = []
        unknown_lines: List[int] = []
        cols_by_family = (
            (c_rows, c_vals, c_rates), (g_rows, g_vals, g_lines),
            (h_rows, h_vals, h_wts), (s_rows, s_idx, s_rho),
            (l_rows, l_vals, l_wts))
        hash_member = hll_ref.hash_member
        pos_val = hll_ref.pos_val
        isnan, isinf = math.isnan, math.isinf
        line_no = -1
        samples = 0
        for line in buf.split(b"\n"):
            if not line:
                continue
            line_no += 1
            if line.startswith(b"_e{") or line.startswith(b"_sc"):
                unknown.append(line)
                unknown_lines.append(line_no)
                continue
            type_start = line.find(b"|")
            if type_start < 0:
                unknown.append(line)
                unknown_lines.append(line_no)
                continue
            value_start = line.find(b":", 0, type_start)
            if value_start < 0:
                unknown.append(line)
                unknown_lines.append(line_no)
                continue
            ent = table.get(line[:value_start] + line[type_start:])
            if ent is None:
                unknown.append(line)
                unknown_lines.append(line_no)
                continue
            family, row, rate = ent
            toks = line[value_start + 1:type_start].split(b":")
            if toks and toks[-1] == b"":
                toks.pop()  # trailing empty segment is ignored (parity)
            cols = cols_by_family[family]
            mark = len(cols[0])  # a line only appends to its own family
            n_before = samples
            bad = False
            for tok in toks:
                if family == FAM_SET:
                    # non-ASCII members go to Python: the scalar parser
                    # round-trips them through UTF-8-with-replacement,
                    # changing the hashed bytes
                    if not tok.isascii():
                        bad = True
                        break
                    idx, rho = pos_val(hash_member(tok))
                    cols[0].append(row)
                    cols[1].append(idx)
                    cols[2].append(rho)
                else:
                    try:
                        v = _strict_float(tok)
                    except ValueError:
                        bad = True
                        break
                    if isnan(v) or isinf(v):
                        bad = True
                        break
                    cols[0].append(row)
                    cols[1].append(v)
                    if family == FAM_GAUGE:
                        cols[2].append(line_no)
                    elif family == FAM_COUNTER:
                        cols[2].append(rate)
                    elif family == FAM_LLHIST:
                        # scalar-path parity: 1e-9 rate floor before the
                        # reciprocal (LLHistTable.add does the same)
                        cols[2].append(1.0 / max(rate, 1e-9))
                    else:  # histo weight
                        cols[2].append(1.0 / rate)
                samples += 1
            if bad:
                # a malformed segment fails the whole line in the scalar
                # parser: roll back everything this line emitted
                for col in cols:
                    del col[mark:]
                samples = n_before
                unknown.append(line)
                unknown_lines.append(line_no)
        res = PyParseResult()
        res.lines = line_no + 1
        res.samples = samples
        res.unknown = unknown
        res.unknown_lines = unknown_lines
        res.c_rows = np.asarray(c_rows, np.int32)
        res.c_vals = np.asarray(c_vals, np.float32)
        res.c_rates = np.asarray(c_rates, np.float32)
        res.g_rows = np.asarray(g_rows, np.int32)
        res.g_vals = np.asarray(g_vals, np.float32)
        res.g_lines = np.asarray(g_lines, np.int32)
        res.h_rows = np.asarray(h_rows, np.int32)
        res.h_vals = np.asarray(h_vals, np.float32)
        res.h_wts = np.asarray(h_wts, np.float32)
        res.s_rows = np.asarray(s_rows, np.int32)
        res.s_idx = np.asarray(s_idx, np.int32)
        res.s_rho = np.asarray(s_rho, np.int32)
        res.l_rows = np.asarray(l_rows, np.int32)
        if l_rows:
            # vectorized float64 binning — the same llhist_ref code the
            # scalar path runs per value, so parity is definitional
            vals64 = np.asarray(l_vals, np.float64)
            bins, wts = _bin_llhist(vals64, np.asarray(l_wts, np.float64))
            res.l_bins = bins
            res.l_wts = wts
            res.l_clamped = int(
                wts[llhist_ref.clamped_mask(vals64)].sum())
        else:
            res.l_bins = _EMPTY_I32
            res.l_wts = _EMPTY_I32
            res.l_clamped = 0
        return res


def _bin_llhist(vals64: np.ndarray, wts: np.ndarray):
    """(values, 1/rate weights) -> (bin ids int32, integral weights
    int32); weights round half-to-even like the scalar path's round(),
    clipped into int32 (a valid @1e-10 rate must saturate, not wrap)."""
    bins = llhist_ref.bin_index(vals64).astype(np.int32, copy=False)
    w = np.clip(np.rint(wts), 1.0, np.iinfo(np.int32).max).astype(np.int32)
    return bins, w
