"""Operator HTTP API.

Endpoint parity with reference http.go:15-65: /healthcheck, /version,
/builddate, /config/json, /config/yaml (secrets redacted via
util.StringSecret), and optional /quitquitquit (config.http_quit).
Runs a stdlib ThreadingHTTPServer; profiling endpoints are served under
/debug/ (JAX device memory stats in place of Go pprof heap profiles).

Pull-side self-telemetry (core/telemetry.py) is served at:
  GET /metrics       Prometheus text exposition of every self-metric
                     plus per-device HBM gauges
  GET /debug/events  the event flight recorder (ring buffer, ?n=N)
  GET /debug/flush   the last N flush rounds with per-sink latency
"""

from __future__ import annotations

import dataclasses
import json
import os
import socket
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

import yaml

import veneur_tpu
from veneur_tpu.util.secret import StringSecret

BUILD_DATE = "dev"

# llhist series exported per route: http.route renders .p50/.p99 gauges
# + .count counter, tagged method:/path: (scripts/check_metric_names.py
# expands HIST_ROWS tuples against the README inventory)
HIST_ROWS = ("http.route",)

# routes timed individually; anything else buckets under path:other so
# scanning garbage paths can't mint unbounded label values
_TIMED_ROUTES = frozenset({
    "/healthcheck", "/healthcheck/tracing", "/healthcheck/ready",
    "/version", "/builddate", "/config/json", "/config/yaml", "/metrics",
    "/query", "/alerts", "/quitquitquit", "/import",
    "/debug/events", "/debug/flush", "/debug/latency", "/debug/ledger",
    "/debug/reshard", "/reshard",
    "/debug/traces", "/debug/cardinality", "/debug/device",
    "/debug/memory",
    "/debug/threads", "/debug/profile/cpu", "/debug/profile/device",
    "/debug/pprof", "/debug/pprof/", "/debug/pprof/profile",
    "/debug/pprof/heap", "/debug/pprof/allocs", "/debug/pprof/goroutine",
    "/debug/pprof/block", "/debug/pprof/mutex",
    "/debug/pprof/threadcreate", "/debug/pprof/cmdline",
    "/debug/pprof/symbol", "/debug/pprof/trace",
})


def config_to_dict(cfg: Any) -> Any:
    """Recursively serialize the Config dataclass tree, redacting secrets
    (reference util.StringSecret marshals as REDACTED)."""
    if isinstance(cfg, StringSecret):
        return str(cfg)
    if dataclasses.is_dataclass(cfg) and not isinstance(cfg, type):
        return {f.name: config_to_dict(getattr(cfg, f.name))
                for f in dataclasses.fields(cfg)}
    if isinstance(cfg, dict):
        return {k: config_to_dict(v) for k, v in cfg.items()}
    if isinstance(cfg, (list, tuple)):
        return [config_to_dict(v) for v in cfg]
    return cfg


class _Handler(BaseHTTPRequestHandler):
    server_ref = None  # class attr set per HTTPApi instance subclass

    def log_message(self, fmt, *args):  # silence default stderr access log
        pass

    def _send(self, status: int, body: bytes,
              content_type: str = "text/plain") -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        # which process answered: under a SO_REUSEPORT graceful restart
        # two instances share the port, and the old one's readiness poll
        # must not accept its own listener's answer
        self.send_header("X-Veneur-Pid", str(os.getpid()))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802
        t0 = time.perf_counter()
        try:
            self._route_GET()
        finally:
            self.server_ref.observe_route(
                "GET", self.path, time.perf_counter() - t0)

    def _route_GET(self) -> None:
        api = self.server_ref
        path = self.path.split("?", 1)[0]
        if path == "/healthcheck":
            self._send(200, b"ok\n")
        elif path == "/healthcheck/tracing":
            # reference http.go:45-47: tracing plane liveness (mounted
            # whenever the API is up, like the reference)
            self._send(200, b"ok\n")
        elif path == "/healthcheck/ready":
            # the full readiness ladder: listener/flush state as before,
            # plus the server's own degradation verdict — shedding
            # overload state or a tripped flush watchdog answer 503 with
            # a JSON reason, so orchestrators stop routing to an
            # instance that is wedged or actively dropping data. A
            # standalone API (the proxy) passes its own `ready` source;
            # its body may be a full dict (the proxy includes the ring
            # member table alongside the reason).
            ready, reason = True, ""
            if api.ready_source is not None:
                ready, reason = api.ready_source()
            elif api.server is not None:
                if api.require_flush_for_ready and not api.server.flush_count:
                    ready, reason = False, "no flush completed yet"
                else:
                    rs = getattr(api.server, "ready_state", None)
                    if rs is not None:
                        ready, reason = rs()
            if ready:
                self._send(200, b"ready\n")
            else:
                body = (dict(reason, ready=False)
                        if isinstance(reason, dict)
                        else {"ready": False, "reason": reason})
                self._send(503, json.dumps(body).encode() + b"\n",
                           "application/json")
        elif path == "/version":
            self._send(200, veneur_tpu.__version__.encode())
        elif path == "/builddate":
            self._send(200, BUILD_DATE.encode())
        elif path == "/config/json":
            body = json.dumps(config_to_dict(api.config), indent=2).encode()
            self._send(200, body, "application/json")
        elif path == "/config/yaml":
            body = yaml.safe_dump(config_to_dict(api.config)).encode()
            self._send(200, body, "application/x-yaml")
        elif path == "/metrics":
            # content negotiation: exemplars are OpenMetrics-only
            # syntax (a mid-line `#` breaks text/plain 0.0.4 parsers),
            # so they render only when the scraper asks for
            # application/openmetrics-text (or forces ?exemplars=1),
            # and the response is stamped with that content type + EOF
            accept = self.headers.get("Accept") or ""
            want_om = ("openmetrics" in accept
                       or _query_str(self.path, "exemplars").lower()
                       in ("1", "true", "yes"))
            if want_om:
                body = (api.telemetry.registry.render_prometheus(
                    exemplars=True) + "# EOF\n").encode()
                self._send(200, body,
                           "application/openmetrics-text; version=1.0.0; "
                           "charset=utf-8")
            else:
                body = api.telemetry.registry.render_prometheus().encode()
                self._send(200, body,
                           "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/debug/events":
            limit = int(_query_float(self.path, "n", 0.0, max_value=1e6))
            kind = _query_str(self.path, "kind")
            trace_id = _query_str(self.path, "trace_id")
            self._send(200, api.telemetry.events_json(
                limit, kind=kind, trace_id=trace_id),
                "application/json")
        elif path == "/debug/flush":
            limit = int(_query_float(self.path, "n", 0.0, max_value=1e6))
            if _query_str(self.path, "waterfall").lower() not in (
                    "", "0", "false", "no"):
                # the last N flush rounds as per-family/per-device/
                # per-sink segment trees (core/latency.py)
                from veneur_tpu.core import latency as latency_mod
                body = json.dumps({
                    "rounds": latency_mod.waterfall_rounds(
                        api.telemetry.flushes.snapshot(limit)),
                }, indent=2, default=str).encode()
                self._send(200, body, "application/json")
                return
            self._send(200, api.telemetry.flushes_json(limit),
                       "application/json")
        elif path == "/debug/latency":
            # the latency observatory report: per-plane sample-age
            # llhists, queue dwell/depth, pending retraces
            source = api.latency_source
            if source is None:
                latency = getattr(api.server, "latency", None)
                source = getattr(latency, "report", None)
            if source is None:
                self._send(404, b"no latency source\n")
                return
            body = json.dumps(source(), indent=2, default=str).encode()
            self._send(200, body, "application/json")
        elif path == "/debug/reshard":
            # elastic reshard state machine: phase, epoch, deadline,
            # WAL segment counters (parallel/reshard.py)
            controller = getattr(api.server, "reshard", None)
            if controller is None:
                self._send(404, b"no reshard controller\n")
                return
            self._send(200, json.dumps(controller.describe(),
                                       indent=2).encode() + b"\n",
                       "application/json")
        elif path == "/debug/ledger":
            # the flow ledger's conservation report: per-identity
            # imbalances, lifetime stage totals, live inventory stocks,
            # and the last N closed intervals as a waterfall
            # (?intervals=N). Served by the proxy too (routing +
            # destination-pool identities).
            source = api.ledger_source
            if source is None:
                ledger = getattr(api.server, "ledger", None)
                source = getattr(ledger, "report", None)
            if source is None:
                self._send(404, b"no ledger source\n")
                return
            n = int(_query_float(self.path, "intervals", 0.0,
                                 max_value=1e4))
            body = json.dumps(source(intervals=n), indent=2,
                              default=str).encode()
            self._send(200, body, "application/json")
        elif path == "/debug/traces":
            # the cross-tier self-trace store (trace/store.py): this
            # tier's recorded spans grouped by interval trace.
            # ?trace_id= (hex) drills into one trace, ?interval= into
            # one flush interval, ?n= bounds the listing. Served by
            # server, proxy, AND global — one flush interval's trace is
            # retrievable on every tier it crossed.
            source = api.trace_source
            if source is None:
                plane = getattr(api.server, "trace_plane", None)
                source = getattr(plane, "report", None)
            if source is None:
                self._send(404, b"no trace source\n")
                return
            body = json.dumps(source(
                trace_id=_query_str(self.path, "trace_id"),
                interval=int(_query_float(self.path, "interval", 0.0,
                                          max_value=1e12)),
                limit=int(_query_float(self.path, "n", 0.0,
                                       max_value=1e4))),
                indent=2, default=str).encode()
            self._send(200, body, "application/json")
        elif path == "/debug/cardinality":
            # series-cardinality observatory: top-N names by live rows
            # with mint rates and per-tag-key HLL estimates for the top
            # offenders; ?name= drills into one name. Served by the
            # server (core/server.py cardinality_report) and the proxy
            # (per-destination forwarded-key estimates).
            source = api.cardinality_source
            if source is None:
                source = getattr(api.server, "cardinality_report", None)
            if source is None:
                self._send(404, b"no cardinality source\n")
                return
            top = int(_query_float(self.path, "top", 20.0,
                                   max_value=10000.0))
            name = _query_str(self.path, "name")
            body = json.dumps(source(top=top, name=name), indent=2,
                              default=str).encode()
            self._send(200, body, "application/json")
        elif path == "/debug/device":
            # device capacity & shard-balance observatory
            # (core/deviceobs.py): HBM generation ledger by family/
            # lifecycle state with backend reconciliation, kernel
            # dispatch/compile registry, per-shard balance + recommended
            # reshard plan, and the device watermark rung
            source = getattr(api.server, "device_report", None)
            if source is None:
                self._send(404, b"no device source\n")
                return
            body = json.dumps(source(), indent=2,
                              default=str).encode()
            self._send(200, body, "application/json")
        elif path == "/query":
            # the live query plane (core/query.py): percentile / count /
            # rate / cardinality / value / bin-occupancy lookups against
            # a consistent read-only capture of the LIVE device
            # generation — sub-interval staleness, no flush perturbation.
            # ?metric=&kind=&q=&tags=a:b,c:d&lo=&hi=. A standalone API
            # (the proxy) passes its own aggregate view as the source.
            source = api.query_source
            if source is None:
                plane = getattr(api.server, "query_plane", None)
                source = getattr(plane, "query", None)
            if source is None:
                self._send(404, b"no query source\n")
                return
            from veneur_tpu.core.query import (QueryError, QuerySpec,
                                               parse_tags)
            try:
                spec = QuerySpec.build(
                    metric=_query_str(self.path, "metric"),
                    kind=_query_str(self.path, "kind", "value"),
                    q=_query_str(self.path, "q") or None,
                    tags=parse_tags(_query_str(self.path, "tags")),
                    lo=_query_str(self.path, "lo") or None,
                    hi=_query_str(self.path, "hi") or None)
            except (QueryError, ValueError) as e:
                self._send(400, json.dumps({"error": str(e)}).encode()
                           + b"\n", "application/json")
                return
            from veneur_tpu.core.query import ReshardRetry
            try:
                result = source(spec)
            except ReshardRetry as e:
                # typed retry, not an error: a reshard cutover is
                # swapping the topology under the capture — the caller
                # re-issues once the swap settles (sub-second)
                self._send(503, json.dumps(
                    {"error": str(e), "retry": True}).encode() + b"\n",
                    "application/json")
                return
            except QueryError as e:
                self._send(400, json.dumps({"error": str(e)}).encode()
                           + b"\n", "application/json")
                return
            except Exception as e:  # timeout / device fault: the
                # query plane is best-effort, never a crash surface
                self._send(500, json.dumps({"error": str(e)}).encode()
                           + b"\n", "application/json")
                return
            self._send(200, json.dumps(result, indent=2,
                                       default=str).encode(),
                       "application/json")
        elif path == "/alerts":
            # the alert engine's rule table + state machines
            # (core/alerts.py): per-rule state, last value, hold-down
            engine = api.alerts_source
            if engine is None:
                engine = getattr(api.server, "alerts", None)
            if engine is None:
                self._send(404, b"no alert engine\n")
                return
            self._send(200, json.dumps(engine.report(), indent=2,
                                       default=str).encode(),
                       "application/json")
        elif path == "/debug/memory":
            self._send(200, _device_memory_report(),
                       "application/json")
        elif path == "/debug/profile/cpu":
            # reference server.go:1382-1390 enable_profiling CPU profile;
            # continuous sampler when enable_profiling is on, else a
            # request-scoped sample
            from veneur_tpu.core import profiling
            seconds = _query_float(self.path, "seconds", 2.0)
            sampler = getattr(api.server, "profiler", None)
            if sampler is not None and sampler.running:
                body = sampler.report().encode()
            else:
                body = profiling.sample_for(seconds).encode()
            self._send(200, body)
        elif path == "/debug/pprof/profile":
            # real pprof wire format (reference http.go:53-63 mounts Go
            # pprof here): block for ?seconds=N, return gzipped proto —
            # `go tool pprof http://host/debug/pprof/profile` works
            from veneur_tpu.core import profiling
            seconds = _query_float(self.path, "seconds", 5.0,
                                   max_value=120.0)
            try:
                body = profiling.pprof_for(seconds)
            except RuntimeError as e:
                # one capture at a time (Go pprof parity)
                self._send(503, str(e).encode())
                return
            self._send(200, body, "application/octet-stream")
        elif path in ("/debug/pprof/heap", "/debug/pprof/allocs"):
            # pprof heap profile backed by tracemalloc: request-scoped by
            # default; enable_profiling keeps tracing armed so later
            # requests see allocations since. Go serves the same profile
            # at both routes (only the default sample type differs);
            # inside the arming-throttle window the previous capture is
            # served so scraping the pair back-to-back works
            from veneur_tpu.core import profiling
            keep = bool(getattr(api.config, "enable_profiling", False))
            try:
                body, _fresh = profiling.heap_pprof_or_cached(
                    keep_tracing=keep)
            except profiling.HeapProfileThrottled as e:
                # rate-limited with nothing cached yet: hammering the
                # endpoint can't keep tracemalloc always-on
                self._send(429, str(e).encode())
                return
            self._send(200, body, "application/octet-stream")
        elif path == "/debug/pprof/goroutine":
            # thread stacks in pprof form (Go names this route goroutine;
            # tooling hardcodes the path)
            from veneur_tpu.core import profiling
            self._send(200, profiling.threads_pprof(),
                       "application/octet-stream")
        elif path in ("/debug/pprof/block", "/debug/pprof/mutex"):
            # no CPython contention profiler: a valid empty profile keeps
            # pprof scrapers working (reference mounts all pprof routes)
            from veneur_tpu.core import profiling
            kind = "contentions" if path.endswith("block") else "mutex"
            self._send(200, profiling.empty_pprof(kind),
                       "application/octet-stream")
        elif path == "/debug/pprof/threadcreate":
            from veneur_tpu.core import profiling
            self._send(200, profiling.threadcreate_pprof(),
                       "application/octet-stream")
        elif path == "/debug/pprof/cmdline":
            # NUL-separated argv, the Go pprof cmdline contract;
            # surrogateescape survives non-UTF-8 argv bytes (POSIX argv
            # is bytes; CPython decodes it with surrogateescape)
            self._send(200, b"\x00".join(
                a.encode("utf-8", "surrogateescape")
                for a in sys.argv), "text/plain")
        elif path == "/debug/pprof/symbol":
            # 0: our profiles carry pre-symbolized frames, no address
            # lookup is ever needed (the Go handler advertises its
            # symbolizer count the same way)
            self._send(200, b"num_symbols: 0\n", "text/plain")
        elif path == "/debug/pprof/trace":
            self._send(501, b"execution trace is a Go-runtime feature "
                            b"with no CPython analog; use "
                            b"/debug/pprof/profile or "
                            b"/debug/profile/device\n")
        elif path == "/debug/pprof/" or path == "/debug/pprof":
            self._send(200, (
                b"veneur-tpu profiles:\n"
                b"  /debug/pprof/profile?seconds=N  pprof CPU profile\n"
                b"  /debug/pprof/heap               pprof heap profile\n"
                b"  /debug/pprof/goroutine          thread stacks (pprof)\n"
                b"  /debug/pprof/allocs             alias of heap\n"
                b"  /debug/pprof/block|mutex        empty (no analog)\n"
                b"  /debug/pprof/threadcreate       live-thread count\n"
                b"  /debug/pprof/cmdline|symbol     pprof text protocols\n"
                b"  /debug/profile/cpu?seconds=N    text CPU profile\n"
                b"  /debug/profile/device?seconds=N xprof device trace\n"
                b"  /debug/memory                   device memory JSON\n"
                b"  /debug/threads                  all-thread stacks\n"
                b"  /debug/events?n=N               event flight recorder\n"
                b"  /debug/flush?n=N                recent flush rounds\n"
                b"  /debug/flush?waterfall=1        per-family segment trees\n"
                b"  /debug/traces?trace_id=&interval=  cross-tier traces\n"
                b"  /debug/latency                  latency observatory\n"
                b"  /debug/ledger?intervals=N       flow-ledger conservation\n"
                b"  /debug/cardinality?top=N&name=  series cardinality\n"
                b"  /debug/device                   HBM ledger & shard balance\n"
                b"  /query?metric=&kind=&q=         live query plane\n"
                b"  /alerts                         alert rule states\n"
                b"  /metrics                        Prometheus exposition\n"))
        elif path == "/debug/profile/device":
            # jax.profiler trace (TensorBoard-loadable zip) — the TPU
            # analog of /debug/pprof/profile (reference http.go:53-63)
            from veneur_tpu.core import profiling
            seconds = _query_float(self.path, "seconds", 2.0)
            try:
                body = profiling.capture_device_trace(seconds)
            except Exception as e:
                self._send(500, f"trace failed: {e}\n".encode())
                return
            self.send_response(200)
            self.send_header("Content-Type", "application/zip")
            self.send_header("Content-Disposition",
                             'attachment; filename="device-trace.zip"')
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif path == "/debug/threads":
            # faulthandler needs a real fd; format stacks directly instead
            import traceback
            names = {t.ident: t.name for t in threading.enumerate()}
            parts = []
            for ident, frame in sys._current_frames().items():
                parts.append(f"Thread {names.get(ident, '?')} ({ident}):\n")
                parts.extend(traceback.format_stack(frame))
                parts.append("\n")
            self._send(200, "".join(parts).encode())
        else:
            self._send(404, b"not found\n")

    def do_POST(self) -> None:  # noqa: N802
        t0 = time.perf_counter()
        try:
            self._route_POST()
        finally:
            self.server_ref.observe_route(
                "POST", self.path, time.perf_counter() - t0)

    def _route_POST(self) -> None:
        api = self.server_ref
        path = self.path.split("?", 1)[0]
        if path == "/quitquitquit" and api.http_quit:
            self._send(200, b"bye\n")
            threading.Thread(target=api.quit, daemon=True).start()
        elif path == "/reshard":
            # elastic reshard (parallel/reshard.py): {"shards": M}
            # plans + prewarms in the background and cuts over at the
            # next flush boundary; poll GET /debug/reshard for state
            controller = getattr(api.server, "reshard", None)
            if controller is None:
                self._send(404, b"no reshard controller\n")
                return
            try:
                n = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(n) or b"{}")
                shards = int(body["shards"])
            except (ValueError, KeyError, TypeError) as e:
                self._send(400, json.dumps(
                    {"error": f"bad request: {e}"}).encode() + b"\n",
                    "application/json")
                return
            from veneur_tpu.parallel.reshard import ReshardError
            try:
                state = controller.begin(
                    shards, deadline_s=body.get("deadline_s"))
            except ReshardError as e:
                self._send(409, json.dumps(
                    {"error": str(e)}).encode() + b"\n",
                    "application/json")
                return
            self._send(202, json.dumps(state, indent=2).encode()
                       + b"\n", "application/json")
        else:
            self._send(404, b"not found\n")


def _query_str(path: str, key: str, default: str = "") -> str:
    from urllib.parse import parse_qs, urlparse
    vals = parse_qs(urlparse(path).query).get(key)
    return vals[0] if vals else default


def _query_float(path: str, key: str, default: float,
                 max_value: float = 60.0) -> float:
    """Bounded query-param parse: profiling durations are clamped so one
    request can't pin a sampler or hold the JAX trace open indefinitely."""
    from urllib.parse import parse_qs, urlparse
    try:
        vals = parse_qs(urlparse(path).query).get(key)
        val = float(vals[0]) if vals else default
    except (TypeError, ValueError):
        return default
    return min(max(val, 0.0), max_value)


def _device_memory_report() -> bytes:
    """JAX stand-in for /debug/pprof/heap: per-device memory stats."""
    try:
        import jax
        stats = []
        for d in jax.devices():
            try:
                ms = d.memory_stats() or {}
            except Exception:
                ms = {}
            stats.append({"device": str(d), "memory_stats": ms})
        return json.dumps(stats, indent=2, default=str).encode()
    except Exception as e:
        return json.dumps({"error": str(e)}).encode()


class HTTPApi:
    """Serves the ops endpoints for a running server (or standalone proxy)."""

    def __init__(self, config, server=None, address: str = "127.0.0.1:0",
                 http_quit: bool = False, on_quit=None,
                 require_flush_for_ready: bool = False, telemetry=None,
                 cardinality=None, latency=None, ready=None, ledger=None,
                 traces=None, query=None, alerts=None):
        self.config = config
        self.server = server
        self.http_quit = http_quit
        self.on_quit = on_quit
        self.require_flush_for_ready = require_flush_for_ready
        # /query source: a callable(QuerySpec) -> dict. The owning
        # server's query_plane.query is used by default; a standalone
        # API (the proxy) passes its ProxyQueryView's aggregate query
        self.query_source = query
        # /alerts source: an object with .report() -> dict; the owning
        # server's AlertEngine by default (a proxy has none)
        self.alerts_source = alerts
        # per-route latency (core/latency.py): every request through
        # do_GET/do_POST lands in a per-(method, path) llhist, exported
        # as http.route.* rows — the request plane was the last untimed
        # hand-off in the latency observatory
        self._route_hists: Dict[str, "object"] = {}
        self._route_lock = threading.Lock()
        # /debug/cardinality source: a callable(top=N, name="") -> dict.
        # The owning server's cardinality_report is used by default; a
        # standalone API (the proxy) passes its own.
        self.cardinality_source = cardinality
        # /debug/latency source: a zero-arg callable -> dict; the owning
        # server's latency.report is used by default, the proxy passes
        # its own observatory's
        self.latency_source = latency
        # /debug/ledger source: a callable(intervals=N) -> dict; the
        # owning server's ledger.report by default, the proxy passes
        # its own ledger's
        self.ledger_source = ledger
        # /debug/traces source: a callable(trace_id=, interval=, limit=)
        # -> dict; the owning server's trace_plane.report by default,
        # the proxy passes its own plane's
        self.trace_source = traces
        # /healthcheck/ready source for a standalone API (the proxy):
        # a callable -> (ready, reason_str_or_body_dict); None defers to
        # the owning server's readiness ladder
        self.ready_source = ready
        # /metrics & the flight recorder serve the owning server's
        # telemetry; a standalone API (proxy passes its own, tests pass
        # none) gets a private registry so the routes always answer —
        # device HBM gauges still render fresh at scrape time
        if telemetry is None:
            telemetry = getattr(server, "telemetry", None)
        if telemetry is None:
            from veneur_tpu.core import telemetry as telemetry_mod
            telemetry = telemetry_mod.Telemetry()
            telemetry.registry.add_collector(
                telemetry_mod.device_memory_rows)
        self.telemetry = telemetry
        self.telemetry.registry.add_collector(self.route_telemetry_rows)
        host, _, port = address.rpartition(":")
        handler = type("BoundHandler", (_Handler,), {"server_ref": self})

        class _ReusableHTTPServer(ThreadingHTTPServer):
            # graceful restart: the replacement process binds the same
            # fixed port while this one still serves. Set the socket
            # option by hand — socketserver's allow_reuse_port attribute
            # only exists on Python 3.11+, and this package supports 3.10
            def server_bind(self):
                if hasattr(socket, "SO_REUSEPORT"):
                    try:
                        self.socket.setsockopt(
                            socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
                    except OSError:
                        pass
                super().server_bind()

        self._httpd = _ReusableHTTPServer((host or "127.0.0.1", int(port)),
                                          handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self):
        return self._httpd.server_address

    def observe_route(self, method: str, raw_path: str,
                      elapsed_s: float) -> None:
        from veneur_tpu.core.latency import LatencyHist
        path = raw_path.split("?", 1)[0]
        if path not in _TIMED_ROUTES:
            path = "other"
        key = f"{method} {path}"
        with self._route_lock:
            hist = self._route_hists.get(key)
            if hist is None:
                hist = self._route_hists[key] = LatencyHist("http.route")
        hist.observe(elapsed_s)

    def route_telemetry_rows(self):
        """http.route.{p50,p99} gauges + .count counter per route."""
        with self._route_lock:
            items = sorted(self._route_hists.items())
        rows = []
        for key, hist in items:
            method, _, path = key.partition(" ")
            tags = [f"method:{method}", f"path:{path}"]
            snap = hist.snapshot()
            for label in ("p50", "p99"):
                rows.append((f"http.route.{label}", "gauge",
                             snap[label], tags))
            rows.append(("http.route.count", "counter",
                         float(snap["count"]), tags))
        return rows

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="http-api", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    def quit(self) -> None:
        if self.on_quit is not None:
            self.on_quit()
        else:
            self.stop()
