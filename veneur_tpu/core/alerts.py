"""On-device alert predicates over the live query plane.

Declarative rule tables — threshold on a t-digest quantile, llhist
bin-range occupancy, counter rate/count, HLL cardinality — evaluated
every `alerts.interval` seconds against ONE consistent read-only
capture of the live generation (core/query.py). Rule values come out
of the same readout kernels the flush runs; the threshold compare over
all rules is a single vmapped device dispatch (padded to a power-of-two
rule count so the jit trace is reused as rule tables evolve).

Each rule runs a Prometheus-style state machine with a `for:` hold-down:

    idle --breach--> pending --held for `for_s`--> firing
    pending --clear--> idle          firing --clear--> idle (resolved)

Every state change lands in the flight recorder as an
`alert_transition` event (rule id, value, threshold — stamped with the
active interval trace id like every event), and the current state
exports as `alert.*` rows in /metrics. Transition LOG lines are
rate-limited to the first per rule per flush interval; events and rows
are never suppressed. Rules hot-reload via SIGHUP
(`Server.reload_alerts`), preserving in-flight state for rule ids that
survive the reload.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from veneur_tpu.core.latency import LatencyHist
from veneur_tpu.core.query import (QueryError, QuerySpec, _KIND_FAMILIES,
                                   parse_tags)

logger = logging.getLogger("veneur_tpu.core.alerts")

# llhist series exported by the engine (lint-expanded, see latency.py)
HIST_ROWS = ("alert.eval",)

# rule comparison operators -> the op codes the device compare selects on
_OPS = {">": 0, ">=": 1, "<": 2, "<=": 3, "==": 4, "!=": 5}

# exported state codes for the alert.state gauge
STATE_CODES = {"idle": 0.0, "pending": 1.0, "firing": 2.0}


def _duration_s(v) -> float:
    """'400ms' / '30s' / '1h30m' / bare numbers -> seconds, via the
    config module's Go-style parser (AlertsConfig.interval already goes
    through it, so `for:` accepts the same grammar)."""
    from veneur_tpu.config import parse_duration
    try:
        return parse_duration(v)
    except ValueError:
        return float(v)  # bare numeric strings ("5") mean seconds


@jax.jit
def _compare_rules(values, ops, thresholds, valid):
    """The single vmapped threshold dispatch: (N,) rule values against
    (N,) thresholds under per-rule op codes. Rules whose value could
    not be resolved this round (no live rows) carry valid=False and
    never breach."""
    def one(v, op, t, ok):
        pred = jnp.select(
            [op == 0, op == 1, op == 2, op == 3, op == 4],
            [v > t, v >= t, v < t, v <= t, v == t], v != t)
        return ok & pred
    return jax.vmap(one)(values, ops, thresholds, valid)


def _pad_len(n: int) -> int:
    """Power-of-two padding (floor 8) so the compare kernel compiles a
    handful of shapes total, not one per rule-table size."""
    return max(8, 1 << (max(n, 1) - 1).bit_length())


@dataclass(frozen=True)
class AlertRule:
    """One validated rule; `spec` is its query-plane lookup."""

    id: str
    metric: str
    kind: str
    op: str
    threshold: float
    for_s: float
    spec: QuerySpec
    q: Optional[float] = None
    tags: Tuple[str, ...] = ()
    lo: Optional[float] = None
    hi: Optional[float] = None

    @classmethod
    def parse(cls, d: dict) -> "AlertRule":
        if not isinstance(d, dict):
            raise QueryError(f"alert rule must be a mapping, got {d!r}")
        rid = str(d.get("id") or "").strip()
        if not rid:
            raise QueryError("alert rule requires an id")
        op = str(d.get("op", ">"))
        if op not in _OPS:
            raise QueryError(
                f"rule {rid!r}: unknown op {op!r} "
                f"(expected one of {sorted(_OPS)})")
        if "threshold" not in d:
            raise QueryError(f"rule {rid!r}: threshold is required")
        tags = d.get("tags") or ()
        if isinstance(tags, str):
            tags = parse_tags(tags)
        if str(d.get("kind", "quantile")) == "shard_skew":
            # device-observatory rule: no query-plane lookup — the
            # value is DeviceObservatory.shard_skew() each tick
            return cls(id=rid,
                       metric=str(d.get("metric") or "device.shard.skew"),
                       kind="shard_skew", op=op,
                       threshold=float(d["threshold"]),
                       for_s=_duration_s(d.get("for", 0.0)), spec=None,
                       tags=tuple(tags))
        spec = QuerySpec.build(
            metric=str(d.get("metric") or ""),
            kind=str(d.get("kind", "quantile")),
            q=d.get("q"), tags=tuple(tags),
            lo=d.get("lo"), hi=d.get("hi"))
        return cls(id=rid, metric=spec.metric, kind=spec.kind, op=op,
                   threshold=float(d["threshold"]),
                   for_s=_duration_s(d.get("for", 0.0)), spec=spec,
                   q=spec.q, tags=spec.tags, lo=spec.lo, hi=spec.hi)


@dataclass
class _RuleState:
    state: str = "idle"
    since_unix: float = 0.0       # entered the current state at
    pending_since: float = 0.0
    last_value: float = float("nan")
    breaching: bool = False
    transitions: int = 0
    last_log_flush: int = -1      # log rate-limit marker (flush id)


class AlertEngine:
    """The server's alert evaluator: one daemon loop, one capture + one
    vmapped compare per tick, Python state machines per rule."""

    def __init__(self, server, query_plane, interval_s: float = 1.0,
                 rules: Sequence[dict] = ()):
        self._server = server
        self._plane = query_plane
        self.interval_s = max(float(interval_s), 0.05)
        self._lock = threading.Lock()
        self._rules: List[AlertRule] = []
        self._states: Dict[str, _RuleState] = {}
        self.evals_total = 0
        self.transitions_total = 0
        self.suppressed_logs_total = 0
        self.reloads_total = 0
        self.rule_errors_total = 0
        self._eval_hist = LatencyHist("alert.eval")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if rules:
            self.configure(rules)

    # -- rule table management (initial load + SIGHUP hot reload) --------

    def configure(self, rule_dicts: Sequence[dict],
                  interval_s: Optional[float] = None) -> int:
        """(Re)load the rule table. In-flight state machines survive for
        rule ids present in both tables; rules that vanish are dropped
        (a firing rule that is deleted resolves silently — deleting the
        rule IS the operator's acknowledgment). Returns the rule
        count."""
        rules = [AlertRule.parse(d) for d in rule_dicts or ()]
        seen = set()
        for r in rules:
            if r.id in seen:
                raise QueryError(f"duplicate alert rule id {r.id!r}")
            seen.add(r.id)
        with self._lock:
            old = self._states
            self._rules = rules
            self._states = {r.id: old.get(r.id, _RuleState())
                            for r in rules}
            if interval_s is not None:
                self.interval_s = max(float(interval_s), 0.05)
            self.reloads_total += 1
        return len(rules)

    # -- the evaluation loop ---------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="alert-loop", daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout)

    def _loop(self) -> None:
        beat = self._server.overload.supervisor.beat
        beat("alert-loop")
        while not self._stop.wait(self.interval_s):
            beat("alert-loop")
            try:
                self.evaluate_once()
            except QueryError:
                pass  # shutdown race: the capture refused, loop exits soon
            except Exception:
                self.rule_errors_total += 1
                logger.exception("alert evaluation failed")

    def evaluate_once(self, now: Optional[float] = None) -> List[dict]:
        """One tick: capture -> per-rule lookup -> one device compare ->
        state machines. Returns the transitions recorded (for the drill
        script and tests)."""
        with self._lock:
            rules = list(self._rules)
        if not rules:
            return []
        t0 = time.perf_counter()
        self.evals_total += 1
        specs = [r.spec for r in rules if r.spec is not None]
        families: List[str] = []
        for s in specs:
            for fam in _KIND_FAMILIES[s.kind]:
                if fam not in families:
                    families.append(fam)
        ps = self._plane.ps_for(specs)
        need_bins = any(s.kind == "bin_occupancy" for s in specs)
        bundle = None
        if specs:  # pure shard_skew rule sets never touch the store
            bundle = self._plane.capture(families, ps=ps,
                                         need_bins=need_bins)
        values = np.full(len(rules), np.nan, np.float32)
        for i, rule in enumerate(rules):
            if rule.kind == "shard_skew":
                obs = getattr(self._server, "deviceobs", None)
                skew = obs.shard_skew() if obs is not None else None
                if skew is not None and not np.isnan(skew):
                    values[i] = np.float32(skew)
                continue
            try:
                res = self._plane.evaluate(bundle, rule.spec, ps)
            except Exception:
                self.rule_errors_total += 1
                logger.exception("alert rule %s evaluation failed",
                                 rule.id)
                continue
            if res["value"] is not None:
                values[i] = np.float32(res["value"])
        breaches = self._compare(rules, values)
        if now is None:
            now = time.time()
        transitions = self._advance(rules, values, breaches, now)
        self._eval_hist.observe(time.perf_counter() - t0)
        for tr in transitions:
            self._record_transition(tr)
        return transitions

    def _compare(self, rules: List[AlertRule],
                 values: np.ndarray) -> np.ndarray:
        n = len(rules)
        width = _pad_len(n)
        vals = np.zeros(width, np.float32)
        vals[:n] = np.nan_to_num(values, nan=0.0)
        ops = np.zeros(width, np.int32)
        ops[:n] = [_OPS[r.op] for r in rules]
        thr = np.zeros(width, np.float32)
        thr[:n] = [r.threshold for r in rules]
        valid = np.zeros(width, bool)
        valid[:n] = ~np.isnan(values)
        out = np.asarray(_compare_rules(vals, ops, thr, valid))
        return out[:n]

    def _advance(self, rules, values, breaches, now: float) -> List[dict]:
        transitions: List[dict] = []
        with self._lock:
            for rule, value, breach in zip(rules, values, breaches):
                st = self._states.get(rule.id)
                if st is None:  # raced a reload; next tick sees it
                    continue
                st.last_value = float(value)
                st.breaching = bool(breach)
                old = st.state
                new = old
                if breach:
                    if old == "idle":
                        st.pending_since = now
                        new = ("firing" if rule.for_s <= 0.0
                               else "pending")
                    elif old == "pending" and \
                            now - st.pending_since >= rule.for_s:
                        new = "firing"
                else:
                    if old in ("pending", "firing"):
                        new = "idle"
                if new != old:
                    st.state = new
                    st.since_unix = now
                    st.transitions += 1
                    self.transitions_total += 1
                    transitions.append({
                        "rule": rule.id,
                        "from_state": old,
                        "to_state": ("resolved" if old == "firing"
                                     and new == "idle" else new),
                        "value": round(float(value), 6),
                        "threshold": rule.threshold,
                        "op": rule.op,
                        "metric": rule.metric,
                        "unix": round(now, 3),
                    })
        return transitions

    def _record_transition(self, tr: dict) -> None:
        telemetry = getattr(self._server, "telemetry", None)
        if telemetry is not None:
            telemetry.record_event(
                "alert_transition", rule=tr["rule"],
                from_state=tr["from_state"], to_state=tr["to_state"],
                value=tr["value"], threshold=tr["threshold"],
                metric=tr["metric"])
        # LOG rate limit: first transition per rule per flush interval;
        # the rest are counted, never logged (events/rows still record)
        flush_id = int(getattr(self._server, "flush_count", 0))
        with self._lock:
            st = self._states.get(tr["rule"])
            if st is None:
                return
            if st.last_log_flush == flush_id:
                self.suppressed_logs_total += 1
                return
            st.last_log_flush = flush_id
        logger.info(
            "alert %s: %s -> %s (value=%s %s threshold=%s, metric=%s)",
            tr["rule"], tr["from_state"], tr["to_state"], tr["value"],
            tr["op"], tr["threshold"], tr["metric"])

    # -- export ----------------------------------------------------------

    def report(self) -> dict:
        """The GET /alerts payload."""
        with self._lock:
            rules = list(self._rules)
            states = {rid: (st.state, st.since_unix, st.last_value,
                            st.breaching, st.transitions)
                      for rid, st in self._states.items()}
        out_rules = []
        for r in rules:
            state, since, value, breaching, transitions = states.get(
                r.id, ("idle", 0.0, float("nan"), False, 0))
            entry = {
                "id": r.id, "metric": r.metric, "kind": r.kind,
                "op": r.op, "threshold": r.threshold,
                "for_s": r.for_s, "state": state,
                "since_unix": round(since, 3),
                "breaching": breaching,
                "transitions": transitions,
            }
            if r.q is not None:
                entry["q"] = r.q
            if r.tags:
                entry["tags"] = list(r.tags)
            if r.lo is not None:
                entry["lo"], entry["hi"] = r.lo, r.hi
            entry["value"] = (None if np.isnan(value)
                              else round(float(value), 6))
            out_rules.append(entry)
        return {
            "interval_s": self.interval_s,
            "rules": out_rules,
            "evals_total": self.evals_total,
            "transitions_total": self.transitions_total,
            "reloads_total": self.reloads_total,
            "generated_unix": round(time.time(), 3),
        }

    def telemetry_rows(self) -> List[tuple]:
        with self._lock:
            rules = list(self._rules)
            states = {rid: (st.state, st.last_value)
                      for rid, st in self._states.items()}
        rows: List[tuple] = [
            ("alert.rules", "gauge", float(len(rules)), ()),
            ("alert.evals_total", "counter", float(self.evals_total), ()),
            ("alert.transitions_total", "counter",
             float(self.transitions_total), ()),
            ("alert.rule_errors_total", "counter",
             float(self.rule_errors_total), ()),
            ("alert.suppressed_logs_total", "counter",
             float(self.suppressed_logs_total), ()),
        ]
        for r in rules:
            state, value = states.get(r.id, ("idle", float("nan")))
            tags = [f"rule:{r.id}"]
            rows.append(("alert.state", "gauge",
                         STATE_CODES.get(state, 0.0), tags))
            rows.append(("alert.firing", "gauge",
                         1.0 if state == "firing" else 0.0, tags))
            if not np.isnan(value):
                rows.append(("alert.value", "gauge", float(value), tags))
        snap = self._eval_hist.snapshot()
        for label in ("p50", "p99", "max"):
            rows.append((f"alert.eval.{label}", "gauge", snap[label], ()))
        rows.append(("alert.eval.count", "counter",
                     float(snap["count"]), ()))
        return rows
