"""Device capacity & shard-balance observatory.

Six observability layers watch the host side (telemetry, cardinality,
latency waterfall, flow ledger, tracing, live queries) but the device
plane — where the column store keeps live generations, recycled donated
spares, flush-inflight snapshots, prewarm-rung throwaways, and reshard
capture buffers — was a black box. This module is the accounting layer
for it, three planes wired through the existing registries:

- **HBM ledger** — every `_BaseTable` generation registers its arrays'
  nbytes as a *token* tagged family / table / shard / lifecycle state
  (``live`` / ``spare`` / ``inflight`` / ``prewarm`` /
  ``reshard_capture``). Lifecycle transitions *retag* the token (a
  recycled spare is shape-identical to the generation it was captured
  from, so nbytes is conserved) and every exit path — donation failure,
  capacity mismatch, topology-epoch mismatch, cutover merge — *drops*
  it. The invariant the conservation tests pin: ``total_bytes()`` equals
  the exact sum of registered generation nbytes at every step of
  swap / resize / prewarm / reshard. The total is reconciled against
  ``jax.device_memory_stats`` where the backend provides it (TPU/GPU;
  the CPU backend reports nothing) and feeds the overload ladder's
  device watermark rung (`overload_device_soft_bytes` /
  `_hard_bytes`) beside the RSS rung.
- **Kernel registry** — the jitted apply / readout / merge / reset /
  prewarm kernels register dispatch counts and wall time into
  per-(kind, family) LatencyHist rows (`device.kernel.*`), plus
  compile/retrace counts generalizing the PR-10/15 compile-cache probe
  beyond the resize hook: prewarm-rung compiles and post-resize
  retraces land in the same `device.compile.*` counters.
- **Shard-balance observatory** — computed at scrape time from the
  attached store's digest-routed tables: per-shard live rows and
  samples-routed, a digest-space occupancy histogram, the skew ratio
  ``device.shard.skew = max/mean`` that a `shard_skew` alert rule can
  watch, hot-shard detection (> `HOT_SHARD_FACTOR` x mean), and a
  recommended reshard plan that projects live digests onto candidate
  shard counts and prices the best one in `migration_cells` moved rows.

Everything is scrape-time or O(1)-under-a-lock on the hot path, and the
whole observatory is gated by the `device_observatory` config knob (a
`slow`-marked soak pins total cost under 2% of flush wall time, the
same bar as the latency/cardinality observatories). The full ledger +
kernel table + balance report is served at ``GET /debug/device``.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from veneur_tpu.core.latency import LatencyHist

# lifecycle states a generation token may carry
STATE_LIVE = "live"
STATE_SPARE = "spare"
STATE_INFLIGHT = "inflight"
STATE_PREWARM = "prewarm"
STATE_RESHARD = "reshard_capture"

_STATES = (STATE_LIVE, STATE_SPARE, STATE_INFLIGHT, STATE_PREWARM,
           STATE_RESHARD)

# kernel kinds the registry tracks; each timed kind renders a
# `device.kernel.<kind>_s` llhist series (p50/p99/max gauges + count
# counter). Listed literally so scripts/check_metric_names.py can lint
# the expanded names against the README inventory.
KERNEL_KINDS = ("apply", "readout", "merge", "reset", "prewarm")
HIST_ROWS = ("device.kernel.apply_s", "device.kernel.readout_s",
             "device.kernel.merge_s", "device.kernel.reset_s",
             "device.kernel.prewarm_s")

# a shard is "hot" above this multiple of the mean live-row count
HOT_SHARD_FACTOR = 2.0

# digest-space occupancy histogram resolution (bins over [0, 2^64))
DIGEST_BINS = 16

_U64 = np.uint64


def _nbytes_of(arrays: Any) -> int:
    """Sum of nbytes over all array leaves of a state pytree. Works on
    jax.Arrays, numpy arrays, and the dataclass/tuple states the tables
    use; non-array leaves (ints, None) contribute nothing."""
    try:
        import jax
        leaves = jax.tree_util.tree_leaves(arrays)
    except Exception:  # pragma: no cover - jax always importable here
        leaves = [arrays]
    total = 0
    for leaf in leaves:
        n = getattr(leaf, "nbytes", None)
        if n is not None:
            total += int(n)
    return total


def backend_memory_stats() -> List[dict]:
    """Per-device allocator stats where the backend exposes them
    (TPU/GPU `memory_stats()`; CPU returns None). Used to reconcile the
    ledger against what the runtime actually holds."""
    rows: List[dict] = []
    try:
        import jax
        devices = jax.devices()
    except Exception:  # pragma: no cover
        return rows
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        rows.append({
            "device": getattr(d, "id", None),
            "platform": getattr(d, "platform", ""),
            "bytes_in_use": int(stats.get("bytes_in_use", 0)),
            "bytes_limit": int(stats.get("bytes_limit", 0)),
            "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0)),
        })
    return rows


class _Token:
    __slots__ = ("family", "table", "state", "nbytes", "shard")

    def __init__(self, family: str, table: str, state: str, nbytes: int,
                 shard: Optional[int]):
        self.family = family
        self.table = table
        self.state = state
        self.nbytes = nbytes
        self.shard = shard


class DeviceObservatory:
    """One server's (or standalone store's) device observatory.

    Disabled, every note_* call is a cheap early return and
    `note_generation` hands back None (retag/drop tolerate None), so
    the hook sites in the column store cost one attribute read — the
    <2% overhead guard's off switch."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._tokens: Dict[int, _Token] = {}
        self._next_token = 1
        self._total = 0           # running sum of registered nbytes
        self.peak_bytes = 0       # high-water mark of _total
        # kernel registry: (kind, family) -> dispatch count / hist
        self._dispatch: Dict[Tuple[str, str], int] = {}
        self._kernel_hists: Dict[Tuple[str, str], LatencyHist] = {}
        # compile/retrace counts + last compile wall per family
        self._compiles: Dict[str, int] = {}
        self._compile_seconds: Dict[str, float] = {}
        # shard-balance plane reads the attached store at scrape time
        self._store = None
        self._resize_events = 0

    # ------------------------------------------------------------------
    # HBM ledger
    # ------------------------------------------------------------------

    def note_generation(self, family: str, state: str, arrays: Any,
                        table: Optional[str] = None,
                        shard: Optional[int] = None) -> Optional[int]:
        """Register one device generation; returns an opaque token used
        to retag/drop it across lifecycle transitions, or None when the
        observatory is disabled or the state holds no arrays."""
        if not self.enabled or arrays is None:
            return None
        nbytes = _nbytes_of(arrays)
        if nbytes <= 0:
            return None
        with self._lock:
            tok = self._next_token
            self._next_token += 1
            self._tokens[tok] = _Token(family, table or family, state,
                                       nbytes, shard)
            self._total += nbytes
            if self._total > self.peak_bytes:
                self.peak_bytes = self._total
        return tok

    def retag(self, token: Optional[int], new_state: str) -> None:
        """Move a registered generation to a new lifecycle state. The
        bytes stay registered — a retag conserves the ledger total."""
        if token is None:
            return
        with self._lock:
            t = self._tokens.get(token)
            if t is not None:
                t.state = new_state

    def drop(self, token: Optional[int]) -> None:
        """Unregister a generation (donated away, freed, or merged)."""
        if token is None:
            return
        with self._lock:
            t = self._tokens.pop(token, None)
            if t is not None:
                self._total -= t.nbytes

    def total_bytes(self) -> int:
        with self._lock:
            return self._total

    def note_resize(self) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._resize_events += 1

    def ledger(self) -> dict:
        """Full ledger breakdown: per-family per-state bytes, per-table
        rows, totals, peak, and a forecast-to-next-resize row (a grow
        doubles the live generation, so next-resize demand is live
        bytes x2 for the growing family — the report forecasts the
        worst case: every family doubling at once)."""
        with self._lock:
            toks = [(t.family, t.table, t.state, t.nbytes, t.shard)
                    for t in self._tokens.values()]
            total, peak = self._total, self.peak_bytes
        by_family: Dict[str, Dict[str, int]] = {}
        by_table: Dict[str, dict] = {}
        live_total = 0
        for family, table, state, nbytes, shard in toks:
            fam = by_family.setdefault(
                family, {s: 0 for s in _STATES})
            fam[state] = fam.get(state, 0) + nbytes
            row = by_table.setdefault(
                table, {"family": family, "bytes": 0, "states": {}})
            row["bytes"] += nbytes
            row["states"][state] = row["states"].get(state, 0) + nbytes
            if shard is not None:
                row["shard"] = shard
            if state == STATE_LIVE:
                live_total += nbytes
        return {
            "total_bytes": total,
            "peak_bytes": peak,
            "live_bytes": live_total,
            # worst-case demand at the next capacity rung: every live
            # generation doubles (grow policy) while the old one is
            # still resident for the copy
            "forecast_next_resize_bytes": live_total * 2,
            "generations": len(toks),
            "by_family": by_family,
            "by_table": by_table,
        }

    # ------------------------------------------------------------------
    # Kernel registry
    # ------------------------------------------------------------------

    def note_kernel(self, kind: str, family: str,
                    seconds: Optional[float] = None, n: int = 1) -> None:
        """Record `n` dispatches of a jitted kernel; `seconds` (when the
        caller timed the dispatch) feeds the `device.kernel.<kind>_s`
        llhist for that family."""
        if not self.enabled:
            return
        key = (kind, family)
        with self._lock:
            self._dispatch[key] = self._dispatch.get(key, 0) + n
            if seconds is not None:
                hist = self._kernel_hists.get(key)
                if hist is None:
                    hist = self._kernel_hists[key] = LatencyHist(
                        f"device.kernel.{kind}_s")
        if seconds is not None:
            hist.observe(seconds)

    def note_compile(self, family: str,
                     seconds: Optional[float] = None) -> None:
        """Record one XLA compile/retrace for `family` (prewarm-rung
        compile, post-resize retrace, or first-dispatch trace)."""
        if not self.enabled:
            return
        with self._lock:
            self._compiles[family] = self._compiles.get(family, 0) + 1
            if seconds is not None:
                self._compile_seconds[family] = float(seconds)

    def kernel_report(self) -> dict:
        with self._lock:
            dispatch = dict(self._dispatch)
            hists = dict(self._kernel_hists)
            compiles = dict(self._compiles)
            compile_s = dict(self._compile_seconds)
        kernels: List[dict] = []
        for (kind, family), count in sorted(dispatch.items()):
            row = {"kind": kind, "family": family, "dispatches": count}
            hist = hists.get((kind, family))
            if hist is not None:
                row["wall"] = hist.snapshot()
            kernels.append(row)
        return {
            "kernels": kernels,
            "compiles": compiles,
            "last_compile_seconds": compile_s,
        }

    # ------------------------------------------------------------------
    # Shard-balance observatory
    # ------------------------------------------------------------------

    def attach_store(self, store) -> None:
        self._store = store

    def _sharded_tables(self) -> List[Tuple[str, Any]]:
        store = self._store
        if store is None:
            return []
        out = []
        for family, table in store.tables():
            if getattr(table, "_shard_of", None) is not None \
                    and getattr(table, "_n_shards", 0) > 1:
                out.append((family, table))
        return out

    def shard_balance(self) -> Optional[dict]:
        """Per-shard live rows / samples-routed / digest occupancy for
        the attached store's digest-routed tables; None when the store
        isn't sharded. Reads host-side routing arrays only — no device
        sync."""
        tables = self._sharded_tables()
        if not tables:
            return None
        store = self._store
        plane = getattr(store, "shard_plane", None)
        n_shards = tables[0][1]._n_shards
        rows = np.zeros(n_shards, np.int64)
        digest_hist = np.zeros(DIGEST_BINS, np.int64)
        per_family: Dict[str, list] = {}
        digests_all: List[np.ndarray] = []
        shift = _U64(64 - (DIGEST_BINS.bit_length() - 1))
        for family, table in tables:
            with table.lock:
                n = len(table.meta)
                shard_of = np.asarray(table._shard_of[:n])
                live = np.asarray(table._has_meta[:n], bool)
                # dict keys are (digest64 << 2) | scope — wider than 64
                # bits as Python ints, so mask before the uint64 cast
                dig_list = [(dk >> 2) & 0xFFFFFFFFFFFFFFFF
                            for row, dk in enumerate(table._dict_key_of)
                            if row < n and live[row]]
            fam_rows = np.bincount(shard_of[live].astype(np.int64),
                                   minlength=n_shards)[:n_shards]
            rows += fam_rows
            per_family[family] = [int(x) for x in fam_rows]
            if dig_list:
                digests = np.asarray(dig_list, np.uint64)
                digests_all.append(digests)
                digest_hist += np.bincount(
                    (digests >> shift).astype(np.int64),
                    minlength=DIGEST_BINS)[:DIGEST_BINS]
        mean = float(rows.mean()) if rows.size else 0.0
        skew = float(rows.max() / mean) if mean > 0 else None
        hot = [int(i) for i in np.nonzero(
            rows > HOT_SHARD_FACTOR * mean)[0]] if mean > 0 else []
        samples: Dict[str, list] = {}
        if plane is not None:
            for family, acc in getattr(plane, "_samples", {}).items():
                samples[family] = [int(x) for x in acc]
        out = {
            "n_shards": int(n_shards),
            "rows_per_shard": [int(x) for x in rows],
            "rows_per_shard_by_family": per_family,
            "samples_routed": samples,
            "digest_occupancy": [int(x) for x in digest_hist],
            "skew": skew,
            "hot_shards": hot,
        }
        plan = self._reshard_plan(digests_all, int(n_shards), rows)
        if plan is not None:
            out["reshard_plan"] = plan
        return out

    def _reshard_plan(self, digests_all: List[np.ndarray], n_old: int,
                      rows: np.ndarray) -> Optional[dict]:
        """Project live digests onto candidate shard counts and price
        the best one: projected skew + migration_cells cost in moved
        rows. Only a recommendation — the reshard controller cuts over."""
        if not digests_all:
            return None
        try:
            import jax
            max_m = len(jax.devices())
        except Exception:  # pragma: no cover
            max_m = n_old
        digests = np.concatenate(digests_all)
        if digests.size == 0 or max_m < 2:
            return None
        # digest-home routing: home = (digest * M) >> 64, computed via
        # the 128-bit object path (numpy has no u128)
        dig_obj = digests.astype(object)
        old_home = np.asarray([(int(d) * n_old) >> 64 for d in dig_obj],
                              np.int64)
        best = None
        for m in range(2, max_m + 1):
            if m == n_old:
                continue
            new_home = np.asarray([(int(d) * m) >> 64 for d in dig_obj],
                                  np.int64)
            proj = np.bincount(new_home, minlength=m)[:m]
            mean = float(proj.mean())
            if mean <= 0:
                continue
            proj_skew = float(proj.max() / mean)
            moved = int(np.count_nonzero(old_home != new_home))
            cand = (proj_skew, moved, m)
            if best is None or cand < best:
                best = cand
        if best is None:
            return None
        proj_skew, moved, m = best
        try:
            from veneur_tpu.parallel.reshard import migration_cells
            cells = len(migration_cells(n_old, m))
        except Exception:
            cells = None
        return {
            "from_shards": n_old,
            "to_shards": m,
            "projected_skew": proj_skew,
            "rows_moved": moved,
            "migration_cells": cells,
        }

    def shard_skew(self) -> Optional[float]:
        """max/mean live-row ratio across shards; None when the store
        isn't sharded or holds no rows — the `shard_skew` alert rule's
        and `device.shard.skew` gauge's source."""
        tables = self._sharded_tables()
        if not tables:
            return None
        n_shards = tables[0][1]._n_shards
        rows = np.zeros(n_shards, np.int64)
        for _family, table in tables:
            with table.lock:
                n = len(table.meta)
                shard_of = np.asarray(table._shard_of[:n])
                live = np.asarray(table._has_meta[:n], bool)
            rows += np.bincount(shard_of[live].astype(np.int64),
                                minlength=n_shards)[:n_shards]
        mean = float(rows.mean()) if rows.size else 0.0
        if mean <= 0:
            return None
        return float(rows.max() / mean)

    # ------------------------------------------------------------------
    # Export
    # ------------------------------------------------------------------

    def telemetry_rows(self) -> List[tuple]:
        if not self.enabled:
            return []
        rows: List[tuple] = []
        led = self.ledger()
        rows.append(("device.mem.total_bytes", "gauge",
                     float(led["total_bytes"]), ()))
        rows.append(("device.mem.peak_bytes", "gauge",
                     float(led["peak_bytes"]), ()))
        rows.append(("device.mem.forecast_next_resize_bytes", "gauge",
                     float(led["forecast_next_resize_bytes"]), ()))
        rows.append(("device.mem.generations", "gauge",
                     float(led["generations"]), ()))
        for family, states in sorted(led["by_family"].items()):
            for state, nbytes in sorted(states.items()):
                if nbytes:
                    rows.append(("device.mem.bytes", "gauge",
                                 float(nbytes),
                                 (f"family:{family}", f"state:{state}")))
        with self._lock:
            dispatch = dict(self._dispatch)
            hists = dict(self._kernel_hists)
            compiles = dict(self._compiles)
        for (kind, family), count in sorted(dispatch.items()):
            rows.append(("device.kernel.dispatches", "counter",
                         float(count),
                         (f"kind:{kind}", f"family:{family}")))
        for (kind, family), hist in sorted(hists.items()):
            snap = hist.snapshot()
            tags = (f"family:{family}",)
            base = f"device.kernel.{kind}_s"
            for label in ("p50", "p99", "max"):
                rows.append((f"{base}.{label}", "gauge", snap[label],
                             tags))
            rows.append((f"{base}.count", "counter",
                         float(snap["count"]), tags))
        for family, count in sorted(compiles.items()):
            rows.append(("device.compile.count", "counter", float(count),
                         (f"family:{family}",)))
        skew = self.shard_skew()
        if skew is not None:
            rows.append(("device.shard.skew", "gauge", skew, ()))
        return rows

    def report(self) -> dict:
        """The `/debug/device` payload: ledger + backend reconciliation
        + kernel table + shard balance."""
        led = self.ledger()
        backend = backend_memory_stats()
        recon = None
        if backend:
            in_use = sum(r["bytes_in_use"] for r in backend)
            recon = {
                "backend_bytes_in_use": in_use,
                "ledger_bytes": led["total_bytes"],
                # allocator slack: runtime-held bytes the ledger doesn't
                # model (XLA scratch, executables, donation slop)
                "unaccounted_bytes": in_use - led["total_bytes"],
            }
        out = {
            "generated_unix": time.time(),
            "enabled": self.enabled,
            "ledger": led,
            "backend_devices": backend,
            "reconciliation": recon,
            **self.kernel_report(),
        }
        balance = self.shard_balance()
        if balance is not None:
            out["shard_balance"] = balance
        return out
