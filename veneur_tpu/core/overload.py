"""Ingest admission control, overload degradation & pipeline supervision.

The ingest-side counterpart of util/resilience.py (PR 2 made egress fail
gracefully; this module makes ingest degrade loudly, never wedge —
SURVEY §1's operational contract, and the SALSA principle of shedding
precision under pressure, never correctness). Four pieces:

- `TokenBucket` / admission: per-plane (statsd, ssf) token-bucket rate
  limits. A packet over budget is NOT silently dropped: the shed ladder
  drops spans first, then histogram/set samples, and never counter/gauge
  deltas — an over-limit statsd packet still parses, but only its
  essential (counter/gauge) samples are kept. Every shed sample is
  counted in `ingest.shed_total` (class: tag).

- `KernelDropMonitor`: the kernel's own UDP drop counter, polled from
  `/proc/net/udp{,6}` by socket inode (SO_RXQ_OVFL ancillary data needs
  recvmsg; the proc counter covers the same loss and costs one read per
  poll). Invisible kernel loss becomes `ingest.kernel_drops` in
  /metrics.

- `WatermarkMonitor`: soft/hard RSS thresholds stepping the server
  through ok -> degraded -> shedding. Degraded tightens sampling
  (histogram/set samples admitted at `overload_watermark_degraded_keep`)
  and pauses span ingest; shedding drops histogram/set samples entirely.
  Counter/gauge deltas are admitted in every state. Chaos can add
  simulated pressure (`chaos_ingest_rss_bytes`) so the ladder is
  soak-testable without actually ballooning the heap.

- `Supervisor`: heartbeat watcher over the long-lived pipeline threads
  (ingest pump dispatch, span workers, flush loop). A component whose
  heartbeat goes stale beyond `supervisor_deadline` is logged at ERROR
  and exported (`supervisor.stalls_total`); one stalled past
  `supervisor_escalation_deadline` escalates to the crash machinery
  (faulthandler dump + hard exit — crash = recovery, util/crash.py),
  exactly like the flush watchdog. Numeric probes (native
  `vnt_pump_stalls`) ride along as monotonic stall counters.

Everything is thread-safe, allocation-bounded, and exported through one
`telemetry_rows` collector (`OverloadManager`).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

logger = logging.getLogger("veneur_tpu.overload")

# degradation ladder states (gauge values for /metrics)
OK = "ok"
DEGRADED = "degraded"
SHEDDING = "shedding"
STATE_CODES = {OK: 0, DEGRADED: 1, SHEDDING: 2}

# shed ladder classes, least- to most-protected. Spans go first (they
# are derived/redundant observability), histogram/set samples next
# (they lose precision, not truth — percentiles from a sample survive),
# counter/gauge deltas never (losing a delta corrupts the sum forever).
CLASS_SPAN = "span"
CLASS_HISTOGRAM = "histogram"
CLASS_SET = "set"

_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


class TokenBucket:
    """Classic token bucket: `rate` tokens/s refill, `burst` capacity.
    `admit(n)` takes n tokens if available (all-or-nothing, packets are
    atomic); thread-safe; a rate of 0 admits everything."""

    def __init__(self, rate: float, burst: float, clock=time.monotonic):
        self.rate = max(0.0, float(rate))
        self.burst = max(1.0, float(burst)) if self.rate else 0.0
        self._tokens = self.burst
        self._clock = clock
        self._last = clock()
        self._lock = threading.Lock()

    def admit(self, n: float = 1.0) -> bool:
        if self.rate <= 0:
            return True
        with self._lock:
            now = self._clock()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.rate)
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def admit_debt(self, n: float = 1.0) -> bool:
        """Batch-metering variant: admit whenever the bucket is positive
        and charge the FULL cost, letting the balance go negative (debt
        repaid by refill before anything else admits). All-or-nothing
        `admit` starves any batch larger than one burst forever; debt
        admission keeps the long-run rate exactly `rate` for arbitrarily
        large batches, with overshoot bounded by one batch."""
        if self.rate <= 0:
            return True
        with self._lock:
            now = self._clock()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.rate)
            self._last = now
            if self._tokens > 0:
                self._tokens -= n
                return True
            return False


class KernelDropMonitor:
    """Polls /proc/net/udp{,6} for the drops column of watched sockets.

    Sockets are matched by inode (stable across the socket's life,
    immune to REUSEPORT port sharing). The exported value is the summed
    per-socket delta since watching began, so a listener restart never
    double-counts. Off Linux (no /proc/net/udp) the monitor is inert.
    """

    PROC_FILES = ("/proc/net/udp", "/proc/net/udp6")

    def __init__(self):
        self._lock = threading.Lock()
        # inode -> [label, baseline (first-seen drops), last-seen drops]
        self._watched: Dict[int, list] = {}
        self._totals: Dict[str, int] = {}  # label -> accumulated delta

    @property
    def watching(self) -> bool:
        with self._lock:
            return bool(self._watched)

    def watch_socket(self, sock, label: str) -> None:
        """Register a bound UDP socket for drop polling."""
        try:
            inode = os.fstat(sock.fileno()).st_ino
        except OSError:
            return
        with self._lock:
            self._watched[inode] = [label, None, 0]
            self._totals.setdefault(label, 0)

    @staticmethod
    def parse_proc_udp(text: str) -> Dict[int, int]:
        """`/proc/net/udp` rows -> {inode: drops}. The drops column is
        the last field; inode is field 9 (0-based, after the header)."""
        out: Dict[int, int] = {}
        for line in text.splitlines()[1:]:
            fields = line.split()
            if len(fields) < 13:
                continue
            try:
                out[int(fields[9])] = int(fields[12])
            except ValueError:
                continue
        return out

    def _read_proc(self) -> Dict[int, int]:
        merged: Dict[int, int] = {}
        for path in self.PROC_FILES:
            try:
                with open(path) as f:
                    merged.update(self.parse_proc_udp(f.read()))
            except OSError:
                continue
        return merged

    def poll(self) -> int:
        """One scan; returns the total new drops observed this poll."""
        with self._lock:
            if not self._watched:
                return 0
        by_inode = self._read_proc()
        fresh = 0
        with self._lock:
            for inode, entry in self._watched.items():
                drops = by_inode.get(inode)
                if drops is None:
                    continue  # socket gone or proc row unreadable
                label, baseline, last = entry
                if baseline is None:
                    # first sighting: pre-existing drops are not ours
                    entry[1] = entry[2] = drops
                    continue
                delta = drops - last
                if delta > 0:
                    self._totals[label] = self._totals.get(label, 0) + delta
                    fresh += delta
                entry[2] = drops
        return fresh

    def totals(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._totals)


def current_rss_bytes() -> Optional[int]:
    """Current resident set from /proc/self/statm (shared with
    core/diagnostics.py); None off Linux."""
    try:
        with open("/proc/self/statm", "rb") as f:
            return int(f.read().split()[1]) * _PAGE_SIZE
    except (OSError, IndexError, ValueError):
        return None


class WatermarkMonitor:
    """RSS watermarks -> the ok/degraded/shedding ladder.

    `observe(rss)` applies the thresholds; `tick()` reads real RSS
    (plus any chaos-simulated pressure) and applies it. Recovery is
    immediate — one observation below the soft watermark returns to ok
    (the acceptance contract: back to ok within one interval of
    pressure release)."""

    def __init__(self, soft_bytes: int = 0, hard_bytes: int = 0,
                 on_transition: Optional[Callable[[str, str, int], None]]
                 = None, rss_reader=current_rss_bytes,
                 pressure: Optional[Callable[[], int]] = None):
        self.soft_bytes = int(soft_bytes)
        self.hard_bytes = int(hard_bytes)
        self.on_transition = on_transition
        self._rss_reader = rss_reader
        self._pressure = pressure  # chaos: extra simulated bytes
        self._lock = threading.Lock()
        self.state = OK
        self.last_rss = 0
        self.transitions = 0

    @property
    def enabled(self) -> bool:
        return self.soft_bytes > 0 or self.hard_bytes > 0

    def tick(self) -> str:
        if not self.enabled:
            return self.state  # don't even read /proc when disabled
        rss = self._rss_reader()
        if rss is None:
            # off-Linux: chaos-simulated pressure must still drive the
            # ladder (the soak/drill path), just without a real reading
            rss = 0
        if self._pressure is not None:
            try:
                rss += int(self._pressure())
            except Exception:
                pass
        return self.observe(rss)

    def observe(self, rss: int) -> str:
        if not self.enabled:
            return OK
        if self.hard_bytes and rss >= self.hard_bytes:
            new = SHEDDING
        elif self.soft_bytes and rss >= self.soft_bytes:
            new = DEGRADED
        else:
            new = OK
        with self._lock:
            self.last_rss = rss
            old, self.state = self.state, new
            if new != old:
                self.transitions += 1
        if new != old:
            log = (logger.error if new == SHEDDING
                   else logger.warning if new == DEGRADED else logger.info)
            log("overload state %s -> %s (rss=%d soft=%d hard=%d)",
                old, new, rss, self.soft_bytes, self.hard_bytes)
            if self.on_transition is not None:
                try:
                    self.on_transition(old, new, rss)
                except Exception:
                    logger.exception("overload transition hook failed")
        return new


class Supervisor:
    """Heartbeat watcher for the long-lived pipeline threads.

    Components `register` (or implicitly via the first `beat`) and then
    beat from their loop bodies; `probe`s are polled callables returning
    a monotonic stall counter (the native pump's `vnt_pump_stalls`).
    The watch loop runs on its own daemon thread at `poll_interval`;
    a component overdue past `deadline` is flagged (ERROR log + stall
    counter + event), and one overdue past `escalation_deadline` (when
    > 0) calls `escalate` — by default the flush-watchdog abort path:
    dump all thread stacks and exit hard so the process supervisor
    restarts a wedged instance (crash = recovery)."""

    def __init__(self, deadline: float, poll_interval: float = 1.0,
                 escalation_deadline: float = 0.0,
                 on_stall: Optional[Callable[[str, float], None]] = None,
                 escalate: Optional[Callable[[str, float], None]] = None,
                 clock=time.monotonic):
        self.deadline = float(deadline)
        self.poll_interval = max(0.05, float(poll_interval))
        self.escalation_deadline = float(escalation_deadline)
        self.on_stall = on_stall
        self._escalate = escalate if escalate is not None else _hard_abort
        self._clock = clock
        self._lock = threading.Lock()
        self._beats: Dict[str, float] = {}
        self._deadlines: Dict[str, float] = {}  # per-component overrides
        self._stalled: Dict[str, float] = {}  # name -> first-flagged at
        self.stall_counts: Dict[str, int] = {}
        self._probes: List[Tuple[str, Callable[[], int], int]] = []
        self.probe_stalls: Dict[str, int] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- component API ---------------------------------------------------

    def register(self, name: str,
                 deadline: Optional[float] = None) -> None:
        """`deadline` overrides the global one for this component — the
        flush loop beats once per interval, so its deadline must exceed
        the interval regardless of how tight the global deadline is."""
        with self._lock:
            self._beats.setdefault(name, self._clock())
            if deadline is not None:
                self._deadlines[name] = float(deadline)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._beats.pop(name, None)
            self._deadlines.pop(name, None)
            self._stalled.pop(name, None)
            # drop the component's probes too: a probe closure keeps its
            # owner (e.g. the native Pump) alive and polled forever, and
            # a listener restart would double-register under the name
            self._probes = [p for p in self._probes if p[0] != name]
            self.probe_stalls.pop(name, None)

    def beat(self, name: str) -> None:
        now = self._clock()
        with self._lock:
            self._beats[name] = now
            if name in self._stalled:
                del self._stalled[name]
                recovered = True
            else:
                recovered = False
        if recovered:
            logger.info("supervisor: %s heartbeat recovered", name)

    def add_probe(self, name: str, fn: Callable[[], int]) -> None:
        """A monotonic counter to watch; increases surface as stalls."""
        with self._lock:
            self._probes.append((name, fn, 0))
            self.probe_stalls.setdefault(name, 0)

    # -- watch loop ------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self.deadline > 0

    def start(self) -> None:
        if not self.enabled or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="pipeline-supervisor", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_interval):
            try:
                self.check()
            except Exception:
                logger.exception("supervisor check failed")

    def check(self) -> List[str]:
        """One supervision pass; returns the names flagged stalled."""
        now = self._clock()
        flagged: List[str] = []
        with self._lock:
            beats = dict(self._beats)
            deadlines = dict(self._deadlines)
            probes = list(self._probes)
        for name, last in beats.items():
            age = now - last
            if age <= deadlines.get(name, self.deadline):
                continue
            with self._lock:
                fresh = name not in self._stalled
                if fresh:
                    self._stalled[name] = now
                    self.stall_counts[name] = \
                        self.stall_counts.get(name, 0) + 1
                first = self._stalled[name]
            if fresh:
                flagged.append(name)
                logger.error(
                    "supervisor: %s stalled — no heartbeat for %.1fs "
                    "(deadline %.1fs)", name, age,
                    deadlines.get(name, self.deadline))
                if self.on_stall is not None:
                    try:
                        self.on_stall(name, age)
                    except Exception:
                        logger.exception("supervisor stall hook failed")
            stalled_for = now - first
            if (self.escalation_deadline > 0
                    and stalled_for >= self.escalation_deadline):
                logger.critical(
                    "supervisor: %s stalled past the escalation deadline "
                    "(%.1fs); escalating", name, stalled_for)
                self._escalate(name, age)
        for name, fn, seen in probes:
            try:
                value = int(fn())
            except Exception:
                continue
            if value > seen:
                with self._lock:
                    # identity-matched update: unregister() may have
                    # removed entries since the snapshot, so positional
                    # indexing would corrupt a different probe
                    for j, entry in enumerate(self._probes):
                        if entry[0] == name and entry[1] is fn:
                            self._probes[j] = (name, fn, value)
                            break
                    else:
                        continue  # unregistered mid-check: discard
                    self.probe_stalls[name] = \
                        self.probe_stalls.get(name, 0) + (value - seen)
                    total = self.probe_stalls[name]
                logger.warning(
                    "supervisor: probe %s advanced by %d (total %d)",
                    name, value - seen, total)
        return flagged

    def stalled_components(self) -> List[str]:
        with self._lock:
            return sorted(self._stalled)

    def counts_snapshot(self) -> Tuple[Dict[str, int], Dict[str, int]]:
        """(stall_counts, probe_stalls) copies for scrape-time export —
        check() mutates both concurrently on the watch thread."""
        with self._lock:
            return dict(self.stall_counts), dict(self.probe_stalls)


def _hard_abort(name: str, age: float) -> None:
    """Default escalation: the flush-watchdog abort path (crash =
    recovery). Reports through the crash machinery (util/crash.py —
    Sentry-equivalent reporters see the stall before the process
    dies), dumps every thread's stack so the wedge is attributable
    post-mortem, then exits hard — daemon threads can't block it."""
    from veneur_tpu.util import crash
    try:
        raise RuntimeError(
            f"pipeline supervisor: {name} stalled for {age:.1f}s "
            f"past the escalation deadline")
    except RuntimeError as exc:
        try:
            crash.consume_panic(exc)  # logs critical + notifies reporters
        except RuntimeError:
            pass  # consume_panic re-raises by contract; we exit below
    import faulthandler
    faulthandler.dump_traceback(all_threads=True)
    os._exit(3)


class OverloadManager:
    """One server's overload posture: admission buckets, the watermark
    ladder, kernel-drop polling, and the supervisor — plus the single
    monitor thread that ticks the pollable pieces and the telemetry
    collector that exports all of it."""

    def __init__(self, config, chaos=None,
                 on_transition: Optional[Callable] = None,
                 on_stall: Optional[Callable] = None,
                 escalate: Optional[Callable] = None):
        burst_s = max(0.1, float(
            getattr(config, "ingest_rate_limit_burst", 1.0)))
        statsd_rate = float(getattr(config, "ingest_rate_limit_statsd", 0))
        span_rate = float(getattr(config, "ingest_rate_limit_spans", 0))
        self.statsd_bucket = TokenBucket(
            statsd_rate, statsd_rate * burst_s)
        self.span_bucket = TokenBucket(span_rate, span_rate * burst_s)
        self.degraded_keep = min(1.0, max(0.0, float(
            getattr(config, "overload_watermark_degraded_keep", 0.25))))
        self._keep_roll = 0  # deterministic 1-in-N admission counter
        self.watermarks = WatermarkMonitor(
            soft_bytes=getattr(config, "overload_watermark_soft_bytes", 0),
            hard_bytes=getattr(config, "overload_watermark_hard_bytes", 0),
            on_transition=on_transition,
            pressure=(chaos.simulated_rss_bytes if chaos is not None
                      else None))
        # device watermark rung: HBM occupancy from the device
        # observatory's ledger, beside the host-RSS rung. The byte
        # source attaches late (attach_device_source) because the
        # observatory is constructed after this manager; until then the
        # reader returns None and the rung observes 0.
        self._device_source: Optional[Callable[[], int]] = None
        self.device_watermarks = WatermarkMonitor(
            soft_bytes=getattr(config, "overload_device_soft_bytes", 0),
            hard_bytes=getattr(config, "overload_device_hard_bytes", 0),
            on_transition=on_transition,
            rss_reader=self._read_device_bytes)
        self.kernel_drops = KernelDropMonitor()
        self.supervisor = Supervisor(
            deadline=getattr(config, "supervisor_deadline", 0.0),
            poll_interval=getattr(config, "supervisor_poll", 1.0),
            escalation_deadline=getattr(
                config, "supervisor_escalation_deadline", 0.0),
            on_stall=on_stall, escalate=escalate)
        self.poll_interval = max(0.05, float(
            getattr(config, "overload_watermark_poll", 1.0)))
        self._shed_lock = threading.Lock()
        self.shed_total: Dict[str, int] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- state -----------------------------------------------------------

    def attach_device_source(self, fn: Callable[[], int]) -> None:
        """Wire the HBM-ledger byte source (DeviceObservatory
        .total_bytes) into the device watermark rung."""
        self._device_source = fn

    def _read_device_bytes(self) -> Optional[int]:
        fn = self._device_source
        if fn is None:
            return None
        try:
            return int(fn())
        except Exception:
            logger.exception("device watermark byte source failed")
            return None

    @property
    def state(self) -> str:
        # severity max across the RSS and device-HBM rungs: either
        # breaching degrades/sheds, so the ladder below reads ONE state
        code = max(STATE_CODES[self.watermarks.state],
                   STATE_CODES[self.device_watermarks.state])
        return (OK, DEGRADED, SHEDDING)[code]

    # -- admission (the shed ladder) -------------------------------------

    def shed(self, cls: str, n: int = 1, reason: str = "") -> None:
        """Account one shed decision; every dropped sample lands here."""
        key = f"{cls}|{reason}" if reason else cls
        with self._shed_lock:
            self.shed_total[key] = self.shed_total.get(key, 0) + n

    def shed_snapshot(self) -> Dict[str, int]:
        """Copy of the shed table (class|reason -> n) — the flow
        ledger's ingress.shed probe source."""
        with self._shed_lock:
            return dict(self.shed_total)

    def admit_span(self) -> bool:
        """Spans shed first: any degradation state pauses span ingest,
        and the span-plane token bucket bounds the happy path."""
        if self.state != OK:
            self.shed(CLASS_SPAN, reason="overload")
            return False
        if not self.span_bucket.admit():
            self.shed(CLASS_SPAN, reason="rate_limit")
            return False
        return True

    def admit_spans(self, n: int) -> bool:
        """Batch form of admit_span for the native SSF buffer path
        (all-or-nothing: a native batch ingests as one unit). The token
        ask is clamped to the bucket's capacity — a batch larger than
        one burst would otherwise NEVER fit and be shed forever even on
        an idle server; clamping keeps the long-run rate bounded while
        treating an oversized batch as one full burst."""
        if self.state != OK:
            self.shed(CLASS_SPAN, n, reason="overload")
            return False
        bucket = self.span_bucket
        ask = min(float(n), bucket.burst) if bucket.burst else float(n)
        if not bucket.admit(ask):
            self.shed(CLASS_SPAN, n, reason="rate_limit")
            return False
        return True

    def admit_statsd_packet(self) -> bool:
        """Packet-level admission for the statsd plane (the TCP line
        path, where the line is the intake unit). False does NOT mean
        drop-the-packet — it means parse it in essential-only mode
        (the shed ladder protects counter/gauge deltas)."""
        return self.statsd_bucket.admit()

    def admit_statsd_batch(self, n: int) -> bool:
        """Batch admission for the columnar statsd plane: ONE bucket
        take per parsed batch, token cost = the batch's sample count —
        so the rate limit meters actual sample load, not packet counts,
        and admission overhead amortizes over tens of thousands of
        samples. Debt-style (TokenBucket.admit_debt): the full cost is
        always charged, so the limit holds exactly even when one pump
        chunk carries more samples than a whole burst — while a batch
        larger than the burst still gets through once the bucket is
        positive instead of starving forever. False means the batch's
        histogram/set/llhist columns are shed with exact per-class
        counts; counter/gauge columns still land."""
        return self.statsd_bucket.admit_debt(float(n))

    def histo_set_keep(self) -> float:
        """Fraction of histogram/set samples to admit right now, for
        batch (native-column) consumers: 1.0 in ok, the degraded keep
        ratio in degraded, 0.0 in shedding."""
        state = self.state
        if state == SHEDDING:
            return 0.0
        if state == DEGRADED:
            return self.degraded_keep
        return 1.0

    def admit_sample(self, cls: str, over_limit: bool = False) -> bool:
        """Per-sample ladder for histogram/set samples. Counter/gauge
        samples never pass through here — they are always admitted."""
        state = self.state
        if state == SHEDDING or over_limit:
            self.shed(cls, reason="rate_limit" if over_limit else "overload")
            return False
        if state == DEGRADED:
            # deterministic keep-1-in-N tightening: keeps the sample
            # stream statistically useful while cutting device pressure
            keep_every = max(1, round(1.0 / self.degraded_keep)) \
                if self.degraded_keep > 0 else 0
            if keep_every == 0:
                self.shed(cls, reason="degraded")
                return False
            with self._shed_lock:
                self._keep_roll += 1
                keep = (self._keep_roll % keep_every) == 0
            if not keep:
                self.shed(cls, reason="degraded")
            return keep
        return True

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        self.supervisor.start()
        # the monitor thread only exists when it has something to poll:
        # watermarks configured, or UDP sockets registered for kernel-
        # drop visibility (Server.start() binds listeners before this)
        if self._thread is None and (self.watermarks.enabled
                                     or self.device_watermarks.enabled
                                     or self.kernel_drops.watching):
            self._thread = threading.Thread(
                target=self._monitor_loop, name="overload-monitor",
                daemon=True)
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self.supervisor.stop()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None

    def _monitor_loop(self) -> None:
        while not self._stop.wait(self.poll_interval):
            try:
                self.watermarks.tick()
                self.device_watermarks.tick()
                self.kernel_drops.poll()
            except Exception:
                logger.exception("overload monitor tick failed")

    # -- export ----------------------------------------------------------

    def telemetry_rows(self):
        """(name, kind, value, tags) rows for the /metrics collector."""
        rows = [("overload.state", "gauge",
                 float(STATE_CODES[self.state]), ()),
                ("overload.rss_state", "gauge",
                 float(STATE_CODES[self.watermarks.state]), ()),
                ("overload.rss_bytes", "gauge",
                 float(self.watermarks.last_rss), ()),
                ("overload.transitions", "counter",
                 float(self.watermarks.transitions), ()),
                ("overload.device_state", "gauge",
                 float(STATE_CODES[self.device_watermarks.state]), ()),
                ("overload.device_bytes", "gauge",
                 float(self.device_watermarks.last_rss), ()),
                ("overload.device_transitions", "counter",
                 float(self.device_watermarks.transitions), ())]
        with self._shed_lock:
            shed = dict(self.shed_total)
        for key, n in sorted(shed.items()):
            cls, _, reason = key.partition("|")
            tags = [f"class:{cls}"] + ([f"reason:{reason}"] if reason else [])
            rows.append(("ingest.shed_total", "counter", float(n), tags))
        for label, n in sorted(self.kernel_drops.totals().items()):
            rows.append(("ingest.kernel_drops", "counter", float(n),
                         [f"listener:{label}"]))
        sup = self.supervisor
        stall_counts, probe_stalls = sup.counts_snapshot()
        for name, n in sorted(stall_counts.items()):
            rows.append(("supervisor.stalls_total", "counter", float(n),
                         [f"component:{name}"]))
        for name, n in sorted(probe_stalls.items()):
            rows.append(("supervisor.probe_stalls_total", "counter",
                         float(n), [f"probe:{name}"]))
        rows.append(("supervisor.stalled_components", "gauge",
                     float(len(sup.stalled_components())), ()))
        return rows
