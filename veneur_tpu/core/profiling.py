"""Profiling surface: the pprof-equivalent for a TPU-hosted pipeline.

The reference always mounts Go pprof (reference http.go:53-63) and starts
a CPU profile when `enable_profiling` is set (reference
server.go:1382-1390). Python has no goroutine-style sampling profiler in
the stdlib, so this module provides:

  * StackSampler — a ~100 Hz all-threads stack sampler (the pprof CPU
    profile analog): aggregates `sys._current_frames()` into flat and
    cumulative hit counts per call site, reported as a text profile.
  * encode_pprof + sampler_to_pprof / heap_pprof / threads_pprof — real
    pprof wire format (hand-encoded profile.proto) for CPU, tracemalloc
    heap, and live-thread profiles; `go tool pprof` and speedscope read
    them directly.
  * capture_device_trace — a bounded `jax.profiler.trace` session whose
    output directory is zipped and returned (open in TensorBoard /
    xprof to see device timelines, XLA ops, and HBM traffic).
  * start_profile_server — `jax.profiler.start_server` for live
    TensorBoard capture, the idiomatic TPU profiling hook.

Wired to config `enable_profiling` (continuous sampler from startup) and
`profile_server_port`, and to the HTTP endpoints /debug/pprof/{profile,
heap,goroutine}, /debug/profile/cpu, and /debug/profile/device
(core.httpapi).
"""

from __future__ import annotations

import collections
import io
import logging
import os
import shutil
import sys
import tempfile
import threading
import time
import zipfile
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger("veneur_tpu.profiling")


class StackSampler:
    """Samples every thread's Python stack at `hz` and aggregates call
    sites. Flat hits = frames executing when sampled (self time);
    cumulative hits = frames anywhere on a sampled stack."""

    MAX_STACK_DEPTH = 64

    def __init__(self, hz: float = 100.0, collect_stacks: bool = False):
        self.hz = hz
        self._flat: collections.Counter = collections.Counter()
        self._cum: collections.Counter = collections.Counter()
        # full leaf-to-root stacks -> hits, for the pprof export. Only
        # request-scoped samplers collect these: leaf sites key on
        # f_lineno, so a continuous sampler would mint unbounded unique
        # stack tuples over a long-running server's lifetime
        self.collect_stacks = collect_stacks
        self._stacks: collections.Counter = collections.Counter()
        self._samples = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._started_at = 0.0

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._started_at = time.time()
        self._thread = threading.Thread(
            target=self._run, name="stack-sampler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=2.0)
        self._thread = None

    @property
    def running(self) -> bool:
        return self._thread is not None

    # -- sampling ---------------------------------------------------------

    def _run(self) -> None:
        period = 1.0 / self.hz
        me = threading.get_ident()
        while not self._stop.wait(period):
            self._sample_once(me)

    def _sample_once(self, skip_ident: int) -> None:
        frames = sys._current_frames()
        with self._lock:
            self._samples += 1
            for ident, frame in frames.items():
                if ident == skip_ident:
                    continue
                seen = set()
                top = True
                stack = []
                while frame is not None:
                    code = frame.f_code
                    site = (code.co_filename, code.co_name,
                            frame.f_lineno if top else code.co_firstlineno)
                    if top:
                        self._flat[site] += 1
                        top = False
                    if site not in seen:
                        self._cum[site] += 1
                        seen.add(site)
                    if (self.collect_stacks
                            and len(stack) < self.MAX_STACK_DEPTH):
                        stack.append(site)
                    frame = frame.f_back
                if self.collect_stacks:
                    self._stacks[tuple(stack)] += 1

    # -- reporting --------------------------------------------------------

    def snapshot(self) -> Tuple[int, List, List]:
        with self._lock:
            return (self._samples,
                    self._flat.most_common(),
                    self._cum.most_common())

    def reset(self) -> None:
        with self._lock:
            self._flat.clear()
            self._cum.clear()
            self._stacks.clear()
            self._samples = 0
            self._started_at = time.time()

    def report(self, top: int = 40) -> str:
        """pprof-style text profile: flat% then cum% per call site."""
        samples, flat, cum = self.snapshot()
        lines = [
            f"cpu profile: {samples} samples "
            f"({time.time() - self._started_at:.1f}s at {self.hz:.0f} Hz)",
            "",
            f"{'flat%':>7} {'hits':>8}  site (self time)",
        ]
        for site, hits in flat[:top]:
            pct = 100.0 * hits / max(1, samples)
            lines.append(f"{pct:6.1f}% {hits:8d}  "
                         f"{_short(site[0])}:{site[2]} {site[1]}")
        lines += ["", f"{'cum%':>7} {'hits':>8}  site (cumulative)"]
        for site, hits in cum[:top]:
            pct = 100.0 * hits / max(1, samples)
            lines.append(f"{pct:6.1f}% {hits:8d}  "
                         f"{_short(site[0])}:{site[2]} {site[1]}")
        return "\n".join(lines) + "\n"


def _short(path: str) -> str:
    parts = path.split(os.sep)
    return os.sep.join(parts[-3:]) if len(parts) > 3 else path


def sample_for(seconds: float, hz: float = 100.0, top: int = 40) -> str:
    """One-shot profile: sample for `seconds`, return the text report
    (the request-scoped mode when no continuous sampler is running)."""
    sampler = StackSampler(hz=hz)
    sampler.start()
    time.sleep(max(0.01, seconds))
    sampler.stop()
    return sampler.report(top=top)


# -- pprof wire format ------------------------------------------------------
# Hand-encoded https://github.com/google/pprof profile.proto (the schema
# is small and stable), so `go tool pprof` / speedscope / pyroscope read
# our CPU profiles directly — the reference serves real pprof at
# /debug/pprof/profile (http.go:53-63).

def _varint(n: int) -> bytes:
    out = bytearray()
    while n >= 0x80:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)
    return bytes(out)


def _field_bytes(tag: int, payload: bytes) -> bytes:
    return _varint((tag << 3) | 2) + _varint(len(payload)) + payload


def _field_varint(tag: int, value: int) -> bytes:
    return _varint(tag << 3) + _varint(value)


def _packed(tag: int, values) -> bytes:
    body = b"".join(_varint(v) for v in values)
    return _field_bytes(tag, body)


def encode_pprof(stacks, sample_types, period_type, period: int,
                 started: float) -> bytes:
    """Encode aggregated stacks as a gzipped pprof Profile.

    stacks: {leaf-first tuple of (filename, name, line): [values...]}
    with one value per entry in sample_types ([(type, unit), ...]);
    period_type is the (type, unit) of `period`. One Location+Function
    is emitted per unique call site."""
    import gzip

    strings: Dict[str, int] = {"": 0}

    def sid(s: str) -> int:
        i = strings.get(s)
        if i is None:
            i = strings[s] = len(strings)
        return i

    func_ids: Dict[Tuple[str, str], int] = {}
    functions: List[bytes] = []
    loc_ids: Dict[Tuple[str, str, int], int] = {}
    locations: List[bytes] = []

    def loc_id(site: Tuple[str, str, int]) -> int:
        i = loc_ids.get(site)
        if i is not None:
            return i
        filename, name, line = site
        fkey = (filename, name)
        fid = func_ids.get(fkey)
        if fid is None:
            fid = func_ids[fkey] = len(functions) + 1
            functions.append(
                _field_varint(1, fid)
                + _field_varint(2, sid(name))
                + _field_varint(3, sid(name))
                + _field_varint(4, sid(filename)))
        i = loc_ids[site] = len(locations) + 1
        line_msg = _field_varint(1, fid) + _field_varint(2, line)
        locations.append(
            _field_varint(1, i) + _field_bytes(4, line_msg))
        return i

    samples: List[bytes] = []
    for stack, values in stacks.items():
        ids = [loc_id(site) for site in stack]  # already leaf-first
        samples.append(_packed(1, ids) + _packed(2, values))

    def value_type(type_s: str, unit_s: str) -> bytes:
        return (_field_varint(1, sid(type_s))
                + _field_varint(2, sid(unit_s)))

    out = bytearray()
    for type_s, unit_s in sample_types:
        out += _field_bytes(1, value_type(type_s, unit_s))
    for s in samples:
        out += _field_bytes(2, s)
    for loc in locations:
        out += _field_bytes(4, loc)
    for fn in functions:
        out += _field_bytes(5, fn)
    for s in sorted(strings, key=strings.get):
        out += _field_bytes(6, s.encode())
    out += _field_varint(9, int(started * 1e9))
    out += _field_varint(10, int((time.time() - started) * 1e9))
    out += _field_bytes(11, value_type(*period_type))
    out += _field_varint(12, period)
    return gzip.compress(bytes(out))


def sampler_to_pprof(sampler: StackSampler) -> bytes:
    """CPU profile: samples/count + cpu/nanoseconds (the shape Go's CPU
    profile uses)."""
    with sampler._lock:
        raw = dict(sampler._stacks)
        started = sampler._started_at
    period_ns = int(1e9 / sampler.hz)
    stacks = {stack: [hits, hits * period_ns] for stack, hits in raw.items()}
    return encode_pprof(stacks, [("samples", "count"),
                                 ("cpu", "nanoseconds")],
                        ("cpu", "nanoseconds"), period_ns, started)


def threads_pprof() -> bytes:
    """All live thread stacks as a pprof profile (the goroutine-profile
    analog: one sample per thread, value 1)."""
    stacks: Dict[tuple, list] = {}
    for frame in sys._current_frames().values():
        stack = []
        while frame is not None and len(stack) < StackSampler.MAX_STACK_DEPTH:
            code = frame.f_code
            stack.append((code.co_filename, code.co_name, frame.f_lineno))
            frame = frame.f_back
        key = tuple(stack)
        prev = stacks.get(key)
        if prev is None:
            stacks[key] = [1]
        else:
            prev[0] += 1
    return encode_pprof(stacks, [("threads", "count")],
                        ("threads", "count"), 1, time.time())


def empty_pprof(kind: str, unit: str = "count") -> bytes:
    """A valid zero-sample pprof profile. Served at /debug/pprof/block
    and /mutex: the Go runtime's contention profilers have no CPython
    analog (no runtime hook records lock-wait stacks), and an empty
    profile keeps `go tool pprof`-style consumers working instead of
    breaking scrapers with a 404 (reference http.go mounts every pprof
    route unconditionally)."""
    return encode_pprof({}, [(kind, unit)], (kind, unit), 1, time.time())


def threadcreate_pprof() -> bytes:
    """/debug/pprof/threadcreate analog: CPython doesn't record which
    stack created each thread, so this reports one synthetic sample
    carrying the live-thread count (the headline number Go's profile is
    scraped for)."""
    site = (("<unavailable>", "threading.create (sites not recorded)", 0),)
    return encode_pprof({site: [threading.active_count()]},
                        [("threadcreate", "count")],
                        ("threadcreate", "count"), 1, time.time())


_heap_traced_since = [0.0]
_heap_last_armed = [0.0]
_heap_lock = threading.Lock()
# minimum spacing between request-scoped tracemalloc armings: hammering
# the unauthenticated endpoint must not keep 25-frame tracing (the
# steady-state ingest overhead the request-scoped design removes)
# effectively always-on, nor serialize HTTP threads behind back-to-back
# half-second holds
HEAP_ARM_MIN_INTERVAL_S = 10.0


class HeapProfileThrottled(RuntimeError):
    """Raised when a request-scoped arming is asked for too soon after
    the previous one (HTTP layer maps it to 429)."""


def heap_pprof(limit: int = 10_000, keep_tracing: bool = False) -> bytes:
    """Heap profile at /debug/pprof/heap: a tracemalloc snapshot encoded
    as pprof with objects/count + space/bytes sample types. CPython can't
    reconstruct allocations made before tracing began, so a request with
    tracing off arms it for the duration of the request only — 25-frame
    tracemalloc costs real steady-state CPU on the ingest hot path, and a
    single unauthenticated GET must not durably slow the server (the Go
    reference's heap profile is near-free). keep_tracing=True (the
    enable_profiling config) leaves it armed so later requests see
    everything allocated since."""
    import tracemalloc

    # serialized: without the lock, one request's request-scoped stop()
    # could land between another's is_tracing() check and take_snapshot()
    with _heap_lock:
        armed_here = False
        if not tracemalloc.is_tracing():
            now = time.time()
            if not keep_tracing and \
                    now - _heap_last_armed[0] < HEAP_ARM_MIN_INTERVAL_S:
                raise HeapProfileThrottled(
                    f"heap profile re-armed too soon; retry in "
                    f"{HEAP_ARM_MIN_INTERVAL_S - (now - _heap_last_armed[0]):.0f}s")
            tracemalloc.start(25)
            armed_here = True
            _heap_last_armed[0] = now
            _heap_traced_since[0] = now
            # give the arena a moment to accumulate request-scoped
            # truth: with tracing armed only for this request, an
            # instant snapshot would be near-empty
            time.sleep(0.5)
        try:
            snap = tracemalloc.take_snapshot()
        finally:
            if armed_here and not keep_tracing:
                tracemalloc.stop()
                _heap_traced_since[0] = 0.0
    stats = sorted(snap.statistics("traceback"),
                   key=lambda s: s.size, reverse=True)[:limit]
    stacks = {}
    for st in stats:
        stack = tuple(
            (fr.filename, os.path.basename(fr.filename), fr.lineno)
            for fr in reversed(st.traceback))  # leaf-first
        prev = stacks.get(stack)
        if prev is None:
            stacks[stack] = [st.count, st.size]
        else:
            prev[0] += st.count
            prev[1] += st.size
    body = encode_pprof(stacks, [("objects", "count"), ("space", "bytes")],
                        ("space", "bytes"), 1,
                        _heap_traced_since[0] or time.time())
    _heap_last_profile[0] = body
    return body


_heap_last_profile = [b""]


def heap_pprof_or_cached(keep_tracing: bool = False) -> Tuple[bytes, bool]:
    """(profile, fresh) for the /heap and /allocs routes. Go serves both
    freely; here a back-to-back scrape of the pair would trip the
    arming throttle on the second request, so inside the throttle
    window the previous capture is served instead (its embedded
    time_nanos dates it). Raises HeapProfileThrottled only when there
    is no capture to fall back on."""
    try:
        return heap_pprof(keep_tracing=keep_tracing), True
    except HeapProfileThrottled:
        if _heap_last_profile[0]:
            return _heap_last_profile[0], False
        raise


_cpu_profile_lock = threading.Lock()


def pprof_for(seconds: float, hz: float = 100.0) -> bytes:
    """One-shot pprof-format CPU profile (the /debug/pprof/profile
    contract: block for `seconds`, then return the gzipped proto).

    One capture at a time, matching Go pprof: concurrent requests would
    each spawn a 100 Hz sys._current_frames() sampler and compound
    whole-process GIL overhead. Raises RuntimeError when busy (the HTTP
    layer maps it to a 503)."""
    if not _cpu_profile_lock.acquire(blocking=False):
        raise RuntimeError("a CPU profile capture is already in progress")
    try:
        sampler = StackSampler(hz=hz, collect_stacks=True)
        sampler.start()
        time.sleep(max(0.01, seconds))
        sampler.stop()
        return sampler_to_pprof(sampler)
    finally:
        _cpu_profile_lock.release()


def capture_device_trace(seconds: float) -> bytes:
    """Run `jax.profiler.trace` for `seconds` and return the trace
    directory zipped (TensorBoard/xprof-loadable). The trace records
    device (TPU) timelines, XLA module executions, and host runtime."""
    import jax

    tmp = tempfile.mkdtemp(prefix="veneur-trace-")
    try:
        with jax.profiler.trace(tmp):
            time.sleep(max(0.05, seconds))
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
            for root, _dirs, files in os.walk(tmp):
                for name in files:
                    full = os.path.join(root, name)
                    zf.write(full, os.path.relpath(full, tmp))
        return buf.getvalue()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def start_profile_server(port: int) -> bool:
    """Start jax's live profiling gRPC server (TensorBoard 'capture
    profile' target). Returns False when unavailable."""
    try:
        import jax

        jax.profiler.start_server(port)
        logger.info("jax profiler server on port %d", port)
        return True
    except Exception:
        logger.exception("could not start jax profiler server")
        return False
