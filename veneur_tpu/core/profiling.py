"""Profiling surface: the pprof-equivalent for a TPU-hosted pipeline.

The reference always mounts Go pprof (reference http.go:53-63) and starts
a CPU profile when `enable_profiling` is set (reference
server.go:1382-1390). Python has no goroutine-style sampling profiler in
the stdlib, so this module provides:

  * StackSampler — a ~100 Hz all-threads stack sampler (the pprof CPU
    profile analog): aggregates `sys._current_frames()` into flat and
    cumulative hit counts per call site, reported as a text profile.
  * capture_device_trace — a bounded `jax.profiler.trace` session whose
    output directory is zipped and returned (open in TensorBoard /
    xprof to see device timelines, XLA ops, and HBM traffic).
  * start_profile_server — `jax.profiler.start_server` for live
    TensorBoard capture, the idiomatic TPU profiling hook.

Wired to config `enable_profiling` (continuous sampler from startup) and
`profile_server_port`, and to the HTTP endpoints
/debug/profile/cpu and /debug/profile/device (core.httpapi).
"""

from __future__ import annotations

import collections
import io
import logging
import os
import shutil
import sys
import tempfile
import threading
import time
import zipfile
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger("veneur_tpu.profiling")


class StackSampler:
    """Samples every thread's Python stack at `hz` and aggregates call
    sites. Flat hits = frames executing when sampled (self time);
    cumulative hits = frames anywhere on a sampled stack."""

    def __init__(self, hz: float = 100.0):
        self.hz = hz
        self._flat: collections.Counter = collections.Counter()
        self._cum: collections.Counter = collections.Counter()
        self._samples = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._started_at = 0.0

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._started_at = time.time()
        self._thread = threading.Thread(
            target=self._run, name="stack-sampler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=2.0)
        self._thread = None

    @property
    def running(self) -> bool:
        return self._thread is not None

    # -- sampling ---------------------------------------------------------

    def _run(self) -> None:
        period = 1.0 / self.hz
        me = threading.get_ident()
        while not self._stop.wait(period):
            self._sample_once(me)

    def _sample_once(self, skip_ident: int) -> None:
        frames = sys._current_frames()
        with self._lock:
            self._samples += 1
            for ident, frame in frames.items():
                if ident == skip_ident:
                    continue
                seen = set()
                top = True
                while frame is not None:
                    code = frame.f_code
                    site = (code.co_filename, code.co_name,
                            frame.f_lineno if top else code.co_firstlineno)
                    if top:
                        self._flat[site] += 1
                        top = False
                    if site not in seen:
                        self._cum[site] += 1
                        seen.add(site)
                    frame = frame.f_back

    # -- reporting --------------------------------------------------------

    def snapshot(self) -> Tuple[int, List, List]:
        with self._lock:
            return (self._samples,
                    self._flat.most_common(),
                    self._cum.most_common())

    def reset(self) -> None:
        with self._lock:
            self._flat.clear()
            self._cum.clear()
            self._samples = 0
            self._started_at = time.time()

    def report(self, top: int = 40) -> str:
        """pprof-style text profile: flat% then cum% per call site."""
        samples, flat, cum = self.snapshot()
        lines = [
            f"cpu profile: {samples} samples "
            f"({time.time() - self._started_at:.1f}s at {self.hz:.0f} Hz)",
            "",
            f"{'flat%':>7} {'hits':>8}  site (self time)",
        ]
        for site, hits in flat[:top]:
            pct = 100.0 * hits / max(1, samples)
            lines.append(f"{pct:6.1f}% {hits:8d}  "
                         f"{_short(site[0])}:{site[2]} {site[1]}")
        lines += ["", f"{'cum%':>7} {'hits':>8}  site (cumulative)"]
        for site, hits in cum[:top]:
            pct = 100.0 * hits / max(1, samples)
            lines.append(f"{pct:6.1f}% {hits:8d}  "
                         f"{_short(site[0])}:{site[2]} {site[1]}")
        return "\n".join(lines) + "\n"


def _short(path: str) -> str:
    parts = path.split(os.sep)
    return os.sep.join(parts[-3:]) if len(parts) > 3 else path


def sample_for(seconds: float, hz: float = 100.0, top: int = 40) -> str:
    """One-shot profile: sample for `seconds`, return the text report
    (the request-scoped mode when no continuous sampler is running)."""
    sampler = StackSampler(hz=hz)
    sampler.start()
    time.sleep(max(0.01, seconds))
    sampler.stop()
    return sampler.report(top=top)


def capture_device_trace(seconds: float) -> bytes:
    """Run `jax.profiler.trace` for `seconds` and return the trace
    directory zipped (TensorBoard/xprof-loadable). The trace records
    device (TPU) timelines, XLA module executions, and host runtime."""
    import jax

    tmp = tempfile.mkdtemp(prefix="veneur-trace-")
    try:
        with jax.profiler.trace(tmp):
            time.sleep(max(0.05, seconds))
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
            for root, _dirs, files in os.walk(tmp):
                for name in files:
                    full = os.path.join(root, name)
                    zf.write(full, os.path.relpath(full, tmp))
        return buf.getvalue()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def start_profile_server(port: int) -> bool:
    """Start jax's live profiling gRPC server (TensorBoard 'capture
    profile' target). Returns False when unavailable."""
    try:
        import jax

        jax.profiler.start_server(port)
        logger.info("jax profiler server on port %d", port)
        return True
    except Exception:
        logger.exception("could not start jax profiler server")
        return False
