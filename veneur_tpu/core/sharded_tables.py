"""Live multi-device sharding for the column store's HBM-heavy families.

The reference scales its hot path by sharding metric keys across worker
goroutines and re-merging forwarded state on a global instance (reference
server.go:1016, worker.go:410-467, flusher.go:516-591). On a multi-chip
host the TPU-native equivalent keeps ONE host intern table but spreads the
interval state of the two big families across the local devices:

  histograms  (K, C) slot grids      merge = centroid re-insertion
  sets        (K, 16384) registers   merge = elementwise max

Batches round-robin across per-device states during ingest (pure data
parallelism — no communication), and the flush-time global merge runs as
one jitted computation over a stacked array sharded on the device axis, so
XLA SPMD lowers the merges to ICI collectives (all-reduce-max for HLL,
all-gather + batched recompress for digests). Counters and gauges stay
single-device: their state is (K,) scalars — too small to shard — and
gauges additionally need cross-batch ordering that a round-robin split
would destroy.

Enable with config `tpu.shards: N` (0/1 = single-device tables).
"""

from __future__ import annotations

import logging
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from veneur_tpu.core.columnstore import HistoTable, SetTable, _SetRegisters
from veneur_tpu.ops import batch_hll, batch_tdigest

logger = logging.getLogger("veneur_tpu.sharded")

SHARD_AXIS = "shard"


def local_shard_devices(n: int) -> List:
    """The n local devices to shard over; falls back to the virtual CPU
    devices when the default platform is smaller (validation topologies)."""
    devices = jax.local_devices()
    if len(devices) < n:
        try:
            cpu = jax.devices("cpu")
            if len(cpu) >= n:
                logger.warning(
                    "shard_devices=%d > %d local devices; using the "
                    "virtual CPU mesh (validation only)", n, len(devices))
                devices = cpu
        except RuntimeError:
            pass
    if len(devices) < n:
        logger.warning("shard_devices=%d > %d available; clamping",
                       n, len(devices))
        n = len(devices)
    return list(devices[:n])


def _stack_on_mesh(mesh: Mesh, leaves: List[jnp.ndarray]) -> jnp.ndarray:
    """Assemble per-device arrays (one per mesh device, already resident)
    into a single (n, ...) jax.Array sharded on the leading axis — no
    host round-trip, no device copy."""
    n = len(leaves)
    shard_shape = (1,) + leaves[0].shape
    global_shape = (n,) + leaves[0].shape
    sharding = NamedSharding(mesh, P(SHARD_AXIS))
    expanded = [leaf[None] for leaf in leaves]  # dispatched on-device
    return jax.make_array_from_single_device_arrays(
        global_shape, sharding, [x for x in expanded])


@jax.jit
def _merge_hll_stacked(stacked: jnp.ndarray) -> jnp.ndarray:
    """(n, K, M) int8 sharded on axis 0 -> (K, M) register max. XLA SPMD
    lowers the reduction over the sharded axis to an all-reduce-max."""
    return jnp.max(stacked, axis=0)


@jax.jit
def _merge_histo_stacked(stacked: Dict[str, jnp.ndarray]
                         ) -> Dict[str, jnp.ndarray]:
    """Per-shard digest states stacked on axis 0 -> one merged state.
    Mirrors parallel.mesh._merge_digest_keysharded: concatenate every
    shard's centroids per key and recompress once as a batched kernel
    (the global veneur's re-insertion, reference worker.go:455-457);
    scalar stats reduce with sum/min/max."""
    w = stacked["weights"]                      # (n, K, C)
    m = jnp.where(w > 0, stacked["wv"] / jnp.maximum(w, 1e-30), 0.0)
    sw = stacked["sweights"]                    # staged-but-uncompacted
    sm = jnp.where(sw > 0, stacked["swv"] / jnp.maximum(sw, 1e-30), 0.0)
    n, num_keys, c = w.shape
    cat_m = jnp.concatenate([m, sm], axis=-1)   # (n, K, 2C)
    cat_w = jnp.concatenate([w, sw], axis=-1)
    cat_m = jnp.moveaxis(cat_m, 0, 1).reshape(num_keys, n * 2 * c)
    cat_w = jnp.moveaxis(cat_w, 0, 1).reshape(num_keys, n * 2 * c)
    new_m, new_w = batch_tdigest._recompress(cat_m, cat_w, num_keys)
    return {
        "wv": new_m * new_w,
        "weights": new_w,
        "swv": jnp.zeros_like(new_w),
        "sweights": jnp.zeros_like(new_w),
        "dmin": jnp.min(stacked["dmin"], axis=0),
        "dmax": jnp.max(stacked["dmax"], axis=0),
        "drecip": jnp.sum(stacked["drecip"], axis=0),
        "lmin": jnp.min(stacked["lmin"], axis=0),
        "lmax": jnp.max(stacked["lmax"], axis=0),
        "lsum": jnp.sum(stacked["lsum"], axis=0),
        "lweight": jnp.sum(stacked["lweight"], axis=0),
        "lrecip": jnp.sum(stacked["lrecip"], axis=0),
    }


class ShardedHistoTable(HistoTable):
    """HistoTable whose interval state lives round-robin across N local
    devices; flush merges across the device axis with collectives."""

    def __init__(self, capacity: int = 1024, batch_cap: int = 8192,
                 devices: List = None, max_rows: int = 0):
        self._devices = devices or local_shard_devices(2)
        self._mesh = Mesh(np.asarray(self._devices), (SHARD_AXIS,))
        self._next = 0
        super().__init__(capacity, batch_cap, max_rows=max_rows)

    def _init_arrays(self):
        self._init_pending()
        self.states = [
            jax.device_put(batch_tdigest.init_state(self.capacity), d)
            for d in self._devices]
        self._shard_counts = [np.zeros(self.capacity, np.int32)
                              for _ in self._devices]
        self.state = None  # unused; all device state lives in .states

    def _grow_arrays(self, new_cap):
        grown = []
        for dev, st in zip(self._devices, self.states):
            new = batch_tdigest.init_state(new_cap)
            g = {k: jax.lax.dynamic_update_slice(
                    new[k], st[k], (0,) * new[k].ndim) for k in new}
            grown.append(jax.device_put(g, dev))
        self.states = grown
        extended = []
        for counts in self._shard_counts:
            e = np.zeros(new_cap, np.int32)
            e[: counts.shape[0]] = counts
            extended.append(e)
        self._shard_counts = extended

    def _apply_cols(self, cols):
        i = self._next
        self._next = (i + 1) % len(self._devices)
        dev = self._devices[i]
        slots, overflow = batch_tdigest.host_slots(
            cols[0], cols[1], cols[2], self._shard_counts[i])
        if overflow:
            self.states[i] = batch_tdigest.compact(self.states[i])
            self._shard_counts[i][:] = 0
            slots, _ = batch_tdigest.host_slots(
                cols[0], cols[1], cols[2], self._shard_counts[i])
        rows, vals, wts = (jax.device_put(c, dev) for c in cols)
        self.states[i] = batch_tdigest.apply_batch(
            self.states[i], rows, vals, wts, jax.device_put(slots, dev))
        self._applies += 1

    def merge_batch(self, stubs, in_means, in_weights, in_min, in_max,
                    in_recip) -> None:
        """Import-path digest merge lands on one shard (digest merge is
        commutative across shards)."""
        with self.lock:
            rows = np.fromiter(
                (self.row_for(s) for s in stubs), np.int32, len(stubs))
            # cardinality-capped/rejected stubs drop out: scattering a
            # -1 row would negative-index the LAST device row
            ok = rows >= 0
            rows = rows[ok]
            self.touched[rows] = True
            self._note_applied(int(rows.size))
            self.apply_lock.acquire()
        try:
            i = self._next
            self._next = (i + 1) % len(self._devices)
            dev = self._devices[i]
            put = lambda a, t: jax.device_put(np.asarray(a, t)[ok], dev)
            self.states[i] = batch_tdigest.merge_centroid_rows(
                self.states[i], jax.device_put(rows, dev),
                put(in_means, np.float32), put(in_weights, np.float32),
                put(in_min, np.float32), put(in_max, np.float32),
                put(in_recip, np.float32))
            # merge_centroid_rows folds every staged row on this shard
            self._shard_counts[i][:] = 0
        finally:
            self.apply_lock.release()

    def _merged_state(self) -> Dict[str, jnp.ndarray]:
        stacked = {
            k: _stack_on_mesh(self._mesh, [st[k] for st in self.states])
            for k in self.states[0]}
        return _merge_histo_stacked(stacked)

    def snapshot_and_reset(self, percentiles: Tuple[float, ...],
                           need_export: bool = True):
        return self.snapshot_finish(
            self.snapshot_begin(percentiles, need_export))

    def snapshot_begin(self, percentiles: Tuple[float, ...],
                       need_export: bool = True) -> dict:
        with self.lock:
            cols = self._swap_locked()
            self.apply_lock.acquire()
            self._note_generation_locked()
            touched = self.touched.copy()
            meta = list(self.meta)
            self.touched[:] = False
        try:
            if cols is not None:
                self._apply_cols(cols)
            merged = self._merged_state()
            ps = tuple(percentiles)
            if need_export:
                # fused flush+export: one dispatch, two transfers (the
                # merged state's staging is already folded, so the fold
                # inside the fused op is a no-op concat of zeros).
                # Routed through the pallas-aware wrappers so
                # tpu.pallas_tdigest_flush applies to sharded stores too.
                packed, export_packed = self._flush_export(ps, merged)
            else:
                packed = self._flush_packed(ps, merged,
                                            fold_staging=False)
                export_packed = None
            self.states = [
                jax.device_put(batch_tdigest.init_state(self.capacity), d)
                for d in self._devices]
            self._shard_counts = [np.zeros(self.capacity, np.int32)
                                  for _ in self._devices]
        finally:
            self.apply_lock.release()
        return {"packed": packed, "export_packed": export_packed,
                "ps": ps, "touched": touched, "meta": meta}


class ShardedSetTable(SetTable):
    """SetTable whose HLL register banks live round-robin across N local
    devices; flush merges registers with an all-reduce max."""

    def __init__(self, capacity: int = 256, batch_cap: int = 8192,
                 devices: List = None, max_rows: int = 0):
        self._devices = devices or local_shard_devices(2)
        self._mesh = Mesh(np.asarray(self._devices), (SHARD_AXIS,))
        self._next = 0
        # dense path: sharding already spreads register memory across
        # devices, and the collective merge needs uniform dense rows
        super().__init__(capacity, batch_cap, sparse=False,
                         max_rows=max_rows)

    def _init_arrays(self):
        self._init_pending()
        self.states = [
            jax.device_put(batch_hll.init_state(self.capacity), d)
            for d in self._devices]
        self.state = None

    def _grow_arrays(self, new_cap):
        self.states = [
            jax.device_put(
                jnp.pad(st, [(0, new_cap - st.shape[0]), (0, 0)]), dev)
            for dev, st in zip(self._devices, self.states)]

    def _apply_cols(self, cols):
        i = self._next
        self._next = (i + 1) % len(self._devices)
        dev = self._devices[i]
        rows, idxs, rhos = (jax.device_put(c, dev) for c in cols)
        self.states[i] = batch_hll.apply_batch(
            self.states[i], rows, idxs, rhos)

    def merge_batch(self, stubs, in_regs) -> None:
        with self.lock:
            rows = np.fromiter(
                (self.row_for(s) for s in stubs), np.int32, len(stubs))
            # cardinality-capped/rejected stubs drop out: scattering a
            # -1 row would negative-index the LAST device row
            ok = rows >= 0
            rows = rows[ok]
            self.touched[rows] = True
            self._note_applied(int(rows.size))
            self.apply_lock.acquire()
        try:
            i = self._next
            self._next = (i + 1) % len(self._devices)
            dev = self._devices[i]
            self.states[i] = batch_hll.merge_rows(
                self.states[i], jax.device_put(rows, dev),
                jax.device_put(np.asarray(in_regs, np.int8)[ok], dev))
        finally:
            self.apply_lock.release()

    def _merged_state(self) -> jnp.ndarray:
        stacked = _stack_on_mesh(self._mesh, self.states)
        return _merge_hll_stacked(stacked)

    def snapshot_and_reset(self):
        with self.lock:
            cols = self._swap_locked()
            self.apply_lock.acquire()
            self._note_generation_locked()
            touched = self.touched.copy()
            meta = list(self.meta)
            self.touched[:] = False
        try:
            if cols is not None:
                self._apply_cols(cols)
            merged = self._merged_state()
            estimates = np.asarray(batch_hll.estimate(merged))
            # lazy per-row provider (columnstore._SetRegisters): the
            # merged (K, M) bank only crosses the device link if a
            # consumer (the forward exporter) actually reads registers
            registers = _SetRegisters.dense(merged, self.capacity)
            self.states = [
                jax.device_put(batch_hll.init_state(self.capacity), d)
                for d in self._devices]
        finally:
            self.apply_lock.release()
        return estimates, registers, touched, meta
