"""Live multi-device sharding: the column store as a partitioned mesh.

The reference scales its hot path by sharding metric keys across worker
goroutines and re-merging forwarded state on a global instance (reference
server.go:1016, worker.go:410-467, flusher.go:516-591). On a multi-chip
host the TPU-native equivalent keeps ONE host intern table but
PARTITIONS the interval state of every device family across the local
mesh (parallel/collectives.py owns the kernels and the
`Mesh`/`NamedSharding` layout):

  counters    (n, K) Kahan pairs      merge = psum (selection)
  gauges      (n, K) LWW + set mask   merge = home-shard selection
  histograms  per-shard slot grids    merge = centroid re-insertion
  sets        per-shard registers     merge = elementwise max
  llhists     (n, K, BINS) int32      merge = register ADD (bit-exact)

Routing is **digest-home** by default: a key's 64-bit fnv1a digest picks
its home shard at mint time (parallel/sharded_server.py), and every
sample, batch chunk, and import merge for that key lands on that shard.
That single invariant is what makes the whole plane exact:

  * gauges keep last-write-wins ordering (all of a key's writes serialize
    on one shard — the reason the round-robin era could not shard them);
  * counter Kahan pairs and llhist/HLL registers merge by selection
    (summing n-1 zeros), so flush output is bit-identical to a
    single-device table over the same stream;
  * a dead chip's blast radius is exactly its key range — the failover
    tier (proxy shard groups) re-homes only those keys.

Ingest dispatches keep their compiled shapes: the pending buffer is
masked per shard (non-home rows -> PAD_ROW, dropped by the scatter
kernels) instead of split, so kernels never retrace on data-dependent
sub-batch lengths. `shard_routing: roundrobin` keeps the legacy
round-robin behavior for the histogram/set families (A/B escape hatch);
the scalar and llhist families require digest routing and stay
single-device under round-robin.

Enable with config `tpu.shards: N` (0/1 = single-device tables).
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from veneur_tpu.core.columnstore import (CounterTable, GaugeTable,
                                         HistoTable, LLHistTable, PAD_ROW,
                                         SetTable, _BaseTable,
                                         _SetRegisters, _zeros_like_spare)
from veneur_tpu.ops import batch_hll, batch_llhist, batch_tdigest, scalars
from veneur_tpu.parallel import collectives
from veneur_tpu.parallel.collectives import SHARD_AXIS
from veneur_tpu.parallel.sharded_server import (ROUTING_DIGEST,
                                                ROUTING_ROUNDROBIN,
                                                ShardedServingPlane,
                                                local_shard_devices)

logger = logging.getLogger("veneur_tpu.sharded")

__all__ = [
    "ShardedCounterTable", "ShardedGaugeTable", "ShardedHistoTable",
    "ShardedLLHistTable", "ShardedSetTable", "local_shard_devices",
    "SHARD_AXIS",
]


# kept as aliases so pre-mesh callers (tests, notebooks) keep working;
# the implementations moved to parallel/collectives.py
_stack_on_mesh = collectives.stack_on_mesh
_merge_hll_stacked = collectives.merge_hll_stacked
_merge_histo_stacked = collectives.merge_histo_stacked


class _DigestRouted:
    """Mixin: per-row home-shard assignment + batch masking, shared by
    every sharded family table. Initialized BEFORE _BaseTable.__init__
    (whose _init_arrays builds device state on the mesh)."""

    def _routing_init(self, capacity: int, devices: Optional[List],
                      plane: Optional[ShardedServingPlane]) -> None:
        if plane is None:
            plane = ShardedServingPlane(
                devices or local_shard_devices(2))
        self._plane = plane
        self._devices = plane.devices
        self._mesh = plane.mesh
        self._n_shards = plane.n
        self._shard_sharding = collectives.shard_sharding(plane.mesh)
        # row -> home shard, stamped at mint time (see _note_minted);
        # int8 bounds the mesh at 128 shards, far past any host
        self._shard_of = np.zeros(capacity, np.int8)
        self._rr_next = 0  # roundrobin mode's rotation cursor
        # bumped by every live reshard (_retopo_locked): snapshots carry
        # the epoch they were swapped under, so a readout that crossed a
        # cutover can never donate old-mesh buffers back as a spare
        self._topo_epoch = 0

    @property
    def _digest_routed(self) -> bool:
        return self._plane.routing == ROUTING_DIGEST

    def _note_minted(self, row: int, metric) -> None:
        if row < self._shard_of.shape[0]:
            self._shard_of[row] = self._plane.home(metric.digest64)

    def _grow_shard_of(self, new_cap: int) -> None:
        grown = np.zeros(new_cap, np.int8)
        grown[: self._shard_of.shape[0]] = self._shard_of
        self._shard_of = grown

    def _home_of(self, rows: np.ndarray) -> np.ndarray:
        """(batch,) rows -> home shard per sample, -1 for padding."""
        cap = self._shard_of.shape[0]
        safe = np.minimum(rows, cap - 1)
        return np.where(rows < cap, self._shard_of[safe],
                        np.int8(-1)).astype(np.int32)

    def _shard_counts_of(self, home: np.ndarray) -> np.ndarray:
        return np.bincount(home[home >= 0],
                           minlength=self._n_shards).astype(np.int64)

    def _put_sharded(self, host_arr: np.ndarray):
        return jax.device_put(host_arr, self._shard_sharding)

    def _prewarm_apply(self, state, cols, capacity: int):
        # rung compiles must not inflate the serving plane's routed/
        # dispatch accounting — the batch is all-PAD throwaway
        return self._apply_cols_state(state, cols, note=False)

    def _stacked_batch(self, rows: np.ndarray, value_cols: Tuple
                       ) -> Tuple:
        """Masked (n, batch) row column + tiled value columns for one
        fixed-shape stacked dispatch, plus the per-shard sample counts
        for the plane's accounting."""
        home = self._home_of(np.asarray(rows))
        srows = collectives.mask_batch_for_shards(
            home, self._n_shards, np.asarray(rows))
        tiled = tuple(
            np.ascontiguousarray(
                collectives.tile_batch(self._n_shards, np.asarray(c)))
            for c in value_cols)
        return (self._put_sharded(srows),
                tuple(self._put_sharded(t) for t in tiled),
                self._shard_counts_of(home))

    # -- elastic resharding (parallel/reshard.py) ------------------------

    def swap_out(self, **kw) -> dict:
        snap = super().swap_out(**kw)
        snap["_topo_epoch"] = self._topo_epoch
        return snap

    def capture_readonly(self, **kw) -> dict:
        snap = super().capture_readonly(**kw)
        snap["topo_epoch"] = self._topo_epoch
        return snap

    def recycle(self, snap: dict) -> None:
        if snap.pop("_topo_epoch", self._topo_epoch) != self._topo_epoch:
            # the snapshot was swapped out under the OLD mesh and its
            # readout finished after a cutover retopologized this table:
            # its spare/recycle buffers are shaped (N_old, ...) and must
            # never be installed into the (M, ...) generation ladder
            for key in ("cap", "_spare", "_recycle"):
                snap.pop(key, None)
            tok = snap.pop("_devobs", None)
            obs = self._deviceobs
            if obs is not None:
                obs.drop(tok)
            return
        super().recycle(snap)

    def _devobs_note_merge(self, seconds: float) -> None:
        """Kernel-registry row for one collective merge dispatch."""
        obs = self._deviceobs
        if obs is not None:
            obs.note_kernel("merge", self.family, seconds)

    def reshard_swap(self, new_plane: ShardedServingPlane, **kw) -> dict:
        """The per-family cutover primitive: ONE critical section that
        (a) swaps the current interval's generation out exactly like a
        flush boundary (pending columns folded, extras captured), (b)
        reduces the captured per-shard state to a single merged copy on
        the OLD mesh (`_reshard_capture_device` — the same selection /
        reduction expressions the flush merge uses, so the migrated
        values are the values a flush would have emitted), and (c)
        rebinds the table to `new_plane` (`_retopo_locked`). Ingest that
        lands after the locks release accumulates directly in the new
        topology; everything before is in the returned snap, which the
        reshard controller serializes to the range-segment WAL and
        merges back through the family's own merge_batch path.

        Atomic because the table locks are plain (non-reentrant) Locks:
        holding them across an external WAL write would deadlock every
        concurrent ingest dispatch, and releasing between swap and
        retopo would let a sample land in a generation nobody drains."""
        snap = dict(kw)
        with self.lock:
            idle = self._idle_swap_locked(snap)
            if not idle:
                snap["cols"] = self._swap_locked()
            with self.apply_lock:
                if not idle:
                    self._note_generation_locked()
                    snap["touched"] = self.touched.copy()
                    snap["meta"] = list(self.meta)
                    # per-row 64-bit key digests, for the range-cell
                    # partition of the migrating rows (dict key is
                    # (digest64 << 2) | scope)
                    digests = np.zeros(self.touched.shape[0], np.uint64)
                    for row, dict_key in enumerate(self._dict_key_of):
                        if row < digests.shape[0]:
                            digests[row] = np.uint64(
                                (dict_key >> 2) & 0xFFFFFFFFFFFFFFFF)
                    snap["digest64"] = digests
                    self.touched[:] = False
                    self._swap_extras_locked(snap)
                    state = self._swap_device_locked()
                    cols = snap.pop("cols", None)
                    if cols is not None:
                        # folds the final pending columns on the OLD
                        # topology (the routing attrs are still bound)
                        state = self._readout_apply(state, cols, snap)
                    snap.pop("staged", None)
                    self._reshard_capture_device(state, snap)
                    # the captured old-mesh generation stays resident
                    # until the controller's WAL+merge completes: its
                    # ledger token rides the snap as `reshard_capture`
                    obs = self._deviceobs
                    if obs is not None:
                        tok = self._devobs_inflight
                        self._devobs_inflight = None
                        obs.retag(tok, "reshard_capture")
                        snap["_devobs"] = tok
                self._retopo_locked(new_plane)
        snap["_topo_epoch"] = self._topo_epoch
        return snap

    def _reshard_capture_device(self, state, snap: dict) -> None:
        """Family hook: reduce the captured per-shard generation to one
        merged, NON-donated copy the controller can serialize (runs on
        the old mesh, inside the cutover critical section)."""
        raise NotImplementedError

    def _retopo_locked(self, plane: ShardedServingPlane) -> None:
        """Rebind this table to a new serving plane (caller holds
        ``lock`` + ``apply_lock``): new mesh/sharding, every live row's
        home recomputed under the new range assignment, fresh device
        state, and all old-mesh spares/prewarm records invalidated."""
        self._plane = plane
        self._devices = plane.devices
        self._mesh = plane.mesh
        self._n_shards = plane.n
        self._shard_sharding = collectives.shard_sharding(plane.mesh)
        self._rr_next = 0
        shard_of = np.zeros(self._shard_of.shape[0], np.int8)
        for dict_key, row in self.rows.items():
            if row < shard_of.shape[0]:
                shard_of[row] = plane.home(dict_key >> 2)
        self._shard_of = shard_of
        # old-mesh buffers can never serve the new topology
        self._spare = None
        self._spare_cap = -1
        obs = self._deviceobs
        if obs is not None:
            # the parked spare is discarded with the old mesh, and the
            # live generation is about to be rebound to a fresh one —
            # on the IDLE cutover path no swap ran, so the original
            # live token is still held here and dies now
            obs.drop(self._devobs_spare)
            self._devobs_spare = None
            obs.drop(self._devobs_live)
            self._devobs_live = None
        self._prewarmed_caps = set()
        self._topo_epoch += 1
        self._retopo_device_locked()
        if obs is not None:
            self._devobs_live = obs.note_generation(
                self.family, "live", self._devobs_state())

    def _retopo_device_locked(self) -> None:
        # stacked families: a fresh (M, K) zero generation on the new
        # mesh (per-device families override)
        self.state = self._fresh_state()


# ---------------------------------------------------------------------------
# Scalar families: stacked (n, K) state under one NamedSharding, one
# jitted vmapped scatter per dispatch, collective selection at flush.
# ---------------------------------------------------------------------------


class ShardedCounterTable(_DigestRouted, CounterTable):
    """CounterTable partitioned across the mesh: each key's deltas
    accumulate in its home shard's Kahan pair; flush merges by psum
    (pure selection under digest routing, so the f64 host readout is
    bit-identical to single-device)."""

    def __init__(self, capacity: int = 1024, batch_cap: int = 8192,
                 devices: Optional[List] = None, max_rows: int = 0,
                 plane: Optional[ShardedServingPlane] = None):
        self._routing_init(capacity, devices, plane)
        super().__init__(capacity, batch_cap, max_rows=max_rows)

    def _init_arrays(self):
        super()._init_arrays()
        self.state = collectives.init_stacked(
            self._mesh, scalars.init_counters, self.capacity)

    def _grow_arrays(self, new_cap):
        self._grow_shard_of(new_cap)
        self.state = collectives.grow_stacked(self._mesh, self.state,
                                              new_cap)

    def _fresh_state_at(self, capacity: int):
        return collectives.init_stacked(
            self._mesh, scalars.init_counters, capacity)

    def _apply_cols_state(self, state, cols, note: bool = True):
        rows, vals, rates = cols
        srows, (svals, srates), counts = self._stacked_batch(
            rows, (vals, rates))
        if note:
            self._plane.note_routed(self.family, counts)
        return collectives.apply_counters_sharded(
            state, srows, svals, srates)

    def _readout_device(self, state, snap) -> None:
        """Fused donated collective merge: the drained stacked
        generation's buffers come back as the next interval's spare."""
        t0 = time.perf_counter()
        snap["dev"], snap["_spare"] = \
            collectives.merge_counters_stacked_reset(state)
        self._devobs_note_merge(time.perf_counter() - t0)
        self._plane.note_merge_round()

    def _query_readout_device(self, state, snap) -> None:
        # read-only merge over the LIVE stacked generation: the fused
        # reset variant would donate (and zero) the live buffers. Same
        # reduction expression, so query results stay bit-identical to
        # the flush readout under digest routing.
        snap["dev"] = collectives.merge_counters_stacked(state)
        self._plane.note_merge_round()

    def _prewarm_readout(self, state, capacity, ps, need_export):
        return collectives.merge_counters_stacked_reset(state)

    def _reshard_capture_device(self, state, snap: dict) -> None:
        # psum selection, non-donating: (sum, comp) per row, the exact
        # pair snapshot_finish differences (counter totals are integral
        # by the apply kernel's trunc contract, so the f64 host total
        # survives the metricpb int64 wire bit-exactly)
        snap["dev"] = collectives.merge_counters_stacked(state)
        self._plane.note_merge_round()


class ShardedGaugeTable(_DigestRouted, GaugeTable):
    """GaugeTable partitioned across the mesh. Digest-home routing is
    load-bearing here: every write for a key serializes on its home
    shard, so last-write-wins ordering survives sharding (the property
    the round-robin split destroyed, which is why gauges stayed
    single-device until this plane)."""

    def __init__(self, capacity: int = 1024, batch_cap: int = 8192,
                 devices: Optional[List] = None, max_rows: int = 0,
                 plane: Optional[ShardedServingPlane] = None):
        self._routing_init(capacity, devices, plane)
        super().__init__(capacity, batch_cap, max_rows=max_rows)

    def _init_arrays(self):
        super()._init_arrays()
        self.state = collectives.init_stacked(
            self._mesh, scalars.init_gauges, self.capacity)

    def _grow_arrays(self, new_cap):
        self._grow_shard_of(new_cap)
        self.state = collectives.grow_stacked(self._mesh, self.state,
                                              new_cap)

    def _fresh_state_at(self, capacity: int):
        return collectives.init_stacked(
            self._mesh, scalars.init_gauges, capacity)

    def _apply_cols_state(self, state, cols, note: bool = True):
        rows, vals = cols
        srows, (svals,), counts = self._stacked_batch(rows, (vals,))
        if note:
            self._plane.note_routed(self.family, counts)
        return collectives.apply_gauges_sharded(state, srows, svals)

    def merge_batch(self, stubs, values) -> None:
        """Import-path overwrite, routed to each row's home shard (the
        same masked-batch shape as ingest, so ordering semantics
        match)."""
        with self.lock:
            rows = np.fromiter(
                (self.row_for(s) for s in stubs), np.int32, len(stubs))
            ok = rows >= 0  # cardinality-capped stubs drop out
            rows = rows[ok]
            self.touched[rows] = True
            self._note_applied(int(rows.size))
            self.apply_lock.acquire()
        try:
            if rows.size:
                srows, (svals,), _counts = self._stacked_batch(
                    rows, (np.asarray(values, np.float32)[ok],))
                self.state = collectives.merge_gauges_sharded(
                    self.state, srows, svals)
        finally:
            self.apply_lock.release()

    def _readout_device(self, state, snap) -> None:
        t0 = time.perf_counter()
        (dev, _set), snap["_spare"] = \
            collectives.merge_gauges_stacked_reset(state)
        self._devobs_note_merge(time.perf_counter() - t0)
        snap["dev"] = dev
        self._plane.note_merge_round()

    def _query_readout_device(self, state, snap) -> None:
        # non-donating LWW merge (see ShardedCounterTable note)
        dev, _set = collectives.merge_gauges_stacked(state)
        snap["dev"] = dev
        self._plane.note_merge_round()

    def _prewarm_readout(self, state, capacity, ps, need_export):
        return collectives.merge_gauges_stacked_reset(state)

    def _reshard_capture_device(self, state, snap: dict) -> None:
        # home-shard LWW selection, non-donating; the set mask rides
        # along so untouched rows are distinguishable from value 0.0
        dev, set_mask = collectives.merge_gauges_stacked(state)
        snap["dev"] = dev
        snap["set"] = set_mask
        self._plane.note_merge_round()


class ShardedLLHistTable(_DigestRouted, LLHistTable):
    """LLHistTable partitioned across the mesh: a (n, K, BINS_PAD) int32
    register bank sharded on the leading axis; ingest scatter-adds into
    each key's home shard, flush merges with one register-ADD reduction.
    Integer addition is associative and commutative, so the merged
    registers — and therefore every percentile, count, sum, and bucket
    the flusher emits, and every forwarded bin payload — are
    BIT-IDENTICAL to a single-device table (the PR-5 exactness pin,
    generalized to the mesh)."""

    def __init__(self, capacity: int = 1024, batch_cap: int = 8192,
                 devices: Optional[List] = None, max_rows: int = 0,
                 plane: Optional[ShardedServingPlane] = None):
        self._routing_init(capacity, devices, plane)
        super().__init__(capacity, batch_cap, max_rows=max_rows)

    def _init_arrays(self):
        super()._init_arrays()
        self.state = collectives.init_stacked(
            self._mesh, batch_llhist.init_state, self.capacity)

    def _grow_arrays(self, new_cap):
        self._grow_shard_of(new_cap)
        self.state = collectives.grow_stacked(self._mesh, self.state,
                                              new_cap)

    def _fresh_state_at(self, capacity: int):
        return collectives.init_stacked(
            self._mesh, batch_llhist.init_state, capacity)

    def _apply_cols_state(self, state, cols, note: bool = True):
        rows, bins, wts = cols
        srows, (sbins, swts), counts = self._stacked_batch(
            rows, (bins, wts))
        if note:
            self._plane.note_routed(self.family, counts)
        return collectives.apply_llhist_sharded(state, srows, sbins, swts)

    def merge_batch(self, stubs, in_bins) -> None:
        """Import-path register ADD, each incoming row landed on its
        home shard (exact under any routing — addition commutes — but
        home routing keeps the shard-is-the-key-range invariant that
        failover re-homing relies on)."""
        with self.lock:
            rows = np.fromiter(
                (self.row_for(s) for s in stubs), np.int32, len(stubs))
            ok = rows >= 0  # cardinality-capped stubs drop out
            rows = rows[ok]
            self.touched[rows] = True
            self._note_applied(int(rows.size))
            padded = batch_llhist.pad_rows_to_device(
                np.asarray(in_bins)[ok])
            self.samples_total += int(padded.sum())
            home = self._home_of(rows)
            self.apply_lock.acquire()
        try:
            if rows.size:
                self.state = collectives.merge_llhist_rows_at(
                    self.state, jnp.asarray(home), jnp.asarray(rows),
                    jnp.asarray(padded))
        finally:
            self.apply_lock.release()

    def _readout_device(self, state, snap) -> None:
        t0 = time.perf_counter()
        merged, snap["_spare"] = \
            collectives.merge_llhist_stacked_reset(state)
        self._devobs_note_merge(time.perf_counter() - t0)
        self._plane.note_merge_round()
        packed = batch_llhist.flush_packed(merged, snap["ps"])
        rows = np.flatnonzero(snap["touched"])
        bins_dev = None
        if snap.pop("need_bins") and rows.size:
            bins_dev = jnp.take(merged, jnp.asarray(rows, jnp.int32),
                                axis=0)
        snap["packed"] = packed
        snap["bins_dev"] = bins_dev

    def _query_readout_device(self, state, snap) -> None:
        # non-donating register-ADD merge over the live stacked bank
        # (integer addition: bit-identical to the fused reset merge)
        merged = collectives.merge_llhist_stacked(state)
        self._plane.note_merge_round()
        packed = batch_llhist.flush_packed(merged, snap["ps"])
        rows = np.flatnonzero(snap["touched"])
        bins_dev = None
        if snap.pop("need_bins") and rows.size:
            bins_dev = jnp.take(merged, jnp.asarray(rows, jnp.int32),
                                axis=0)
        snap["packed"] = packed
        snap["bins_dev"] = bins_dev

    def _prewarm_readout(self, state, capacity, ps, need_export):
        merged, fresh = collectives.merge_llhist_stacked_reset(state)
        return (batch_llhist.flush_packed(merged, ps), fresh)

    def _reshard_capture_device(self, state, snap: dict) -> None:
        # register ADD, non-donating: the merged (K, BINS_PAD) bank is
        # bit-identical to what a flush would have reduced, and integer
        # addition keeps the replay merge bit-exact too
        snap["bins"] = collectives.merge_llhist_stacked(state)
        self._plane.note_merge_round()


# ---------------------------------------------------------------------------
# Sketch families with per-shard grids (histograms, sets): per-device
# states, digest-home masked dispatch, stacked collective flush merge.
# ---------------------------------------------------------------------------


class _PerDeviceStates:
    """Generation swap over the per-device `states` list (the histo/set
    sharded families keep one committed state per device rather than a
    stacked array; `self.state` stays None)."""

    def _swap_device_locked(self):
        captured = self.states
        spare, self._spare = self._spare, None
        used_spare = (spare is not None
                      and self._spare_cap == self._state_capacity())
        if used_spare:
            self.states = spare
        else:
            self.states = self._fresh_state()
        self._devobs_swap_locked(used_spare)
        return captured

    def _capture_device_locked(self):
        # shallow list copy under apply_lock: a consistent point-in-time
        # set of per-device array refs (ingest rebinds list entries)
        return list(self.states)

    def _retopo_device_locked(self) -> None:
        self.states = self._fresh_state()
        self.state = None


class ShardedHistoTable(_PerDeviceStates, _DigestRouted, HistoTable):
    """HistoTable whose interval state lives across N local devices;
    ingest routes each key's samples to its home shard (digest mode) or
    round-robins whole batches (legacy mode); flush merges across the
    device axis with collectives."""

    def __init__(self, capacity: int = 1024, batch_cap: int = 8192,
                 devices: Optional[List] = None, max_rows: int = 0,
                 plane: Optional[ShardedServingPlane] = None):
        self._routing_init(capacity, devices, plane)
        super().__init__(capacity, batch_cap, max_rows=max_rows)

    def _init_arrays(self):
        self._init_pending()
        self.states = [
            jax.device_put(batch_tdigest.init_state(self.capacity), d)
            for d in self._devices]
        self._shard_counts = [np.zeros(self.capacity, np.int32)
                              for _ in self._devices]
        self.state = None  # unused; all device state lives in .states

    def _grow_arrays(self, new_cap):
        self._grow_shard_of(new_cap)
        grown = []
        for dev, st in zip(self._devices, self.states):
            new = batch_tdigest.init_state(new_cap)
            g = {k: jax.lax.dynamic_update_slice(
                    new[k], st[k], (0,) * new[k].ndim) for k in new}
            grown.append(jax.device_put(g, dev))
        self.states = grown
        extended = []
        for counts in self._shard_counts:
            e = np.zeros(new_cap, np.int32)
            e[: counts.shape[0]] = counts
            extended.append(e)
        self._shard_counts = extended

    def _fresh_state_at(self, capacity: int):
        return [jax.device_put(batch_tdigest.init_state(capacity), d)
                for d in self._devices]

    def _apply_to_shard(self, states, shard_counts, i: int, rows, vals,
                        wts) -> None:
        """One shard's masked fixed-shape batch apply over an explicit
        (states, staging-occupancy) generation — the live path passes
        the table's own, the flush readout the captured one; handles
        the per-shard staging compact."""
        dev = self._devices[i]
        slots, overflow = batch_tdigest.host_slots(
            rows, vals, wts, shard_counts[i])
        if overflow:
            states[i] = batch_tdigest.compact(states[i])
            shard_counts[i][:] = 0
            slots, _ = batch_tdigest.host_slots(
                rows, vals, wts, shard_counts[i])
        states[i] = batch_tdigest.apply_batch(
            states[i], jax.device_put(rows, dev),
            jax.device_put(vals, dev), jax.device_put(wts, dev),
            jax.device_put(slots, dev))

    def _apply_cols_states(self, states, shard_counts, cols) -> None:
        rows, vals, wts = cols
        if not self._digest_routed:
            # legacy round-robin: whole batch to the next shard
            i = self._rr_next
            self._rr_next = (i + 1) % self._n_shards
            self._apply_to_shard(states, shard_counts, i, rows, vals, wts)
            return
        home = self._home_of(rows)
        counts = self._shard_counts_of(home)
        for i in np.flatnonzero(counts).tolist():
            # masked, not split: the kernels' compiled (batch_cap,)
            # shape is preserved; non-home rows scatter-drop
            rows_i = np.where(home == i, rows, PAD_ROW)
            self._apply_to_shard(states, shard_counts, i, rows_i, vals,
                                 wts)
        self._plane.note_routed(self.family, counts)

    def _apply_cols(self, cols):
        self._apply_cols_states(self.states, self._shard_counts, cols)
        self._applies += 1

    def merge_batch(self, stubs, in_means, in_weights, in_min, in_max,
                    in_recip) -> None:
        """Import-path digest merge, routed per home shard (digest mode;
        digest merge is commutative across shards, so the legacy mode's
        single-shard landing stays correct too)."""
        with self.lock:
            rows = np.fromiter(
                (self.row_for(s) for s in stubs), np.int32, len(stubs))
            # cardinality-capped/rejected stubs drop out: scattering a
            # -1 row would negative-index the LAST device row
            ok = rows >= 0
            rows = rows[ok]
            self.touched[rows] = True
            self._note_applied(int(rows.size))
            home = (self._home_of(rows) if self._digest_routed
                    else np.full(rows.shape, self._rr_next, np.int32))
            if not self._digest_routed:
                self._rr_next = (self._rr_next + 1) % self._n_shards
            self.apply_lock.acquire()
        try:
            sel_arrs = tuple(np.asarray(a, np.float32)[ok]
                             for a in (in_means, in_weights, in_min,
                                       in_max, in_recip))
            for i in np.unique(home[home >= 0]).tolist():
                sel = home == i
                dev = self._devices[i]
                put = lambda a: jax.device_put(a, dev)  # noqa: E731
                self.states[i] = batch_tdigest.merge_centroid_rows(
                    self.states[i], put(rows[sel]),
                    *(put(a[sel]) for a in sel_arrs))
                # merge_centroid_rows folds every staged row on this
                # shard
                self._shard_counts[i][:] = 0
        finally:
            self.apply_lock.release()

    def _merged_state(self, states, note: bool = True
                      ) -> Dict[str, jnp.ndarray]:
        stacked = {
            k: collectives.stack_on_mesh(
                self._mesh, [st[k] for st in states])
            for k in states[0]}
        if note:
            self._plane.note_merge_round()
        return collectives.merge_histo_stacked(stacked)

    def _swap_extras_locked(self, snap: dict) -> None:
        snap["staged"] = self._shard_counts
        self._shard_counts = [np.zeros(self.capacity, np.int32)
                              for _ in self._devices]
        self._applies = 0

    def _readout_apply(self, states, cols, snap: dict):
        self._apply_cols_states(states, snap.pop("staged"), cols)
        return states

    def _readout_device(self, states, snap: dict) -> None:
        t0 = time.perf_counter()
        merged = self._merged_state(states)
        self._devobs_note_merge(time.perf_counter() - t0)
        ps = snap["ps"]
        if snap.pop("need_export"):
            # fused flush+export: one dispatch, two transfers (the
            # merged state's staging is already folded, so the fold
            # inside the fused op is a no-op concat of zeros).
            # Routed through the pallas-aware wrappers so
            # tpu.pallas_tdigest_flush applies to sharded stores too.
            packed, export_packed = self._flush_export(ps, merged)
        else:
            packed = self._flush_packed(ps, merged, fold_staging=False)
            export_packed = None
        snap["packed"] = packed
        snap["export_packed"] = export_packed
        snap["_recycle"] = states

    def _prewarm_apply(self, states, cols, capacity: int):
        counts = [np.zeros(capacity, np.int32) for _ in self._devices]
        rows, vals, wts = cols
        for i in range(self._n_shards):
            self._apply_to_shard(states, counts, i, rows, vals, wts)
        return states

    def _prewarm_readout(self, states, capacity: int, ps: tuple,
                         need_export: bool):
        merged = self._merged_state(states, note=False)
        if need_export:
            out = self._flush_export(ps, merged)
        else:
            out = self._flush_packed(ps, merged, fold_staging=False)
        return (out, self._reset_state_donated(states))

    def _retopo_device_locked(self) -> None:
        super()._retopo_device_locked()
        self._shard_counts = [np.zeros(self.capacity, np.int32)
                              for _ in self._devices]
        self._applies = 0

    def _reshard_capture_device(self, states, snap: dict) -> None:
        # concat + recompress across shards (staging already folded by
        # the readout apply above); the merged dict carries BOTH the
        # digest-side d* stats and the local-sample l* stats — the wire
        # encodes d* into MergingDigestData and the controller sidecars
        # l*, because merge_centroid_rows deliberately never touches l*
        snap["hstate"] = self._merged_state(states)

    def merge_local_stats(self, stubs, lmin, lmax, lsum, lweight,
                          lrecip) -> None:
        """Re-attach migrated LOCAL sample stats to their (new) home
        shards. The import merge path (merge_batch above) carries only
        the digest-side state — by design: a forwarded digest is remote
        data, its receiver has no local samples. A reshard migration is
        the one caller for which the l* stats ARE local history, so the
        controller replays them here right after the centroid merge
        (same stub batch, rows already interned and ledger-booked — no
        _note_applied)."""
        with self.lock:
            rows = np.fromiter(
                (self.row_for(s) for s in stubs), np.int32, len(stubs))
            ok = rows >= 0
            rows = rows[ok]
            home = self._home_of(rows)
            self.apply_lock.acquire()
        try:
            arrs = tuple(np.asarray(a, np.float32)[ok]
                         for a in (lmin, lmax, lsum, lweight, lrecip))
            for i in np.unique(home[home >= 0]).tolist():
                sel = home == i
                dev = self._devices[i]
                put = lambda a: jax.device_put(a, dev)  # noqa: E731
                rsel = put(rows[sel])
                st = dict(self.states[i])
                st["lmin"] = st["lmin"].at[rsel].min(put(arrs[0][sel]))
                st["lmax"] = st["lmax"].at[rsel].max(put(arrs[1][sel]))
                st["lsum"] = st["lsum"].at[rsel].add(put(arrs[2][sel]))
                st["lweight"] = st["lweight"].at[rsel].add(
                    put(arrs[3][sel]))
                st["lrecip"] = st["lrecip"].at[rsel].add(put(arrs[4][sel]))
                self.states[i] = st
        finally:
            self.apply_lock.release()


class ShardedSetTable(_PerDeviceStates, _DigestRouted, SetTable):
    """SetTable whose HLL register banks live across N local devices;
    ingest routes each key's stream to its home shard, flush merges
    registers with an all-reduce max (exact under any routing — max
    commutes — with digest routing keeping the key-range invariant)."""

    def __init__(self, capacity: int = 256, batch_cap: int = 8192,
                 devices: Optional[List] = None, max_rows: int = 0,
                 plane: Optional[ShardedServingPlane] = None):
        self._routing_init(capacity, devices, plane)
        # dense path: sharding already spreads register memory across
        # devices, and the collective merge needs uniform dense rows
        super().__init__(capacity, batch_cap, sparse=False,
                         max_rows=max_rows)

    def _init_arrays(self):
        self._init_pending()
        self.states = [
            jax.device_put(batch_hll.init_state(self.capacity), d)
            for d in self._devices]
        self.state = None

    def _grow_arrays(self, new_cap):
        self._grow_shard_of(new_cap)
        self.states = [
            jax.device_put(
                jnp.pad(st, [(0, new_cap - st.shape[0]), (0, 0)]), dev)
            for dev, st in zip(self._devices, self.states)]

    def _state_capacity(self) -> int:
        # dense per-device banks track row capacity (no slot ladder)
        return self.capacity

    def _fresh_state_at(self, capacity: int):
        return [jax.device_put(batch_hll.init_state(capacity), d)
                for d in self._devices]

    def _apply_cols_states(self, states, cols) -> None:
        rows, idxs, rhos = cols
        if not self._digest_routed:
            i = self._rr_next
            self._rr_next = (i + 1) % self._n_shards
            dev = self._devices[i]
            r, ix, rh = (jax.device_put(c, dev) for c in cols)
            states[i] = batch_hll.apply_batch(states[i], r, ix, rh)
            return
        home = self._home_of(rows)
        counts = self._shard_counts_of(home)
        for i in np.flatnonzero(counts).tolist():
            dev = self._devices[i]
            rows_i = np.where(home == i, rows, PAD_ROW)
            states[i] = batch_hll.apply_batch(
                states[i], jax.device_put(rows_i, dev),
                jax.device_put(idxs, dev), jax.device_put(rhos, dev))
        self._plane.note_routed(self.family, counts)

    def _apply_cols(self, cols):
        self._apply_cols_states(self.states, cols)

    def _readout_apply(self, states, cols, snap: dict):
        self._apply_cols_states(states, cols)
        return states

    def merge_batch(self, stubs, in_regs) -> None:
        with self.lock:
            rows = np.fromiter(
                (self.row_for(s) for s in stubs), np.int32, len(stubs))
            # cardinality-capped/rejected stubs drop out: scattering a
            # -1 row would negative-index the LAST device row
            ok = rows >= 0
            rows = rows[ok]
            self.touched[rows] = True
            self._note_applied(int(rows.size))
            home = (self._home_of(rows) if self._digest_routed
                    else np.full(rows.shape, self._rr_next, np.int32))
            if not self._digest_routed:
                self._rr_next = (self._rr_next + 1) % self._n_shards
            self.apply_lock.acquire()
        try:
            regs_sel = np.asarray(in_regs, np.int8)[ok]
            for i in np.unique(home[home >= 0]).tolist():
                sel = home == i
                dev = self._devices[i]
                self.states[i] = batch_hll.merge_rows(
                    self.states[i], jax.device_put(rows[sel], dev),
                    jax.device_put(regs_sel[sel], dev))
        finally:
            self.apply_lock.release()

    def _merged_state(self, states, note: bool = True) -> jnp.ndarray:
        stacked = collectives.stack_on_mesh(self._mesh, states)
        if note:
            self._plane.note_merge_round()
        return collectives.merge_hll_stacked(stacked)

    def _readout_device(self, states, snap: dict) -> None:
        t0 = time.perf_counter()
        merged = self._merged_state(states)
        self._devobs_note_merge(time.perf_counter() - t0)
        snap["estimates"] = np.asarray(batch_hll.estimate(merged))
        # lazy per-row provider (columnstore._SetRegisters): the
        # merged (K, M) bank only crosses the device link if a
        # consumer (the forward exporter) actually reads registers.
        # The provider references the MERGED bank, so the drained
        # per-device generations are recyclable.
        snap["registers"] = _SetRegisters.dense(merged, self.capacity)
        snap["_recycle"] = states

    def prewarm_rung(self, capacity: int, percentiles=(),
                     need_export: bool = True) -> bool:
        """Unlike the sparse table, the dense per-device banks DO track
        row capacity, so a resize retraces — prewarm the rung."""
        return _BaseTable.prewarm_rung(self, capacity, percentiles,
                                       need_export)

    def _prewarm_apply(self, states, cols, capacity: int):
        rows, idxs, rhos = cols
        for i, dev in enumerate(self._devices):
            states[i] = batch_hll.apply_batch(
                states[i], jax.device_put(rows, dev),
                jax.device_put(idxs, dev), jax.device_put(rhos, dev))
        return states

    def _prewarm_readout(self, states, capacity: int, ps: tuple,
                         need_export: bool):
        merged = self._merged_state(states, note=False)
        return (batch_hll.estimate(merged), _zeros_like_spare(states))

    def _reshard_capture_device(self, states, snap: dict) -> None:
        # elementwise register max, non-donating — bit-exact under
        # migration (max is idempotent and commutative)
        snap["regs"] = self._merged_state(states)
