"""Columnar egress: wire encoders that consume FlushBatch arrays directly.

Every sink used to call `batch.materialize()` and loop `for m in metrics`
building one dict/proto/line at a time — at 100k keys that per-InterMetric
Python was the last measured wall (BENCH_r05: `counter` 9.3k/s vs `hll`
3.3M/s). The encoders here walk the FlushBatch sections instead:

* per-row byte fragments (the name/tag-dependent part of a series) are
  rendered ONCE per key lifetime and cached against the row's identity —
  the tags-list object ref that RowMeta shares with every FlushSection —
  so a steady-state flush pays only value formatting + `b"".join`;
* value columns format in bulk off the float64 arrays;
* llhist cumulative buckets ride the BucketSection cumsum matrix — no
  per-line recomputation.

Parity is pinned byte-for-byte against the legacy materialize() path by
tests/test_egress.py (JSON key-order-normalized for Datadog, byte-identical
for Prometheus exposition and Cortex remote-write wire); `extras` rows
(status checks, WAL backfill) keep the legacy per-metric rendering, which
also keeps exemplar/backfill clauses exact.
"""

from __future__ import annotations

import json
import struct
from typing import Dict, List, Optional, Tuple

import numpy as np

from veneur_tpu.core.flusher import FlushBatch, le_tags
from veneur_tpu.samplers.metrics import InterMetric, MetricType

# fragment caches are bounded so a pathological tag churn can't grow a
# sink's cache without limit; at the cap the cache resets (one cold
# flush) rather than evicting piecemeal
FRAG_CACHE_CAP = 1 << 20

_MASK64 = (1 << 64) - 1
_INF = float("inf")


def _bulk_float_strs(values: np.ndarray) -> List[str]:
    """`str(v)` for every value — identical to the f-string/`json.dumps`
    rendering of the same python float (shortest-repr)."""
    return [repr(v) for v in values.tolist()]


def _json_num(v: float) -> str:
    """json.dumps' rendering of one float (Infinity/NaN spellings)."""
    if v == v and v != _INF and v != -_INF:
        return repr(v)
    if v != v:
        return "NaN"
    return "Infinity" if v > 0 else "-Infinity"


# --------------------------------------------------------------------------
# Datadog: series JSON by byte-assembly
# --------------------------------------------------------------------------


class DatadogColumnarEncoder:
    """`{"series": [...]}` body parts straight from FlushBatch columns.

    Per row the invariant JSON prefix — everything up to the inside of
    the `"tags"` array — is cached by `(name, id(tags), kind)`; the
    cache entry holds the tags-list ref so the id can't be recycled.
    A flush then appends `],"points":[[ts,value]]}` per row (buckets
    splice their `le:` tag into the open tags array first). Key order
    inside a series object differs from the legacy `_dd_metric` dict
    (tags rendered last); the parity suite compares key-order
    normalized, which is also the JSON object contract."""

    def __init__(self, sink):
        self.sink = sink
        # (name, id(tags), kind) -> (tags_ref, prefix_bytes|None, has_tags)
        self._frags: Dict[tuple, tuple] = {}

    def _prefix(self, name: str, tags: list,
                is_counter: bool) -> Tuple[Optional[bytes], bool]:
        """The series-object bytes through the open tags array (no
        closing `]}`), or None when the metric's name prefix drops it."""
        sink = self.sink
        if sink.metric_name_prefix_drops and any(
                name.startswith(p) for p in sink.metric_name_prefix_drops):
            return None, False
        out_tags = list(sink.tags)
        host = sink.hostname
        device = ""
        per_metric_excludes = ()
        for prefix, excludes in \
                sink.exclude_tags_prefix_by_prefix_metric.items():
            if name.startswith(prefix):
                per_metric_excludes = excludes
                break
        for t in tags:
            if t.startswith("host:"):
                host = t[5:]
            elif t.startswith("device:"):
                device = t[7:]
            elif (any(t.startswith(p) for p in sink.excluded_tag_prefixes)
                  or any(t.startswith(p) for p in per_metric_excludes)):
                continue
            else:
                out_tags.append(t)
        head = {
            "metric": name,
            "type": "rate" if is_counter else "gauge",
            "host": host,
            "interval": int(sink.interval) or 1,
        }
        if device:
            head["device"] = device
        head["tags"] = out_tags
        enc = json.dumps(head, separators=(",", ":")).encode()
        return enc[:-2], bool(out_tags)  # strip the tags-closing `]}`

    def _frag(self, name: str, tags: list, is_counter: bool):
        key = (name, id(tags), is_counter)
        ent = self._frags.get(key)
        if ent is None:
            if len(self._frags) >= FRAG_CACHE_CAP:
                self._frags.clear()
            prefix, has_tags = self._prefix(name, tags, is_counter)
            ent = self._frags[key] = (tags, prefix, has_tags)
        return ent

    def encode(self, batch: FlushBatch) -> Tuple[List[bytes],
                                                 List[InterMetric]]:
        """-> (series body parts, status checks). Joining parts with
        b"," inside `{"series":[...]}` is the POST body."""
        sink = self.sink
        parts: List[bytes] = []
        checks: List[InterMetric] = []
        ts_b = b"%d" % batch.timestamp
        interval = sink.interval
        for sec in batch.sections:
            is_counter = sec.mtype == MetricType.COUNTER
            vals = sec.values / interval if is_counter else sec.values
            if np.isfinite(vals).all():
                val_strs = [repr(v).encode() for v in vals.tolist()]
            else:
                val_strs = [_json_num(v).encode() for v in vals.tolist()]
            names = sec.names.tolist()
            tagrows = sec.tags.tolist()
            frag = self._frag
            for i, nm in enumerate(names):
                _tags, prefix, _ht = frag(nm, tagrows[i], is_counter)
                if prefix is None:
                    continue
                parts.append(prefix + b'],"points":[[' + ts_b + b","
                             + val_strs[i] + b"]]}")
        if batch.bucket_sections:
            les = _dd_le_json()
            for bs in batch.bucket_sections:
                names = bs.names.tolist()
                tagrows = bs.tags.tolist()
                csum, nz = bs.csum, bs.nz
                for i, nm in enumerate(names):
                    _tags, prefix, has_tags = \
                        self._frag(nm, tagrows[i], True)
                    if prefix is None:
                        continue
                    sep = b"," if has_tags else b""
                    row = csum[i] / interval
                    idxs = np.flatnonzero(nz[i]).tolist()
                    vals_k = row[idxs].tolist() + [float(row[-1])]
                    for k, v in zip(idxs + [-1], vals_k):
                        parts.append(prefix + sep + les[k]
                                     + b'],"points":[[' + ts_b + b","
                                     + _json_num(v).encode() + b"]]}")
        for m in batch.extras:
            if sink.metric_name_prefix_drops and any(
                    m.name.startswith(p)
                    for p in sink.metric_name_prefix_drops):
                continue
            if m.type == MetricType.STATUS:
                checks.append(m)
            else:
                parts.append(json.dumps(
                    sink._dd_metric(m), separators=(",", ":")).encode())
        return parts, checks


_DD_LE_JSON: Optional[List[bytes]] = None


def _dd_le_json() -> List[bytes]:
    global _DD_LE_JSON
    if _DD_LE_JSON is None:
        _DD_LE_JSON = [json.dumps(t).encode() for t in le_tags()]
    return _DD_LE_JSON


# --------------------------------------------------------------------------
# Prometheus: exposition text
# --------------------------------------------------------------------------


class PrometheusColumnarRenderer:
    """render_exposition, but off FlushBatch columns — byte-identical
    output (pinned by tests/test_egress.py). Caches the sanitized name
    per metric name and the rendered label interior per tags-list
    identity; section rows are never backfilled, so only `extras` pay
    the per-metric stamp/exemplar logic of the legacy renderer."""

    def __init__(self):
        self._names: Dict[str, str] = {}
        self._labels: Dict[int, tuple] = {}  # id(tags) -> (ref, interior)

    def _name(self, name: str) -> str:
        out = self._names.get(name)
        if out is None:
            from veneur_tpu.sinks.cortex import sanitize_name
            if len(self._names) >= FRAG_CACHE_CAP:
                self._names.clear()
            out = self._names[name] = sanitize_name(name)
        return out

    def _label_interior(self, tags: list) -> str:
        ent = self._labels.get(id(tags))
        if ent is None:
            from veneur_tpu.sinks.cortex import sanitize_label
            from veneur_tpu.sinks.prometheus import escape_label_value
            if len(self._labels) >= FRAG_CACHE_CAP:
                self._labels.clear()
            parts = []
            for t in tags:
                k, _, v = t.partition(":")
                parts.append(
                    f'{sanitize_label(k)}="{escape_label_value(v)}"')
            ent = self._labels[id(tags)] = (tags, ",".join(parts))
        return ent[1]

    def render(self, batch: FlushBatch, exemplars=None,
               openmetrics: bool = False) -> str:
        from veneur_tpu.sinks.prometheus import exemplar_clause_for

        lines: List[str] = []
        exemplified: set = set()
        for sec in batch.sections:
            names = sec.names.tolist()
            tagrows = sec.tags.tolist()
            val_strs = _bulk_float_strs(sec.values)
            check_ex = (exemplars is not None
                        and sec.mtype == MetricType.COUNTER)
            for i, nm in enumerate(names):
                interior = self._label_interior(tagrows[i])
                label_str = "{" + interior + "}" if interior else ""
                clause = ""
                if check_ex:
                    clause = exemplar_clause_for(
                        _ExemplarProbe(nm, tagrows[i]),
                        exemplars, exemplified)
                lines.append(f"{self._name(nm)}{label_str} "
                             f"{val_strs[i]}{clause}")
        if batch.bucket_sections:
            les = _prom_le_labels()
            le_tag_strs = le_tags()
            for bs in batch.bucket_sections:
                names = bs.names.tolist()
                tagrows = bs.tags.tolist()
                csum, nz = bs.csum, bs.nz
                for i, nm in enumerate(names):
                    sname = self._name(nm)
                    interior = self._label_interior(tagrows[i])
                    pre = "{" + interior + "," if interior else "{"
                    row = csum[i]
                    idxs = np.flatnonzero(nz[i]).tolist()
                    vals_k = row[idxs].tolist() + [float(row[-1])]
                    for k, v in zip(idxs + [-1], vals_k):
                        clause = ""
                        if exemplars is not None:
                            clause = exemplar_clause_for(
                                _ExemplarProbe(
                                    nm, tagrows[i] + [le_tag_strs[k]]),
                                exemplars, exemplified)
                        lines.append(f"{sname}{pre}{les[k]}}} "
                                     f"{v}{clause}")
        for m in batch.extras:
            if m.type == MetricType.STATUS:
                continue
            interior = self._label_interior(m.tags)
            label_str = "{" + interior + "}" if interior else ""
            clause = exemplar_clause_for(m, exemplars, exemplified)
            if m.backfilled:
                stamp = (f" {int(m.timestamp)}" if openmetrics
                         else f" {int(m.timestamp) * 1000}")
            else:
                stamp = ""
            lines.append(f"{self._name(m.name)}{label_str} {m.value}"
                         f"{stamp}{clause}")
        return "\n".join(lines) + ("\n" if lines else "")


class _ExemplarProbe:
    """Duck-typed COUNTER InterMetric for exemplar_clause_for (the
    clause logic only reads name/tags/type)."""

    __slots__ = ("name", "tags")
    type = MetricType.COUNTER

    def __init__(self, name: str, tags: list):
        self.name = name
        self.tags = tags


_PROM_LE: Optional[List[str]] = None


def _prom_le_labels() -> List[str]:
    """`le="<bound>"` rendered label per sorted bin (+Inf last) —
    bounds never contain escapable characters."""
    global _PROM_LE
    if _PROM_LE is None:
        _PROM_LE = [f'le="{t.partition(":")[2]}"' for t in le_tags()]
    return _PROM_LE


# --------------------------------------------------------------------------
# Cortex: remote-write protobuf TimeSeries frames
# --------------------------------------------------------------------------


class CortexColumnarEncoder:
    """WriteRequest TimeSeries frames hand-packed from FlushBatch
    columns, byte-identical to `_series` + `encode_write_request`
    (pinned by tests/test_egress.py). The sorted Label block per row
    caches against (name, tags identity); samples assemble from the
    bulk little-endian float64 dump of the value column plus one
    precomputed timestamp varint. Bucket rows cache the label block
    split at the `le` insertion point so every bin line is two joins.

    Returns the series FRAMES (field-1 bytes); concatenating a chunk of
    frames IS encode_write_request's output for that chunk, so the
    sink's batch_write_size chunking and snappy+POST stay unchanged."""

    def __init__(self, sink):
        self.sink = sink
        self._blocks: Dict[tuple, tuple] = {}   # (name,id) -> (ref, block)
        self._bucket_blocks: Dict[tuple, tuple] = {}  # -> (ref, pre, post)

    def _label_items(self, name: str, tags: list) -> List[tuple]:
        from veneur_tpu.sinks.cortex import sanitize_label, sanitize_name

        sink = self.sink
        labels = {"__name__": sanitize_name(name)}
        for t in tags:
            k, _, v = t.partition(":")
            if k in sink.excluded_tags:
                continue
            labels[sanitize_label(k)] = v  # last write wins on dupes
        if sink.hostname:  # section rows carry no per-metric hostname
            labels.setdefault("host", sink.hostname)
        return sorted(labels.items())

    def _block(self, name: str, tags: list) -> bytes:
        key = (name, id(tags))
        ent = self._blocks.get(key)
        if ent is None:
            from veneur_tpu.sinks.cortex import _encode_label, _field_bytes
            if len(self._blocks) >= FRAG_CACHE_CAP:
                self._blocks.clear()
            block = b"".join(_field_bytes(1, _encode_label(k, v))
                             for k, v in self._label_items(name, tags))
            ent = self._blocks[key] = (tags, block)
        return ent[1]

    def _bucket_block(self, name: str, tags: list) -> Tuple[bytes, bytes]:
        """(pre, post) label-block halves around the sorted insertion
        point of the `le` label; a base `le:` tag is dropped here
        because the bucket's own le label overwrites it (legacy: the
        appended le tag wins last-write in the labels dict)."""
        key = (name, id(tags))
        ent = self._bucket_blocks.get(key)
        if ent is None:
            from veneur_tpu.sinks.cortex import _encode_label, _field_bytes
            if len(self._bucket_blocks) >= FRAG_CACHE_CAP:
                self._bucket_blocks.clear()
            items = [kv for kv in self._label_items(name, tags)
                     if kv[0] != "le"]
            idx = 0
            while idx < len(items) and items[idx][0] < "le":
                idx += 1
            pre = b"".join(_field_bytes(1, _encode_label(k, v))
                           for k, v in items[:idx])
            post = b"".join(_field_bytes(1, _encode_label(k, v))
                            for k, v in items[idx:])
            ent = self._bucket_blocks[key] = (tags, pre, post)
        return ent[1], ent[2]

    def encode(self, batch: FlushBatch) -> Tuple[List[bytes], int]:
        """-> (TimeSeries frames in legacy order, max metric timestamp
        seen). The max-timestamp fold rides the encode pass (the legacy
        flush re-scanned every metric for it in monotonic mode)."""
        from veneur_tpu.sinks.cortex import (
            _encode_exemplar, _field_bytes, _varint, encode_write_request,
        )

        sink = self.sink
        frames: List[bytes] = []
        exemplified: set = set()
        max_ts = 0
        ts = batch.timestamp
        ts_tail = b"\x10" + _varint((ts * 1000) & _MASK64)
        sample_len = 9 + len(ts_tail)
        sample_hdr = b"\x12" + _varint(sample_len)
        mono = sink.convert_counters_to_monotonic
        check_ex = sink._exemplars is not None
        monotonic = sink._monotonic
        for sec in batch.sections:
            n = sec.names.shape[0]
            if n == 0:
                continue
            if ts > max_ts:
                max_ts = ts
            is_counter = sec.mtype == MetricType.COUNTER
            names = sec.names.tolist()
            tagrows = sec.tags.tolist()
            if is_counter and mono:
                for nm, tg, v in zip(names, tagrows,
                                     sec.values.tolist()):
                    key = (nm, tuple(sorted(tg)), "")
                    monotonic[key] = monotonic.get(key, 0.0) + v
                continue
            vb = sec.values.astype("<f8").tobytes()
            row_ex = check_ex and is_counter
            for i, nm in enumerate(names):
                body = (self._block(nm, tagrows[i]) + sample_hdr
                        + b"\x09" + vb[8 * i:8 * i + 8] + ts_tail)
                if row_ex:
                    ex = self._exemplar(nm, tagrows[i], exemplified)
                    if ex is not None:
                        body += _field_bytes(3, _encode_exemplar(*ex))
                frames.append(b"\x0a" + _varint(len(body)) + body)
        if batch.bucket_sections:
            les = _cortex_le_labels()
            le_strs = le_tags()
            for bs in batch.bucket_sections:
                if bs.names.shape[0] and ts > max_ts:
                    max_ts = ts
                names = bs.names.tolist()
                tagrows = bs.tags.tolist()
                csum, nz = bs.csum, bs.nz
                for i, nm in enumerate(names):
                    if mono:
                        base = tagrows[i]
                        row = csum[i]
                        for k in np.flatnonzero(nz[i]).tolist():
                            key = (nm, tuple(sorted(base + [le_strs[k]])),
                                   "")
                            monotonic[key] = (monotonic.get(key, 0.0)
                                              + float(row[k]))
                        key = (nm, tuple(sorted(base + ["le:+Inf"])), "")
                        monotonic[key] = (monotonic.get(key, 0.0)
                                          + float(row[-1]))
                        continue
                    pre, post = self._bucket_block(nm, tagrows[i])
                    row = csum[i]
                    vrow = row.astype("<f8").tobytes()
                    for k in np.flatnonzero(nz[i]).tolist() + [-1]:
                        body = (pre + les[k] + post + sample_hdr + b"\x09"
                                + vrow[8 * k:8 * k + 8 or None] + ts_tail)
                        if check_ex:
                            ex = self._exemplar(
                                nm, tagrows[i] + [le_strs[k]], exemplified)
                            if ex is not None:
                                body += _field_bytes(
                                    3, _encode_exemplar(*ex))
                        frames.append(b"\x0a" + _varint(len(body)) + body)
        for m in batch.extras:
            if m.timestamp > max_ts:
                max_ts = m.timestamp
            if m.type == MetricType.STATUS:
                continue
            if m.type == MetricType.COUNTER and mono:
                key = (m.name, tuple(sorted(m.tags)), m.hostname)
                monotonic[key] = monotonic.get(key, 0.0) + float(m.value)
                continue
            row = sink._series(m)
            entry = sink._exemplar_entry(m, exemplified)
            if entry is not None:
                from veneur_tpu.trace.store import trace_id_hex
                tid, ev, ets = entry
                row = row + ((trace_id_hex(tid), float(ev),
                              int(ets * 1000)),)
            frames.append(encode_write_request([row]))
        return frames, max_ts

    def _exemplar(self, name: str, tags: list, exemplified: set):
        """sink._exemplar_entry for a columnar COUNTER row, converted
        to _encode_exemplar's argument tuple."""
        entry = self.sink._exemplar_entry(
            _ExemplarProbe(name, tags), exemplified)
        if entry is None:
            return None
        from veneur_tpu.trace.store import trace_id_hex
        tid, ev, ets = entry
        return trace_id_hex(tid), float(ev), int(ets * 1000)


_CORTEX_LE: Optional[List[bytes]] = None


def _cortex_le_labels() -> List[bytes]:
    global _CORTEX_LE
    if _CORTEX_LE is None:
        from veneur_tpu.sinks.cortex import _encode_label, _field_bytes
        _CORTEX_LE = [
            _field_bytes(1, _encode_label("le", t.partition(":")[2]))
            for t in le_tags()]
    return _CORTEX_LE
