"""Pull-side self-telemetry: internal registry + flight recorder.

The server's self-metrics are push-only (util/scopedstatsd.py fires them
into the statsd loopback and forgets them). This module is the pull side
of that loop — the analog of the reference's expvar/pprof surface, and
what SALSA (arXiv:2102.12531) and the Circllhist paper (arXiv:2001.06561)
argue every aggregation tier needs: cheap, always-on, bounded-memory
internal state an operator can inspect at the moment of an incident.

Three pieces, all thread-safe and all O(1)-bounded:

- `Registry`: counters / gauges / fixed-bin histograms keyed by
  (name, tags). Every `ScopedClient` emission tees in here (the
  ~40 existing statsd call sites are captured without rewriting them),
  and `render_prometheus` serves the whole registry as text exposition
  for `GET /metrics`.
- `EventRecorder`: a ring-buffer flight recorder of notable events
  (flush rounds, sink errors/skips/timeouts, forward outcomes, watchdog
  ticks, restarts) for `GET /debug/events`.
- `FlushRecorder`: the last N flush rounds with per-phase and per-sink
  latency for `GET /debug/flush`.
"""

from __future__ import annotations

import bisect
import json
import logging
import threading
import time
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

logger = logging.getLogger("veneur_tpu.telemetry")

# Fixed histogram bucket ladder (seconds-oriented, but unit-agnostic):
# 1-2-5 decades from 100µs to 100s. 19 bins + overflow, allocated once
# per series — the capped-bin design the Circllhist paper motivates.
HISTOGRAM_BOUNDS: Tuple[float, ...] = tuple(
    round(m * 10.0 ** e, 10)
    for e in range(-4, 2) for m in (1.0, 2.0, 5.0)
) + (100.0,)

# Series cap: a registry is fed by self-metrics only (bounded-cardinality
# names + tags), so the cap exists to bound a bug, not normal operation.
DEFAULT_MAX_SERIES = 4096

# Overflow attribution cap: at most this many distinct metric NAMES get
# their own series_dropped_by_name counter; later names pool into the
# "_other" bucket. Bounds the debugging aid the same way the registry
# itself is bounded.
MAX_DROPPED_NAMES = 64


def _tags_key(tags: Sequence[str]) -> Tuple[str, ...]:
    return tuple(sorted(tags)) if tags else ()


class _Histogram:
    """Fixed-bound bucket counts + sum/count/min/max. No locking of its
    own; the owning Registry serializes mutation."""

    __slots__ = ("buckets", "count", "sum")

    def __init__(self):
        self.buckets = [0] * (len(HISTOGRAM_BOUNDS) + 1)
        self.count = 0
        self.sum = 0.0

    def observe(self, value: float) -> None:
        self.buckets[bisect.bisect_left(HISTOGRAM_BOUNDS, value)] += 1
        self.count += 1
        self.sum += value


class Registry:
    """Thread-safe counter/gauge/histogram store with a hard series cap.

    `record_statsd` is the ScopedClient tee: statsd kinds map onto the
    registry types (c -> counter with 1/rate scaling, g -> gauge,
    ms -> histogram, observed in seconds).
    """

    def __init__(self, max_series: int = DEFAULT_MAX_SERIES):
        self.max_series = max_series
        # OpenMetrics exemplars: callable(name, tags) -> rendered
        # exemplar clause (or None), consulted per sample line at
        # exposition time. The server wires the self-trace plane's
        # exemplar_for here so /metrics rows (pipeline.sample_age and
        # friends) carry the interval trace that produced them.
        self.exemplar_source = None
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, Tuple[str, ...]], float] = {}
        self._gauges: Dict[Tuple[str, Tuple[str, ...]], float] = {}
        self._histograms: Dict[Tuple[str, Tuple[str, ...]], _Histogram] = {}
        self.series_dropped = 0
        # overflow attribution: name -> drops since the cap was hit, so
        # a silent lossy drop becomes debuggable (which emitter blew the
        # cap?). Bounded at MAX_DROPPED_NAMES; the first drop per name
        # is logged once (rate-limited by construction).
        self.dropped_by_name: Dict[str, int] = {}
        # collectors: zero-arg callables returning (name, kind, value,
        # tags) rows rendered fresh at scrape time (live counters the
        # registry doesn't own, device memory, ...)
        self._collectors: List[Callable[[], Iterable[tuple]]] = []

    # -- writes ----------------------------------------------------------

    def _slot(self, table: dict, name: str, tags: Sequence[str]):
        key = (name, _tags_key(tags))
        if key not in table and self._series_count() >= self.max_series:
            self.series_dropped += 1
            dropped = self.dropped_by_name
            if name in dropped:
                dropped[name] += 1
            elif len(dropped) < MAX_DROPPED_NAMES:
                dropped[name] = 1
                logger.warning(
                    "telemetry registry full (max_series=%d): dropping "
                    "new series for %r (first drop for this name; "
                    "telemetry.series_dropped_by_name counts the rest)",
                    self.max_series, name)
            else:
                dropped["_other"] = dropped.get("_other", 0) + 1
            return None
        return key

    def _series_count(self) -> int:
        return (len(self._counters) + len(self._gauges)
                + len(self._histograms))

    def count(self, name: str, value: float = 1.0,
              tags: Sequence[str] = ()) -> None:
        with self._lock:
            key = self._slot(self._counters, name, tags)
            if key is not None:
                self._counters[key] = self._counters.get(key, 0.0) + value

    def gauge(self, name: str, value: float,
              tags: Sequence[str] = ()) -> None:
        with self._lock:
            key = self._slot(self._gauges, name, tags)
            if key is not None:
                self._gauges[key] = float(value)

    def observe(self, name: str, value: float,
                tags: Sequence[str] = ()) -> None:
        with self._lock:
            key = self._slot(self._histograms, name, tags)
            if key is not None:
                hist = self._histograms.get(key)
                if hist is None:
                    hist = self._histograms[key] = _Histogram()
                hist.observe(value)

    def record_statsd(self, name: str, value, kind: str,
                      tags: Sequence[str], rate: float) -> None:
        """Tee one statsd emission (kind in c/g/ms) into the registry."""
        try:
            if kind == "c":
                scale = 1.0 / rate if 0.0 < rate < 1.0 else 1.0
                self.count(name, float(value) * scale, tags)
            elif kind == "g":
                self.gauge(name, float(value), tags)
            elif kind == "ms":
                # ScopedClient.timing renders ms; the registry keeps
                # seconds so the exposition is Prometheus-idiomatic
                self.observe(name, float(value) / 1000.0, tags)
        except (TypeError, ValueError):
            pass

    # -- collectors ------------------------------------------------------

    def add_collector(self, fn: Callable[[], Iterable[tuple]]) -> None:
        """Register a scrape-time row source. `fn` returns rows of
        (name, kind, value, tags) with kind "counter" or "gauge"; a
        collector that raises is skipped for that scrape."""
        with self._lock:
            self._collectors.append(fn)

    # -- reads -----------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": {self._flat(k): v
                             for k, v in self._counters.items()},
                "gauges": {self._flat(k): v
                           for k, v in self._gauges.items()},
                "histograms": {self._flat(k): h.count
                               for k, h in self._histograms.items()},
                "series_dropped": self.series_dropped,
                "series_dropped_by_name": dict(self.dropped_by_name),
            }

    @staticmethod
    def _flat(key: Tuple[str, Tuple[str, ...]]) -> str:
        name, tags = key
        return f"{name}|{','.join(tags)}" if tags else name

    def render_prometheus(self, exemplars: bool = False) -> str:
        """The whole registry (plus collectors) as Prometheus text
        exposition format 0.0.4. With `exemplars=True` (the operator
        asked for OpenMetrics — content negotiation happens in the
        HTTP layer, which also switches the content type and appends
        `# EOF`), counter lines matching the exemplar source gain the
        OpenMetrics exemplar clause — counters only (exemplars on
        gauges are invalid OpenMetrics) and once per metric name."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = {k: (list(h.buckets), h.count, h.sum)
                          for k, h in self._histograms.items()}
            collectors = list(self._collectors)
            dropped = self.series_dropped
            dropped_by_name = dict(self.dropped_by_name)
        for fn in collectors:
            try:
                for name, kind, value, tags in fn():
                    key = (name, _tags_key(tags))
                    if kind == "counter":
                        counters[key] = counters.get(key, 0.0) + value
                    else:
                        gauges[key] = value
            except Exception:
                continue
        gauges[("telemetry.series_dropped", ())] = float(dropped)
        for name, n in dropped_by_name.items():
            counters[("telemetry.series_dropped_by_name",
                      (f"name:{name}",))] = float(n)

        exemplar_source = self.exemplar_source if exemplars else None
        exemplified: set = set()

        def exemplar_clause(name: str, tags, ptype: str) -> str:
            if (exemplar_source is None or ptype != "counter"
                    or name in exemplified):
                return ""
            try:
                clause = exemplar_source(name, tags) or ""
            except Exception:
                return ""
            if clause:
                exemplified.add(name)
            return clause

        out: List[str] = []
        for table, ptype in ((counters, "counter"), (gauges, "gauge")):
            grouped: Dict[str, list] = {}
            for (name, tags), value in table.items():
                grouped.setdefault(name, []).append((tags, value))
            for metric in sorted(grouped):
                pname = prom_name(metric, ptype)
                out.append(f"# TYPE {pname} {ptype}")
                for tags, value in sorted(grouped[metric]):
                    out.append(f"{pname}{prom_labels(tags)} {fnum(value)}"
                               f"{exemplar_clause(metric, tags, ptype)}")
        hgrouped: Dict[str, list] = {}
        for (name, tags), series in histograms.items():
            hgrouped.setdefault(name, []).append((tags, series))
        for metric in sorted(hgrouped):
            pname = prom_name(metric, "histogram")
            out.append(f"# TYPE {pname} histogram")
            for tags, (buckets, count, total) in sorted(hgrouped[metric]):
                cum = 0
                for bound, n in zip(HISTOGRAM_BOUNDS, buckets):
                    cum += n
                    out.append(f"{pname}_bucket"
                               f"{prom_labels(tags, le=fnum(bound))} {cum}")
                out.append(f"{pname}_bucket"
                           f"{prom_labels(tags, le='+Inf')} {count}")
                out.append(f"{pname}_sum{prom_labels(tags)} {fnum(total)}")
                out.append(f"{pname}_count{prom_labels(tags)} {count}")
        return "\n".join(out) + "\n"


# -- Prometheus text helpers ----------------------------------------------

def fnum(value: float) -> str:
    """Shortest faithful rendering: integers without the trailing .0."""
    f = float(value)
    return str(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


def prom_name(name: str, ptype: str = "gauge") -> str:
    """Dotted self-metric name -> valid Prometheus metric name, under the
    veneur_ namespace; counters gain the conventional _total suffix."""
    cleaned = "".join(ch if ch.isalnum() or ch == "_" else "_"
                      for ch in name)
    if cleaned and cleaned[0].isdigit():
        cleaned = "_" + cleaned
    full = f"veneur_{cleaned}"
    if ptype == "counter" and not full.endswith("_total"):
        full += "_total"
    return full


def prom_label_escape(value: str) -> str:
    return (value.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def prom_labels(tags: Sequence[str], le: Optional[str] = None) -> str:
    """DogStatsD tags ("k:v" or bare "flag") -> a Prometheus label set."""
    pairs: List[Tuple[str, str]] = []
    for tag in tags:
        k, sep, v = tag.partition(":")
        if not sep:
            k, v = "tag", tag
        k = "".join(ch if ch.isalnum() or ch == "_" else "_" for ch in k)
        if not k or k[0].isdigit():
            k = "tag_" + k
        pairs.append((k, prom_label_escape(v)))
    if le is not None:
        pairs.append(("le", le))
    if not pairs:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in pairs) + "}"


# -- flight recorder ------------------------------------------------------

class EventRecorder:
    """Bounded ring buffer of notable events — the black-box recorder.

    `record` costs one deque append under a lock; the ring drops the
    oldest event on overflow (memory stays bounded under sustained event
    load by construction). Events carry a wall-clock timestamp and a
    monotonic sequence number so a reader can detect gaps after a wrap.
    """

    def __init__(self, capacity: int = 512):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=capacity)
        self._seq = 0

    def record(self, kind: str, **fields) -> dict:
        event = {"seq": 0, "ts": time.time(), "kind": kind}
        event.update(fields)
        with self._lock:
            self._seq += 1
            event["seq"] = self._seq
            self._events.append(event)
        return event

    def snapshot(self, limit: int = 0, kind: str = "",
                 trace_id: str = "") -> List[dict]:
        """Newest-last; `limit` > 0 keeps only the most recent events;
        `kind` filters to one event kind (e.g. overload_state,
        pipeline_stall) BEFORE the limit applies, so an operator can
        pull the last N ladder transitions even when chatty events
        (watchdog ticks, flush rounds) dominate the ring. `trace_id`
        (hex) keeps only events stamped with that interval trace, so a
        /debug/ledger or /debug/traces finding cross-links to exactly
        the events of its interval."""
        with self._lock:
            events = list(self._events)
        if kind:
            events = [e for e in events if e.get("kind") == kind]
        if trace_id:
            events = [e for e in events if e.get("trace_id") == trace_id]
        return events[-limit:] if limit > 0 else events

    @property
    def total_recorded(self) -> int:
        with self._lock:
            return self._seq

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)


class FlushRecorder:
    """The last N flush rounds, each a dict with phase timings and
    per-sink outcomes. Sink threads keep a reference to their round's
    dict, so a straggler that finishes after its round was recorded
    still lands its final status (flagged `late`)."""

    def __init__(self, capacity: int = 64):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._rounds: deque = deque(maxlen=capacity)

    def record(self, round_info: dict) -> None:
        with self._lock:
            self._rounds.append(round_info)

    def snapshot(self, limit: int = 0) -> List[dict]:
        with self._lock:
            # per-sink outcome dicts are still mutated by straggler sink
            # threads (that sharing is what lets a late finish land), so
            # copy them too — a reader iterating a shared dict while the
            # straggler inserts a key would blow up mid-serialization
            rounds = [dict(r, sinks={k: dict(v)
                                     for k, v in r.get("sinks", {}).items()})
                      for r in self._rounds]
        return rounds[-limit:] if limit > 0 else rounds

    def __len__(self) -> int:
        with self._lock:
            return len(self._rounds)


class Telemetry:
    """One server's (or proxy's) pull-side telemetry: the registry the
    statsd tee feeds, the event flight recorder, and the flush-round
    table. Constructed unconditionally — recording is cheap enough to be
    always-on, which is the whole point of a flight recorder."""

    def __init__(self, max_series: int = DEFAULT_MAX_SERIES,
                 event_capacity: int = 512, flush_capacity: int = 64):
        self.registry = Registry(max_series=max_series)
        self.events = EventRecorder(capacity=event_capacity)
        self.flushes = FlushRecorder(capacity=flush_capacity)
        # active interval trace stamp: zero-arg callable returning the
        # running interval's trace id (hex, '' when unsampled). When
        # set, every recorded event carries it, so the flight recorder
        # cross-links to /debug/traces (?trace_id= filters on it).
        self.trace_source = None

    def record_event(self, kind: str, **fields) -> dict:
        if self.trace_source is not None and "trace_id" not in fields:
            try:
                tid = self.trace_source()
            except Exception:
                tid = ""
            if tid:
                fields["trace_id"] = tid
        return self.events.record(kind, **fields)

    def events_json(self, limit: int = 0, kind: str = "",
                    trace_id: str = "") -> bytes:
        return json.dumps({
            "capacity": self.events.capacity,
            "total_recorded": self.events.total_recorded,
            "events": self.events.snapshot(limit, kind=kind,
                                           trace_id=trace_id),
        }, indent=2, default=str).encode()

    def flushes_json(self, limit: int = 0) -> bytes:
        return json.dumps({
            "capacity": self.flushes.capacity,
            "rounds": self.flushes.snapshot(limit),
        }, indent=2, default=str).encode()


def device_memory_rows() -> List[tuple]:
    """Per-device HBM gauges for the /metrics collector: bytes in use,
    limit, and peak from jax.Device.memory_stats() (absent off-device)."""
    rows: List[tuple] = []
    try:
        import jax
        for i, d in enumerate(jax.devices()):
            try:
                ms = d.memory_stats() or {}
            except Exception:
                continue
            tags = [f"device:{i}", f"platform:{d.platform}"]
            for stat, metric in (("bytes_in_use", "device.bytes_in_use"),
                                 ("bytes_limit", "device.bytes_limit"),
                                 ("peak_bytes_in_use",
                                  "device.peak_bytes_in_use")):
                value = ms.get(stat)
                if value is not None:
                    rows.append((metric, "gauge", float(value), tags))
    except Exception:
        pass
    return rows
