"""Sub-interval live query plane: serve reads between flushes.

The flush interval used to be the only consistent read point — every
row's value materialized once per interval, at swap. PR 15's
double-buffered generation swap made a read-only capture of the live
device generation an O(1) operation, and both sketch families were
chosen for exactly this kind of online interrogation: t-digests give
mergeable accuracy-bounded quantiles at any moment, Circllhist bins a
one-pass quantile/count readout with a fixed error bound. This module
turns that into a serving surface: `GET /query` answers percentile /
count / rate / cardinality / bin-occupancy lookups for a metric name +
tag filter with sub-interval latency, against the LIVE generation.

Mechanics (core/columnstore.py owns the capture protocol):

  capture   `_BaseTable.capture_readonly()` — fold the pending columns
            into the live state through the normal dispatch path, then
            capture touched/meta/extras and the live device arrays BY
            REFERENCE under the table locks. No swap, no reset, no
            generation advance; residual pending samples after the
            bounded fold are the query's reported staleness.
  readout   `query_readout()` on the server's supervised flush executor
            (core/flushexec.py) — the same single worker the background
            flush readout runs on, so a query can never collide with an
            in-flight readout's donated buffers. Sharded tables
            dispatch the NON-reset collective merges here; results are
            bit-identical to the flush readout over the same rows.
  finish    the family's ordinary `snapshot_finish` transfer + host
            assembly, then host-side row matching (name + tag subset).

Consistency contract (pinned by tests/test_query.py): a query taken
between flushes returns values bit-identical to evaluating the same
readout kernels on the subsequent flush's captured generation
restricted to the same rows — the capture IS the generation the next
swap_out hands to the flush, absent further ingest on those rows.
Queries never touch the ledger (conservation is about samples, and a
query moves none) and never recycle device state.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from veneur_tpu.core.latency import LatencyHist
from veneur_tpu.ops import llhist_ref

logger = logging.getLogger("veneur_tpu.core.query")

# llhist series exported by the plane: query.eval renders
# .p50/.p99/.max gauges + .count counter (scripts/check_metric_names.py
# expands HIST_ROWS tuples against the README inventory)
HIST_ROWS = ("query.eval",)

# canonical kinds; "percentile" is accepted as an alias for "quantile"
QUERY_KINDS = ("quantile", "count", "rate", "cardinality", "value",
               "bin_occupancy")

# kind -> the families searched, in order (quantile falls through the
# t-digest family to llhist so `histogram_encoding: circllhist` stores
# answer transparently)
_KIND_FAMILIES = {
    "quantile": ("histogram", "llhist"),
    "count": ("counter",),
    "rate": ("counter",),
    "cardinality": ("set",),
    "value": ("gauge",),
    "bin_occupancy": ("llhist",),
}


class QueryError(ValueError):
    """A malformed or unanswerable query (surfaced as HTTP 400)."""


class ReshardRetry(QueryError):
    """A reshard cutover is swapping the serving topology under this
    capture — retry once it settles (surfaced as HTTP 503 + retry:
    true, never a shape error). Subclasses QueryError so existing
    catch-alls (the alert engine's tick guard) stay safe."""


def parse_tags(raw: Optional[str]) -> Tuple[str, ...]:
    """'env:prod,region:us' -> a sorted tag tuple (empty for None)."""
    if not raw:
        return ()
    return tuple(sorted(t.strip() for t in raw.split(",") if t.strip()))


@dataclass(frozen=True)
class QuerySpec:
    """One validated query: metric name, kind, and kind parameters."""

    metric: str
    kind: str
    q: Optional[float] = None
    tags: Tuple[str, ...] = ()
    lo: Optional[float] = None
    hi: Optional[float] = None

    @classmethod
    def build(cls, metric: str, kind: str, q=None, tags=(),
              lo=None, hi=None) -> "QuerySpec":
        if not metric:
            raise QueryError("metric is required")
        kind = {"percentile": "quantile"}.get(kind or "", kind)
        if kind not in _KIND_FAMILIES:
            raise QueryError(
                f"unknown kind {kind!r} (expected one of {QUERY_KINDS})")
        if kind == "quantile":
            if q is None:
                raise QueryError("quantile queries require q=")
            # 4-decimal rounding bounds the jit trace cache: the packed
            # flush kernels take the percentile tuple as a STATIC arg,
            # so every distinct q is one compile
            q = round(float(q), 4)
            if not 0.0 <= q <= 1.0:
                raise QueryError(f"q must be in [0, 1], got {q}")
        else:
            q = None
        if kind == "bin_occupancy":
            if lo is None or hi is None:
                raise QueryError("bin_occupancy queries require lo= and hi=")
            lo, hi = float(lo), float(hi)
            if not hi > lo:
                raise QueryError(f"need hi > lo, got [{lo}, {hi})")
        else:
            lo = hi = None
        return cls(metric=metric, kind=kind, q=q,
                   tags=tuple(sorted(tags or ())), lo=lo, hi=hi)


def match_rows(meta: Sequence, touched: np.ndarray, name: str,
               tags: Tuple[str, ...]) -> List[int]:
    """Touched rows whose meta matches `name` and carries every
    requested tag (subset match, the standard dashboard filter)."""
    want = set(tags)
    rows: List[int] = []
    for row, rm in enumerate(meta):
        if rm is None or rm.name != name:
            continue
        if want and not want.issubset(rm.tags or ()):
            continue
        if row < touched.shape[0] and touched[row]:
            rows.append(row)
    return rows


class LiveQueryPlane:
    """The server's live read surface: consistent read-only captures of
    the device families, evaluated with the flush readout kernels, on
    demand. One instance per server; thread-safe (captures serialize on
    the table locks, readouts on the shared flush executor)."""

    def __init__(self, server, timeout_s: float = 30.0):
        self._server = server
        self._timeout_s = timeout_s
        # monotonic counters (GIL point increments, scrape reads race-
        # free enough — a torn read is one scrape stale, never corrupt)
        self.queries_total = 0
        self.errors_total = 0
        self._eval_hist = LatencyHist("query.eval")

    # -- capture ---------------------------------------------------------

    def _tables(self) -> Dict[str, object]:
        store = self._server.store
        return {"counter": store.counters, "gauge": store.gauges,
                "histogram": store.histos, "llhist": store.llhists,
                "set": store.sets}

    def capture(self, families: Sequence[str], ps: Tuple[float, ...] = (),
                need_bins: bool = False) -> dict:
        """One consistent read-only snapshot per requested family,
        readout dispatched through the server's supervised flush
        executor, finished into host arrays. Returns
        {family: {values/flush/..., touched, meta, stale_pending}}."""
        if self._server._shutdown.is_set():
            raise QueryError("server is shutting down")
        reshard = getattr(self._server, "reshard", None)
        if reshard is not None and reshard.state == "cutover":
            # the topology swap is in flight: captures taken now could
            # straddle generations (family A on the new plane, family B
            # still on the old) — typed retry, never a shape error
            raise ReshardRetry("reshard cutover in progress")
        tables = self._tables()
        bundle: dict = {"as_of_unix": time.time()}
        epochs = set()
        for family in families:
            table = tables[family]
            if family == "histogram":
                snap = table.capture_readonly(ps=ps, need_export=False)
            elif family == "llhist":
                snap = table.capture_readonly(ps=ps, need_bins=need_bins)
            else:
                snap = table.capture_readonly()
            epoch = snap.get("topo_epoch")
            if epoch is not None:
                epochs.add(epoch)
            fut = self._server._readout_executor().submit(
                lambda t=table, s=snap: t.query_readout(s))
            snap = fut.result(timeout=self._timeout_s)
            bundle[family] = self._finish(family, table, snap)
        if len(epochs) > 1 or (reshard is not None
                               and reshard.state == "cutover"):
            # a cutover began mid-capture: the bundle mixes topology
            # generations (sharded captures stamp their table's
            # topo_epoch) — retry against the settled plane
            raise ReshardRetry("reshard cutover landed mid-capture")
        return bundle

    @staticmethod
    def _finish(family: str, table, snap: dict) -> dict:
        stale = int(snap.get("stale_pending", 0))
        if family in ("counter", "gauge"):
            values, touched, meta = table.snapshot_finish(snap)
            fam = {"values": values}
        elif family == "histogram":
            flush, _export, touched, meta = table.snapshot_finish(snap)
            fam = {"flush": flush}
        elif family == "llhist":
            flush, bins, touched, meta = table.snapshot_finish(snap)
            fam = {"flush": flush, "bins": bins}
        elif family == "set":
            estimates, _regs, touched, meta = table.snapshot_finish(snap)
            fam = {"values": estimates}
        else:  # pragma: no cover - guarded by _KIND_FAMILIES
            raise QueryError(f"unqueryable family {family!r}")
        fam.update(touched=touched, meta=meta, stale_pending=stale)
        return fam

    # -- evaluation (pure host work over a finished bundle) --------------

    def evaluate(self, bundle: dict, spec: QuerySpec,
                 ps: Tuple[float, ...] = ()) -> dict:
        """Evaluate one spec against a capture bundle. Usable for many
        specs over ONE bundle (the alert engine's path)."""
        matched_family = None
        rows: List[int] = []
        fam: Optional[dict] = None
        for family in _KIND_FAMILIES[spec.kind]:
            fam = bundle.get(family)
            if fam is None:
                continue
            rows = match_rows(fam["meta"], fam["touched"], spec.metric,
                              spec.tags)
            matched_family = family
            if rows:
                break
        out_rows, agg = (self._values_for(matched_family, fam, rows,
                                          spec, ps)
                         if rows else ([], None))
        result = {
            "metric": spec.metric,
            "kind": spec.kind,
            "family": matched_family,
            "matched_rows": len(rows),
            "rows": out_rows,
            "value": agg,
            "as_of_unix": round(bundle["as_of_unix"], 3),
            "stale_pending_samples": int(fam["stale_pending"]) if fam
            else 0,
        }
        if spec.kind == "quantile":
            result["q"] = spec.q
        if spec.kind == "bin_occupancy":
            result["lo"], result["hi"] = spec.lo, spec.hi
        if spec.tags:
            result["tags"] = list(spec.tags)
        return result

    def _values_for(self, family: str, fam: dict, rows: List[int],
                    spec: QuerySpec, ps: Tuple[float, ...]):
        out: List[dict] = []

        def row_entry(row: int, value: float) -> dict:
            rm = fam["meta"][row]
            return {"tags": list(rm.tags or ()), "value": value}

        if spec.kind in ("count", "rate"):
            values = fam["values"]
            elapsed = max(
                time.time() - self._server._interval_start_unix, 1e-9)
            for row in rows:
                v = float(values[row])
                if spec.kind == "rate":
                    v = v / elapsed
                out.append(row_entry(row, v))
            return out, float(sum(e["value"] for e in out))

        if spec.kind in ("value", "cardinality"):
            values = fam["values"]
            for row in rows:
                out.append(row_entry(row, float(values[row])))
            if spec.kind == "cardinality":
                # per-series estimates sum (series are distinct keys;
                # their member streams are reported per tag-set)
                return out, float(sum(e["value"] for e in out))
            return out, max(e["value"] for e in out)

        if spec.kind == "quantile":
            flush = fam["flush"]
            quant = flush.get("quantiles")
            if quant is None or spec.q not in ps:  # idle llhist capture
                return [], None
            qi = ps.index(spec.q)
            for row in rows:
                out.append(row_entry(row, float(quant[row, qi])))
            finite = [e["value"] for e in out
                      if not np.isnan(e["value"])]
            return out, (max(finite) if finite else None)

        if spec.kind == "bin_occupancy":
            bins = fam.get("bins")
            if bins is None or not bins.shape[0]:
                return [], None
            tpos = {int(r): i for i, r in
                    enumerate(np.flatnonzero(fam["touched"]))}
            mids = llhist_ref.BIN_MID
            mask = (mids >= spec.lo) & (mids < spec.hi)
            in_total = 0.0
            all_total = 0.0
            for row in rows:
                i = tpos.get(row)
                if i is None:
                    continue
                total = float(bins[i].sum())
                in_range = float(bins[i][mask].sum())
                frac = in_range / total if total > 0 else 0.0
                out.append(row_entry(row, frac))
                in_total += in_range
                all_total += total
            agg = in_total / all_total if all_total > 0 else 0.0
            return out, agg

        raise QueryError(f"unknown kind {spec.kind!r}")

    # -- the one-shot path (/query) --------------------------------------

    def ps_for(self, specs: Sequence[QuerySpec]) -> Tuple[float, ...]:
        """The percentile tuple one capture dispatches for a set of
        specs: the server's configured percentiles when they cover every
        requested q (the flush kernels are then textually identical to
        the flush's — the bit-identity pin), extended otherwise."""
        server_ps = tuple(self._server.config.percentiles)
        want = {s.q for s in specs if s.kind == "quantile"}
        if want <= set(server_ps):
            return server_ps
        return tuple(sorted(set(server_ps) | want))

    def query(self, spec: QuerySpec) -> dict:
        t0 = time.perf_counter()
        self.queries_total += 1
        try:
            ps = self.ps_for((spec,))
            bundle = self.capture(
                _KIND_FAMILIES[spec.kind], ps=ps,
                need_bins=(spec.kind == "bin_occupancy"))
            result = self.evaluate(bundle, spec, ps)
        except Exception:
            self.errors_total += 1
            raise
        result["eval_s"] = round(time.perf_counter() - t0, 6)
        self._eval_hist.observe(result["eval_s"])
        return result

    # -- export ----------------------------------------------------------

    def telemetry_rows(self) -> List[tuple]:
        rows: List[tuple] = [
            ("query.requests_total", "counter",
             float(self.queries_total), ()),
            ("query.errors_total", "counter",
             float(self.errors_total), ()),
        ]
        snap = self._eval_hist.snapshot()
        for label in ("p50", "p99", "max"):
            rows.append((f"query.eval.{label}", "gauge", snap[label], ()))
        rows.append(("query.eval.count", "counter",
                     float(snap["count"]), ()))
        return rows


class ProxyQueryView:
    """The proxy-side aggregate query surface. A proxy holds no column
    store — its queryable state is the per-destination routing plane:
    forwarded-key HLL cardinalities, queue depths, and forward volume.
    `GET /query` on a proxy therefore serves aggregate views
    (kind=cardinality over forwarded key digests, kind=count over
    forwarded metrics) rather than per-metric values."""

    def __init__(self, proxy):
        self._proxy = proxy
        self._started_unix = time.time()
        self.queries_total = 0
        self.errors_total = 0

    def query(self, spec: QuerySpec) -> dict:
        self.queries_total += 1
        if spec.kind not in ("cardinality", "count", "rate"):
            self.errors_total += 1
            raise QueryError(
                "a proxy serves aggregate views only: kind must be "
                "cardinality, count, or rate")
        try:
            report = self._proxy.cardinality_report(top=4096)
        except Exception:
            self.errors_total += 1
            raise
        rows = []
        total = 0.0
        for entry in report.get("destinations", ()):
            if spec.kind == "cardinality":
                v = float(entry.get("forwarded_keys_estimate", 0))
            else:
                v = float(entry.get("sent_total", 0))
            rows.append({"tags": [f"destination:{entry.get('address')}"],
                         "value": v})
            total += v
        if spec.kind == "rate":
            # cumulative counters over the proxy's lifetime -> mean rate
            # since this view came up alongside the proxy
            elapsed = max(time.time() - self._started_unix, 1e-9)
            for e in rows:
                e["value"] = e["value"] / elapsed
            total = sum(e["value"] for e in rows)
        return {
            "metric": spec.metric or "forward.keys",
            "kind": spec.kind,
            "family": "proxy",
            "matched_rows": len(rows),
            "rows": rows,
            "value": total,
            "as_of_unix": round(time.time(), 3),
        }

    def telemetry_rows(self) -> List[tuple]:
        return [
            ("query.requests_total", "counter",
             float(self.queries_total), ()),
            ("query.errors_total", "counter",
             float(self.errors_total), ()),
        ]
