"""gRPC ingest plane: DogStatsD packets and SSF spans over gRPC.

Parity with the reference's GrpcMetricsSource (reference
networking.go:325-352 StartGRPC / SendPacket / SendSpan, service
definitions protocol/dogstatsd/grpc.proto and ssf/grpc.proto): one gRPC
server per `grpc_listen_addresses` entry exposing

  dogstatsd.DogstatsdGRPC/SendPacket  (DogstatsdPacket{packetBytes})
  ssf.SSFGRPC/SendSpan                (ssf.SSFSpan)

Packets re-enter the normal parse path (native batch parser included);
spans go straight onto the span channel.
"""

from __future__ import annotations

import logging
from concurrent import futures
from typing import Optional

import grpc

from veneur_tpu.core.protos import dogstatsd_pb2
from veneur_tpu.ssf.protos import ssf_pb2

logger = logging.getLogger("veneur_tpu.grpc_ingest")

_EMPTY = dogstatsd_pb2.Empty()


class GrpcIngestServer:
    """Serves both ingest services on one port (like the reference, which
    registers both on the same grpc.Server)."""

    def __init__(self, server, address: str = "127.0.0.1:0",
                 max_workers: int = 4):
        self._server = server
        self._grpc = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers))
        dogstatsd = grpc.method_handlers_generic_handler(
            "dogstatsd.DogstatsdGRPC", {
                "SendPacket": grpc.unary_unary_rpc_method_handler(
                    self._send_packet,
                    request_deserializer=(
                        dogstatsd_pb2.DogstatsdPacket.FromString),
                    response_serializer=(
                        dogstatsd_pb2.Empty.SerializeToString)),
            })
        ssf_svc = grpc.method_handlers_generic_handler(
            "ssf.SSFGRPC", {
                "SendSpan": grpc.unary_unary_rpc_method_handler(
                    self._send_span,
                    request_deserializer=ssf_pb2.SSFSpan.FromString,
                    response_serializer=(
                        dogstatsd_pb2.Empty.SerializeToString)),
            })
        self._grpc.add_generic_rpc_handlers((dogstatsd, ssf_svc))
        self._host = address.rsplit(":", 1)[0] or "127.0.0.1"
        self.port = self._grpc.add_insecure_port(address)
        if self.port == 0:
            raise RuntimeError(f"could not bind gRPC ingest to {address}")

    @property
    def address(self) -> str:
        # a wildcard bind is reachable over loopback; report it that way
        host = "127.0.0.1" if self._host in ("0.0.0.0", "[::]", "::") \
            else self._host
        return f"{host}:{self.port}"

    def start(self) -> None:
        self._grpc.start()
        logger.info("listening for gRPC dogstatsd/SSF on %s", self.address)

    def stop(self, grace: Optional[float] = 0.5) -> None:
        self._grpc.stop(grace)

    # -- handlers ---------------------------------------------------------

    def _send_packet(self, request, context):
        self._server.handle_packet_batch([request.packetBytes])
        return _EMPTY

    def _send_span(self, request, context):
        self._server.stats.inc("packets_received")
        self._server.ingest_span(request)
        return _EMPTY
