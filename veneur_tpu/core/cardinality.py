"""Cardinality observatory: per-name series accounting + shed rung.

veneur's whole job is surviving other people's metrics, and the thing
that kills a metrics aggregator is a cardinality explosion: one bad tag
mints unbounded keys. On this TPU port the cost is worse than host
memory — every column-store capacity doubling is a jit recompile plus
permanent HBM growth. This module makes series cardinality itself
observable and actionable, reusing the paper's own sketch machinery:

- `SpaceSaving`: a bounded-memory heavy-hitter tracker (space-saving,
  with SALSA-style self-adjusting decay at each flush) keyed by metric
  NAME. Fed from the column store's interning path — mints are already
  the slow path, so the hot columnar ingest never pays for it.
  Per-name records carry live-row counts (exact while tracked: mints
  increment, idle-evictions decrement), interval mint counts, and shed
  counts.
- `TagCardinality`: for the current top offenders, per-tag-key
  HyperLogLog distinct-value estimates (ops/hll_ref, p=14), so an
  operator sees WHICH tag is exploding, not just which name. Fed on
  mint attempts — including rejected ones, which is exactly when you
  need the diagnosis.
- the **cardinality watermark rung** of the overload ladder: past
  `cardinality_soft_limit` new-key mints per name per interval, further
  mints for that name are admitted deterministically 1-in-N
  (`cardinality_degraded_keep`); past `cardinality_hard_limit` they are
  rejected outright. Existing rows always keep updating — only NEW keys
  are gated, so pre-existing series never lose a sample. Every shed
  mint is accounted through the server's `ingest.shed_total` path with
  `reason:cardinality` / `reason:cardinality_degraded`. Budgets reset
  at every flush (`roll_interval`), so recovery after a storm is
  immediate: within one interval of the storm stopping, new keys mint
  again.

Everything is thread-safe and allocation-bounded: the tracker holds at
most `top_k` records, tag tracking at most `hll_names` names x
`MAX_TAG_KEYS` HLLs (16 KB each).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from veneur_tpu.ops import hll_ref

logger = logging.getLogger("veneur_tpu.cardinality")

# hard bound on distinct tag KEYS tracked per offending name; a name
# whose samples carry more distinct tag keys than this overflows into
# `tag_keys_overflow` (counted, never allocated)
MAX_TAG_KEYS = 16

# a tag-tracked name idle (no mint attempts) for this many intervals
# releases its HLL slot to the next offender
TAG_IDLE_INTERVALS = 5


class NameRecord:
    """One tracked metric name's accounting. Mutated only under the
    owning accountant's lock."""

    __slots__ = ("name", "weight", "error", "mints_total",
                 "mints_interval", "mints_last_interval", "live_rows",
                 "families", "shed_total", "shed_interval",
                 "first_seen_unix")

    def __init__(self, name: str, error: float = 0.0):
        self.name = name
        # decayed mint score: the space-saving ordering key. `error` is
        # the classic space-saving overestimate bound inherited from the
        # evicted record this one replaced.
        self.weight = 0.0
        self.error = error
        self.mints_total = 0
        self.mints_interval = 0
        self.mints_last_interval = 0
        self.live_rows = 0
        self.families: Dict[str, int] = {}
        self.shed_total = 0
        self.shed_interval = 0
        self.first_seen_unix = time.time()

    def as_dict(self, interval_s: float) -> dict:
        rate = (self.mints_last_interval / interval_s
                if interval_s > 0 else 0.0)
        return {
            "name": self.name,
            "live_rows": self.live_rows,
            "families": dict(self.families),
            "mints_total": self.mints_total,
            "mints_interval": self.mints_interval,
            "mints_last_interval": self.mints_last_interval,
            "mint_rate_per_s": round(rate, 3),
            "shed_total": self.shed_total,
            "weight": round(self.weight, 3),
            "weight_error": round(self.error, 3),
            "first_seen_unix": round(self.first_seen_unix, 3),
        }


class SpaceSaving:
    """Space-saving heavy hitters over metric names, bounded at
    `capacity` records. Not thread-safe on its own — the accountant
    serializes access.

    Eviction is amortized: hitting capacity purges the lowest-scored
    quarter in one O(K log K) pass (score = weight + live rows — a name
    that still owns live rows stays resident even when its mint stream
    went quiet), so a unique-name flood costs O(log K) per mint instead
    of an O(K) min-scan each. Records minted after a purge inherit the
    highest purged score as their error bound — the space-saving
    guarantee, batched: a name minting more than any purged record can
    never be silently lost, and `error` bounds how much of its count
    predates tracking."""

    def __init__(self, capacity: int = 512):
        self.capacity = max(8, int(capacity))
        self.records: Dict[str, NameRecord] = {}
        self.evictions = 0
        self._pending_error = 0.0  # max score purged in the last sweep

    @staticmethod
    def _score(rec: NameRecord) -> float:
        return rec.weight + float(rec.live_rows)

    def get_or_track(self, name: str) -> NameRecord:
        rec = self.records.get(name)
        if rec is None:
            if len(self.records) >= self.capacity:
                ranked = sorted(self.records.values(), key=self._score)
                purge = ranked[:max(1, self.capacity // 4)]
                for victim in purge:
                    del self.records[victim.name]
                self.evictions += len(purge)
                # takeover inherits only the purged WEIGHT (the mint
                # count being bounded), never the live-row gauge — or a
                # brand-new name minted after purging a row-heavy victim
                # would instantly fake a top-offender score
                self._pending_error = max(v.weight for v in purge)
            rec = NameRecord(name, error=self._pending_error)
            rec.weight = self._pending_error  # space-saving takeover
            self.records[name] = rec
        return rec

    def decay(self, factor: float) -> None:
        """SALSA-style self-adjustment, run once per interval: old mint
        activity fades so the tracker follows the CURRENT storm, and
        rows with no weight and no live rows release their slots."""
        drop = [name for name, rec in self.records.items()
                if rec.weight * factor < 0.5 and rec.live_rows <= 0]
        for name in drop:
            del self.records[name]
        for rec in self.records.values():
            rec.weight *= factor
            rec.error *= factor
        self._pending_error *= factor

    def top(self, n: int) -> List[NameRecord]:
        return sorted(self.records.values(), key=self._score,
                      reverse=True)[:max(0, n)]


class TagCardinality:
    """Per-tag-key HLL distinct-value estimates for a bounded set of
    offender names. 16 KB per (name, tag key); bounded at
    `max_names` x MAX_TAG_KEYS."""

    def __init__(self, max_names: int = 8):
        self.max_names = max(0, int(max_names))
        # name -> {tag_key: HLL}
        self._hlls: Dict[str, Dict[str, hll_ref.HLL]] = {}
        self._overflow: Dict[str, int] = {}  # name -> tag keys not tracked
        self._idle: Dict[str, int] = {}      # name -> idle interval count
        self._since: Dict[str, float] = {}   # name -> tracking start unix

    def tracking(self, name: str) -> bool:
        return name in self._hlls

    def can_track(self) -> bool:
        return len(self._hlls) < self.max_names

    def start(self, name: str) -> None:
        if name not in self._hlls and self.can_track():
            self._hlls[name] = {}
            self._overflow[name] = 0
            self._idle[name] = 0
            self._since[name] = time.time()
            logger.info("cardinality: tag tracking started for %r", name)

    def observe(self, name: str, tags: Sequence[str]) -> None:
        per_key = self._hlls.get(name)
        if per_key is None:
            return
        self._idle[name] = 0
        for tag in tags:
            key, sep, value = tag.partition(":")
            if not sep:
                key, value = tag, ""
            hll = per_key.get(key)
            if hll is None:
                if len(per_key) >= MAX_TAG_KEYS:
                    self._overflow[name] += 1
                    continue
                hll = per_key[key] = hll_ref.HLL()
            hll.insert(value.encode())

    def roll_interval(self) -> None:
        """Release slots held by names whose storm has been quiet for
        TAG_IDLE_INTERVALS intervals."""
        for name in list(self._hlls):
            self._idle[name] = self._idle.get(name, 0) + 1
            if self._idle[name] > TAG_IDLE_INTERVALS:
                del self._hlls[name]
                self._overflow.pop(name, None)
                self._idle.pop(name, None)
                self._since.pop(name, None)
                logger.info(
                    "cardinality: tag tracking released for %r (idle)",
                    name)

    def report(self, name: str) -> Optional[dict]:
        per_key = self._hlls.get(name)
        if per_key is None:
            return None
        return {
            "since_unix": round(self._since.get(name, 0.0), 3),
            "tag_keys": {k: int(h.estimate())
                         for k, h in sorted(per_key.items())},
            "tag_keys_overflow": self._overflow.get(name, 0),
        }

    def tracked_names(self) -> List[str]:
        return sorted(self._hlls)


class CardinalityAccountant:
    """The server's cardinality posture: the heavy-hitter tracker, tag
    HLLs, per-name mint budgets (the shed rung), and the telemetry
    collector that exports all of it.

    Hot-path contract: `admit_mint` / `note_mint` / `note_evicted` are
    called from the column-store interning and reclaim paths (under the
    table's buffer lock). They take only this accountant's own lock and
    never call back into store or telemetry locks — dict increments plus,
    for the few tracked offenders, HLL register updates."""

    DECAY = 0.8  # per-interval weight decay (SALSA self-adjustment)

    def __init__(self, soft_limit: int = 0, hard_limit: int = 0,
                 degraded_keep: float = 0.1, top_k: int = 512,
                 hll_names: int = 8, hll_min_mints: int = 64,
                 on_shed: Optional[Callable[[str, int, str], None]] = None,
                 on_event: Optional[Callable[..., None]] = None):
        self.soft_limit = max(0, int(soft_limit))
        self.hard_limit = max(0, int(hard_limit))
        self.degraded_keep = min(1.0, max(0.0, float(degraded_keep)))
        self._keep_every = (max(1, round(1.0 / self.degraded_keep))
                            if self.degraded_keep > 0 else 0)
        self.hll_min_mints = max(1, int(hll_min_mints))
        # on_shed(family_class, n, reason): the server wires this to
        # OverloadManager.shed so rejected mints land in
        # ingest.shed_total{reason:cardinality} like every other shed
        self.on_shed = on_shed
        # on_event(kind, **fields): flight-recorder hook for limit edges
        self.on_event = on_event
        self._lock = threading.Lock()
        self.tracker = SpaceSaving(top_k)
        self.tags = TagCardinality(hll_names)
        self.minted_total = 0
        self.shed_hard_total = 0
        self.shed_soft_total = 0
        self.interval_s = 0.0  # measured flush-to-flush, for rates
        self._last_roll = time.monotonic()
        # names currently over a limit (for /debug/cardinality and the
        # one-edge-per-interval event dedup)
        self._over_soft: Dict[str, bool] = {}
        self._over_hard: Dict[str, bool] = {}

    @property
    def enabled(self) -> bool:
        return self.soft_limit > 0 or self.hard_limit > 0

    # -- hot path (column-store interning) -------------------------------

    def admit_mint(self, family: str, name: str,
                   tags: Sequence[str]) -> bool:
        """One new-key mint ATTEMPT for `name`. Records the attempt
        (tracker weight + tag HLLs — rejected mints still feed the
        diagnosis; that is when the operator needs it), then applies the
        per-name interval budget. Returns False when the mint must be
        rejected; the caller drops the sample and this accountant has
        already counted the shed."""
        events = []
        with self._lock:
            rec = self.tracker.get_or_track(name)
            rec.weight += 1.0
            rec.mints_total += 1
            rec.mints_interval += 1
            mints = rec.mints_interval
            if (not self.tags.tracking(name)
                    and mints >= self.hll_min_mints
                    and self.tags.can_track()):
                self.tags.start(name)
            self.tags.observe(name, tags)
            verdict = True
            reason = ""
            if self.hard_limit and mints > self.hard_limit:
                verdict, reason = False, "cardinality"
                rec.shed_total += 1
                rec.shed_interval += 1
                self.shed_hard_total += 1
                if not self._over_hard.get(name):
                    self._over_hard[name] = True
                    events.append(("cardinality_hard_limit", name, mints))
            elif self.soft_limit and mints > self.soft_limit:
                if not self._over_soft.get(name):
                    self._over_soft[name] = True
                    events.append(("cardinality_soft_limit", name, mints))
                # deterministic keep-1-in-N past the soft watermark:
                # the key stream stays statistically visible while the
                # mint (and recompile/HBM) rate is cut
                keep = (self._keep_every
                        and (mints - self.soft_limit) % self._keep_every
                        == 0)
                if not keep:
                    verdict, reason = False, "cardinality_degraded"
                    rec.shed_total += 1
                    rec.shed_interval += 1
                    self.shed_soft_total += 1
        if not verdict and self.on_shed is not None:
            self.on_shed(family, 1, reason)
        for kind, nm, mints in events:
            logger.warning(
                "cardinality: %s crossed for %r (%d mints this interval)",
                kind, nm, mints)
            if self.on_event is not None:
                try:
                    self.on_event(kind, name=nm, family=family,
                                  mints_interval=mints)
                except Exception:
                    logger.exception("cardinality event hook failed")
        return verdict

    def note_mint(self, family: str, name: str) -> None:
        """A mint that actually allocated a row (admission and the
        max_rows cap both passed)."""
        with self._lock:
            self.minted_total += 1
            rec = self.tracker.records.get(name)
            if rec is not None:
                rec.live_rows += 1
                rec.families[family] = rec.families.get(family, 0) + 1

    def note_evicted(self, family: str, names: Sequence[str]) -> None:
        """Idle-reclaim tombstoned these rows; live counts shrink."""
        if not names:
            return
        with self._lock:
            for name in names:
                rec = self.tracker.records.get(name)
                if rec is not None and rec.live_rows > 0:
                    rec.live_rows -= 1
                    fams = rec.families
                    if fams.get(family, 0) > 1:
                        fams[family] -= 1
                    else:
                        fams.pop(family, None)

    # -- interval rollover (flush path) ----------------------------------

    def roll_interval(self) -> None:
        """Reset per-interval mint budgets (the shed rung's immediate
        recovery), decay the tracker, age out idle tag tracking. Called
        once per flush by the server."""
        now = time.monotonic()
        with self._lock:
            self.interval_s = max(1e-6, now - self._last_roll)
            self._last_roll = now
            # budgets reset -> every over-limit name recovers NOW; a
            # storm that continues re-crosses within the next interval
            # and emits a fresh limit event (one edge pair per interval
            # per name, bounded by the tracker capacity)
            recovered = sorted(set(self._over_hard) | set(self._over_soft))
            self._over_hard.clear()
            self._over_soft.clear()
            for rec in self.tracker.records.values():
                rec.mints_last_interval = rec.mints_interval
                rec.mints_interval = 0
                rec.shed_interval = 0
            self.tracker.decay(self.DECAY)
            self.tags.roll_interval()
        for name in recovered:
            if self.on_event is not None:
                try:
                    self.on_event("cardinality_recovered", name=name)
                except Exception:
                    logger.exception("cardinality event hook failed")

    # -- reads ------------------------------------------------------------

    def top(self, n: int) -> List[dict]:
        with self._lock:
            interval = self.interval_s
            return [rec.as_dict(interval) for rec in self.tracker.top(n)]

    def name_report(self, name: str) -> dict:
        with self._lock:
            rec = self.tracker.records.get(name)
            out = {"name": name,
                   "tracked": rec is not None}
            if rec is not None:
                out.update(rec.as_dict(self.interval_s))
            tag_report = self.tags.report(name)
            if tag_report is not None:
                out["tags"] = tag_report
            return out

    def tag_report(self, name: str) -> Optional[dict]:
        """Per-tag-key HLL estimates for `name`, or None if untracked."""
        with self._lock:
            return self.tags.report(name)

    def limits_report(self) -> dict:
        with self._lock:
            return {
                "soft_limit": self.soft_limit,
                "hard_limit": self.hard_limit,
                "degraded_keep": self.degraded_keep,
                "shed_soft_total": self.shed_soft_total,
                "shed_hard_total": self.shed_hard_total,
                "over_soft": sorted(self._over_soft),
                "over_hard": sorted(self._over_hard),
            }

    def telemetry_rows(self) -> List[tuple]:
        """(name, kind, value, tags) rows for the /metrics collector.
        Per-name rows are bounded to the top 5 offenders."""
        with self._lock:
            rows = [
                ("cardinality.names_tracked", "gauge",
                 float(len(self.tracker.records)), ()),
                ("cardinality.tracker_evictions", "counter",
                 float(self.tracker.evictions), ()),
                ("cardinality.mints_total", "counter",
                 float(self.minted_total), ()),
                ("cardinality.tag_tracked_names", "gauge",
                 float(len(self.tags.tracked_names())), ()),
            ]
            for rec in self.tracker.top(5):
                tags = [f"name:{rec.name}"]
                rows.append(("cardinality.top_name_live_rows", "gauge",
                             float(rec.live_rows), tags))
                rows.append(("cardinality.top_name_mints_interval",
                             "gauge", float(rec.mints_last_interval),
                             tags))
        return rows
