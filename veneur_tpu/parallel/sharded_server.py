"""The sharded serving plane: topology + accounting for the partitioned
column store.

One instance per server owns the device mesh the sharded tables
(core/sharded_tables.py) run on and the digest-home routing function
every family shares: a metric key's 64-bit fnv1a digest picks its home
shard once, at mint time, and every sample / import merge for that key
lands on that shard's slice of the partitioned state. The flush-time
merge is then a collective *selection* (parallel/collectives.py), which
is what keeps the llhist/HLL registers bit-identical to a single-device
table — the PR-5 exactness pin generalized to the mesh.

The plane is also the mesh's self-telemetry root: `mesh.*` rows
describe the topology, `shard.*` rows the per-shard routing volume, so
an operator can see a skewed key space (one hot shard) or a dead chip
(a shard's routed-sample counter flatlining) straight off /metrics.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional

import numpy as np

from veneur_tpu.parallel import collectives

logger = logging.getLogger("veneur_tpu.parallel.sharded_server")

ROUTING_DIGEST = "digest"
ROUTING_ROUNDROBIN = "roundrobin"


def local_shard_devices(n: int) -> List:
    """The n local devices to shard over; falls back to the virtual CPU
    devices when the default platform is smaller (validation
    topologies)."""
    import jax

    devices = jax.local_devices()
    if len(devices) < n:
        try:
            cpu = jax.devices("cpu")
            if len(cpu) >= n:
                logger.warning(
                    "shard_devices=%d > %d local devices; using the "
                    "virtual CPU mesh (validation only)", n, len(devices))
                devices = cpu
        except RuntimeError:
            pass
    if len(devices) < n:
        logger.warning("shard_devices=%d > %d available; clamping",
                       n, len(devices))
        n = len(devices)
    return list(devices[:n])


class ShardedServingPlane:
    """Mesh topology + per-shard routing accounting, shared by every
    sharded family table of one column store."""

    def __init__(self, devices: List, routing: str = ROUTING_DIGEST):
        if routing not in (ROUTING_DIGEST, ROUTING_ROUNDROBIN):
            raise ValueError(f"unknown shard routing {routing!r}")
        self.devices = list(devices)
        self.n = len(self.devices)
        self.routing = routing
        self.mesh = collectives.local_mesh(self.devices)
        # per-shard routed-sample counters, keyed by family. Writers
        # used to all sit under a table's apply lock; the overlapped
        # flush's background readout folds counts lock-free, so the
        # numpy read-modify-write adds now need their own leaf lock
        # (scrapes stay lock-free point reads — one row stale at worst)
        self._samples: Dict[str, np.ndarray] = {}
        self._acc_lock = threading.Lock()
        self.batches_dispatched = 0
        self.merge_rounds = 0

    # -- routing ---------------------------------------------------------

    def home(self, digest64: int) -> int:
        """One key's home shard: contiguous range partition of the
        64-bit digest space (top bits pick the shard, matching
        collectives.home_shards and the proxy ring's group split), so
        an N->M reshard migrates only the cells whose range boundary
        moved."""
        return ((int(digest64) & 0xFFFFFFFFFFFFFFFF) * self.n) >> 64

    def homes(self, digest64_arr) -> np.ndarray:
        return collectives.home_shards(digest64_arr, self.n)

    # -- accounting ------------------------------------------------------

    def note_routed(self, family: str, per_shard_counts) -> None:
        """Fold one dispatch's per-shard sample counts (len n array).
        Thread-safe: called from ingest (under table locks) AND from
        the background flush readout (lock-free by design)."""
        with self._acc_lock:
            acc = self._samples.get(family)
            if acc is None:
                acc = self._samples[family] = np.zeros(self.n, np.int64)
            acc += np.asarray(per_shard_counts, np.int64)
            self.batches_dispatched += 1

    def note_merge_round(self) -> None:
        with self._acc_lock:
            self.merge_rounds += 1

    # -- surfaces --------------------------------------------------------

    def describe(self) -> dict:
        """Topology summary for the startup flight-recorder event and
        /debug surfaces."""
        return {
            "shards": self.n,
            "routing": self.routing,
            "devices": [f"{d.platform}:{d.id}" for d in self.devices],
        }

    def telemetry_rows(self) -> List[tuple]:
        rows: List[tuple] = [
            ("mesh.shards", "gauge", float(self.n), ()),
            ("mesh.merge_rounds", "counter", float(self.merge_rounds), ()),
            ("mesh.batches_dispatched", "counter",
             float(self.batches_dispatched), ()),
        ]
        for family, acc in list(self._samples.items()):
            for shard, count in enumerate(acc.tolist()):
                rows.append(("shard.samples_routed", "counter",
                             float(count),
                             [f"family:{family}", f"shard:{shard}"]))
        return rows


def build_plane(shards: int, routing: str = ROUTING_DIGEST
                ) -> Optional[ShardedServingPlane]:
    """Plane for `shards` local devices; None when the topology can't
    shard (fewer than 2 devices) so callers fall back to single-device
    tables."""
    if not shards or shards <= 1:
        return None
    devices = local_shard_devices(shards)
    if len(devices) < 2:
        return None
    return ShardedServingPlane(devices, routing=routing)
