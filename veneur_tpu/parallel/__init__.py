"""Multi-device serving plane: mesh topology, collective interval
merges, and the dryrun shard_map path.

- `parallel.collectives` — jitted merge kernels + `Mesh`/`NamedSharding`
  plumbing the live sharded tables run on
- `parallel.sharded_server` — the ShardedServingPlane (topology,
  digest-home routing, `mesh.*`/`shard.*` telemetry)
- `parallel.mesh` — the shard_map dryrun/validation path

Submodules import jax lazily enough that the proxy tier (which never
aggregates) still avoids the TPU stack: only importing
`parallel.collectives`/`parallel.mesh` pulls jax in, so this package
__init__ stays import-light.
"""
