"""Collective interval merges for the sharded serving plane.

The reference scales by forwarding mergeable sketch state up a two-tier
gRPC tree (local veneurs -> global veneur, flusher.go:516-591,
worker.go:410-467). On a device mesh the same tree collapses into
collectives: every shard aggregates its own slice of the key space into
a partitioned column store, and the per-interval global merge is one
reduction over the shard axis — psum for counters, masked-sum for
gauges (each key has exactly one home shard), register max for HLL,
register ADD for llhist, concat+recompress for t-digest centroids.
This module owns the jitted merge kernels and the mesh/`NamedSharding`
plumbing the live sharded tables (core/sharded_tables.py) run on; the
dryrun-shaped shard_map path lives next door in parallel/mesh.py.

Every kernel here operates on *stacked* state: a leading shard axis of
size n, laid out with `NamedSharding(mesh, P(SHARD_AXIS))` so XLA SPMD
partitions the apply (pure data parallelism, no communication) and
lowers the flush-time reductions to ICI collectives.

Exactness contract (the PR-5 llhist pin, generalized to the mesh):
with digest-home routing every row's samples land on exactly one
shard, so the counter Kahan pairs, the gauge last-write-wins value,
the llhist int32 registers, and the HLL registers merge by *selection*
— summing n-1 zeros — and the merged result is bit-identical to a
single-device table that saw the same stream.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SHARD_AXIS = "shard"

# pending-buffer padding marker, shared with core/columnstore.py (kept
# numeric here to avoid a circular import; the scatter kernels drop any
# out-of-range row via mode="drop")
PAD_ROW = np.int32(2**31 - 1)


def local_mesh(devices: Sequence) -> Mesh:
    """A 1-D mesh over the given local devices, shard axis leading."""
    return Mesh(np.asarray(devices), (SHARD_AXIS,))


def shard_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-axis partitioning: (n, ...) split one shard per device."""
    return NamedSharding(mesh, P(SHARD_AXIS))


def home_shards(digest64, n_shards: int) -> np.ndarray:
    """Key digest(s) -> home shard id(s). Pure function of the 64-bit
    fnv1a key digest, so every tier (ingest routing, import merges, the
    proxy's shard groups) that derives a home from the same digest
    agrees without coordination.

    Contiguous range partition — home = (digest * n) >> 64, the same
    top-bits split the proxy's ShardGroupRing uses — so each shard owns
    ONE digest range and an N->M reshard migrates at most N+M-1
    contiguous cells instead of rehashing the whole key space (the
    modulo it replaced moved ~every key on any N change). Computed in
    32-bit halves to stay exact in uint64."""
    d = np.asarray(digest64, np.uint64)
    n = np.uint64(n_shards)
    hi = d >> np.uint64(32)
    lo = d & np.uint64(0xFFFFFFFF)
    return ((hi * n + ((lo * n) >> np.uint64(32)))
            >> np.uint64(32)).astype(np.int32)


def range_bounds(n_shards: int) -> List[int]:
    """The digest-space lower bound of every shard's range under
    home_shards: shard i owns [bounds[i], bounds[i+1]) with an implicit
    final bound of 2**64. bounds[i] is the smallest digest with
    home == i (ceil(i * 2**64 / n))."""
    return [(i << 64) // n_shards + (1 if (i << 64) % n_shards else 0)
            for i in range(n_shards)]


def stack_on_mesh(mesh: Mesh, leaves: List[jnp.ndarray]) -> jnp.ndarray:
    """Assemble per-device arrays (one per mesh device, already
    resident) into a single (n, ...) jax.Array sharded on the leading
    axis — no host round-trip, no device copy."""
    n = len(leaves)
    global_shape = (n,) + leaves[0].shape
    sharding = shard_sharding(mesh)
    expanded = [leaf[None] for leaf in leaves]  # dispatched on-device
    return jax.make_array_from_single_device_arrays(
        global_shape, sharding, [x for x in expanded])


def init_stacked(mesh: Mesh, leaf_fn, num_keys: int):
    """Stacked per-shard state: `leaf_fn(num_keys)` broadcast to a
    leading shard axis and laid out across the mesh."""
    n = mesh.devices.size
    sharding = shard_sharding(mesh)

    def mk(leaf):
        return jax.device_put(
            jnp.broadcast_to(leaf[None], (n,) + leaf.shape), sharding)

    return jax.tree.map(mk, leaf_fn(num_keys))


def grow_stacked(mesh: Mesh, state, new_cap: int):
    """Pad the key axis (axis 1) of every stacked leaf to `new_cap`,
    keeping the shard-axis layout."""
    sharding = shard_sharding(mesh)

    def grow(leaf):
        pad = new_cap - leaf.shape[1]
        widths = [(0, 0), (0, pad)] + [(0, 0)] * (leaf.ndim - 2)
        return jax.device_put(jnp.pad(leaf, widths), sharding)

    return jax.tree.map(grow, state)


def mask_batch_for_shards(home: np.ndarray, n: int,
                          rows: np.ndarray) -> np.ndarray:
    """(batch,) interned rows + their home shard ids -> (n, batch) rows
    where shard i keeps only its own rows (everything else PAD_ROW, and
    therefore dropped by the scatter kernels). The stacked batch keeps
    the kernels' compiled shapes fixed — a variable-length split per
    shard would retrace on every dispatch — and under SPMD each device
    scatters only its slice, so the mask costs bandwidth, not a
    recompile."""
    mask = home[None, :] == np.arange(n, dtype=np.int32)[:, None]
    return np.where(mask, rows[None, :], PAD_ROW)


def tile_batch(n: int, col: np.ndarray) -> np.ndarray:
    """Value columns ride to every shard unchanged ((n, batch) tiles);
    the masked row column is what gates which shard applies them."""
    return np.broadcast_to(col, (n,) + col.shape)


# -- sharded apply kernels (vmap over the shard axis; SPMD partitions
# them into per-device scatters with zero communication) ---------------

@partial(jax.jit, donate_argnums=0)
def apply_counters_sharded(state, rows, values, rates):
    return jax.vmap(_counters_body)(state, rows, values, rates)


def _counters_body(state, rows, values, rates):
    # mirrors ops/scalars.apply_counters (Kahan-compensated scatter-add)
    # with the shard axis vmapped over it
    num_keys = state["sum"].shape[0]
    contrib = jnp.trunc(values / rates)
    part = jnp.zeros((num_keys,), jnp.float32).at[rows].add(
        contrib, mode="drop")
    y = part - state["comp"]
    t = state["sum"] + y
    comp = (t - state["sum"]) - y
    return {"sum": t, "comp": comp}


def _gauges_body(state, rows, values):
    num_keys = state["value"].shape[0]
    order = jnp.arange(rows.shape[0], dtype=jnp.int32)
    last = jnp.full((num_keys,), -1, jnp.int32).at[rows].max(
        order, mode="drop")
    touched = last >= 0
    picked = values[jnp.clip(last, 0)]
    return {
        "value": jnp.where(touched, picked, state["value"]),
        "set": state["set"] | touched,
    }


@partial(jax.jit, donate_argnums=0)
def apply_gauges_sharded(state, rows, values):
    return jax.vmap(_gauges_body)(state, rows, values)


# import-path gauge merge: same LWW body, same masked-batch shape (the
# import path routes each stub to its home shard's batch row) — an
# alias, so the kernel compiles once for both call sites
merge_gauges_sharded = apply_gauges_sharded


@partial(jax.jit, donate_argnums=0)
def apply_llhist_sharded(regs, rows, bin_idx, weight):
    """(n, K, BINS_PAD) int32 stacked registers += masked batch."""
    def body(r, rw, bi, w):
        return r.at[rw, bi].add(w, mode="drop")
    return jax.vmap(body)(regs, rows, bin_idx, weight)


@partial(jax.jit, donate_argnums=0)
def merge_llhist_rows_at(regs, shard_ids, rows, in_rows):
    """Import-path whole-row register ADD over stacked state: incoming
    row i lands at (shard_ids[i], rows[i]). Indexed scatter rather than
    a masked tile — import batches are variable-length and each row
    carries ~BINS_PAD*4 bytes, so tiling them n-fold would swamp the
    link for nothing."""
    return regs.at[shard_ids, rows].add(in_rows, mode="drop")


# -- collective interval merges ----------------------------------------
#
# Two shapes per family: the read-only merge (kept for parity tests and
# any caller that wants the stacked state to survive), and the fused
# donated merge+reset the flush readout runs — `donate_argnums=0` lets
# XLA alias the drained interval's buffers for the returned fresh
# generation, so the double-buffered flush never allocates per interval
# and the merged readout leaves the swapped-out state's HBM in place.


def _zeros_tree(state):
    return jax.tree.map(jnp.zeros_like, state)


@partial(jax.jit, donate_argnums=0)
def merge_counters_stacked_reset(state):
    """Fused donated interval merge: (merged Kahan pair, fresh zeroed
    stacked generation aliasing the donated input)."""
    merged = (jnp.sum(state["sum"], axis=0), jnp.sum(state["comp"], axis=0))
    return merged, _zeros_tree(state)


@partial(jax.jit, donate_argnums=0)
def merge_gauges_stacked_reset(state):
    """Fused donated LWW merge: ((value, set), fresh generation)."""
    value = jnp.sum(jnp.where(state["set"], state["value"], 0.0), axis=0)
    return (value, jnp.any(state["set"], axis=0)), _zeros_tree(state)


@partial(jax.jit, donate_argnums=0)
def merge_llhist_stacked_reset(stacked: jnp.ndarray):
    """Fused donated register-ADD merge: ((K, BINS_PAD) merged
    registers, fresh stacked generation)."""
    return jnp.sum(stacked, axis=0), _zeros_tree(stacked)

@jax.jit
def merge_counters_stacked(state) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(n, K) Kahan pairs -> one (K,) pair. With digest-home routing
    exactly one shard holds nonzero state per row, so the sum is pure
    selection and the pair stays exact; the host readout recovers the
    exact total in f64 exactly like the single-device path."""
    return (jnp.sum(state["sum"], axis=0), jnp.sum(state["comp"], axis=0))


@jax.jit
def merge_gauges_stacked(state) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(n, K) LWW values + set masks -> merged (value, set). Each row
    has one home shard, so `where(set, value, 0)` summed over shards IS
    the home shard's last write."""
    value = jnp.sum(jnp.where(state["set"], state["value"], 0.0), axis=0)
    return value, jnp.any(state["set"], axis=0)


@jax.jit
def merge_llhist_stacked(stacked: jnp.ndarray) -> jnp.ndarray:
    """(n, K, BINS_PAD) int32 -> (K, BINS_PAD): register ADD, the exact
    merge the family exists for (associative + commutative integer
    addition — bit-identical to any other shard assignment)."""
    return jnp.sum(stacked, axis=0)


@jax.jit
def merge_hll_stacked(stacked: jnp.ndarray) -> jnp.ndarray:
    """(n, K, M) int8 -> (K, M) register max (all-reduce-max on SPMD)."""
    return jnp.max(stacked, axis=0)


@jax.jit
def merge_histo_stacked(stacked: Dict[str, jnp.ndarray]
                        ) -> Dict[str, jnp.ndarray]:
    """Per-shard t-digest states stacked on axis 0 -> one merged state.
    Concatenate every shard's centroids per key and recompress once as
    a batched kernel (the global veneur's re-insertion, reference
    worker.go:455-457); scalar stats reduce with sum/min/max. With
    digest-home routing only one shard holds centroids per key, so the
    recompress degenerates to a self-compact of the home shard's grid."""
    from veneur_tpu.ops import batch_tdigest

    w = stacked["weights"]                      # (n, K, C)
    m = jnp.where(w > 0, stacked["wv"] / jnp.maximum(w, 1e-30), 0.0)
    sw = stacked["sweights"]                    # staged-but-uncompacted
    sm = jnp.where(sw > 0, stacked["swv"] / jnp.maximum(sw, 1e-30), 0.0)
    n, num_keys, c = w.shape
    cat_m = jnp.concatenate([m, sm], axis=-1)   # (n, K, 2C)
    cat_w = jnp.concatenate([w, sw], axis=-1)
    cat_m = jnp.moveaxis(cat_m, 0, 1).reshape(num_keys, n * 2 * c)
    cat_w = jnp.moveaxis(cat_w, 0, 1).reshape(num_keys, n * 2 * c)
    new_m, new_w = batch_tdigest._recompress(cat_m, cat_w, num_keys)
    return {
        "wv": new_m * new_w,
        "weights": new_w,
        "swv": jnp.zeros_like(new_w),
        "sweights": jnp.zeros_like(new_w),
        "dmin": jnp.min(stacked["dmin"], axis=0),
        "dmax": jnp.max(stacked["dmax"], axis=0),
        "drecip": jnp.sum(stacked["drecip"], axis=0),
        "lmin": jnp.min(stacked["lmin"], axis=0),
        "lmax": jnp.max(stacked["lmax"], axis=0),
        "lsum": jnp.sum(stacked["lsum"], axis=0),
        "lweight": jnp.sum(stacked["lweight"], axis=0),
        "lrecip": jnp.sum(stacked["lrecip"], axis=0),
    }
