"""Multi-chip merge plane: the two-level aggregation tree on a device mesh.

The reference scales horizontally by forwarding mergeable state (t-digests,
HLLs, global counters/gauges) from local veneurs to a global veneur over
gRPC (reference flusher.go:516-591, worker.go:410-467). On a TPU pod the
same tree maps onto the mesh: every chip aggregates its own ingest shard
into a full-width column store, and the per-interval global merge is a set
of collectives over ICI:

  counters  -> psum            (merge = addition, samplers.go:143-145)
  gauges    -> last-set-wins   (merge = overwrite, samplers.go:200-202)
  HLL       -> pmax            (merge = register max, samplers.go:299-311)
  t-digest  -> all_to_all key-sharded recompress + all_gather
               (merge = centroid re-insertion, merging_digest.go:374-389;
               each chip recompresses only its K/n key block)

Cross-host (DCN) hops between tiers use the gRPC forward plane
(veneur_tpu.forward); this module covers the intra-mesh collective path.
"""

from __future__ import annotations

import logging
from functools import partial
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from veneur_tpu.ops import batch_hll, batch_tdigest, scalars

logger = logging.getLogger("veneur_tpu.parallel.mesh")

# shard_map moved to the jax top level (and renamed its replication-
# check kwarg check_rep -> check_vma) after 0.4.x; accept both so the
# collective path runs on every toolchain the image ships
if hasattr(jax, "shard_map"):
    _shard_map, _CHECK_KW = jax.shard_map, "check_vma"
else:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map
    _CHECK_KW = "check_rep"

SHARD_AXIS = "shard"


def make_mesh(n_devices: int = 0) -> Mesh:
    devices = jax.devices()
    if n_devices and len(devices) < n_devices:
        # the default platform (e.g. a single real TPU chip) is smaller
        # than requested; fall back to the virtual CPU mesh
        # (xla_force_host_platform_device_count) for sharding validation
        try:
            cpu = jax.devices("cpu")
            if len(cpu) >= n_devices:
                logger.warning(
                    "make_mesh: default platform has %d devices < %d "
                    "requested; falling back to the virtual CPU mesh "
                    "(validation only — not a production topology)",
                    len(devices), n_devices)
                devices = cpu
            else:
                logger.warning(
                    "make_mesh: only %d devices available, %d requested; "
                    "building an undersized mesh", len(devices), n_devices)
        except RuntimeError:
            pass
    if n_devices:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (SHARD_AXIS,))


def init_sharded_state(mesh: Mesh, num_keys: int) -> Dict:
    """Per-shard column-store state, stacked on a leading shard axis and
    sharded across the mesh. Every shard holds the same key->row layout
    (the host dictionary is replicated by construction: row ids are
    assigned by the global tier's dictionary)."""
    n = mesh.devices.size
    shard = NamedSharding(mesh, P(SHARD_AXIS))

    def mk(leaf):
        stacked = jnp.broadcast_to(leaf[None], (n,) + leaf.shape)
        return jax.device_put(stacked, shard)

    return {
        "counters": jax.tree.map(mk, scalars.init_counters(num_keys)),
        "gauges": jax.tree.map(mk, scalars.init_gauges(num_keys)),
        "histos": jax.tree.map(mk, batch_tdigest.init_state(num_keys)),
        "sets": mk(batch_hll.init_state(num_keys)),
    }


def _merge_digest_keysharded(histo_state, n: int):
    """Inside shard_map: merge every shard's centroid grids, equivalent
    to the global veneur re-inserting each local digest's centroids
    (worker.go:455-457), done as one batched kernel.

    Layout: rather than all_gather-ing all n grids onto every device and
    recompressing all K rows redundantly on each (n*K*2C received and
    K-row sort per device), the key dimension is scattered with an
    all_to_all so each device receives only its K/n key block from every
    shard (K*2C received) and recompresses K/n rows; the compact results
    are then all_gather-ed back to the replicated view. Same collective
    bytes as a reduce_scatter+all_gather pair, n-fold less compute and
    peak memory per device."""
    num_keys = histo_state["wv"].shape[0]
    # fold each shard's staging grid into its slot list
    m, w = batch_tdigest._fold_grids(histo_state)  # (K, 2C)
    pad = (-num_keys) % n
    if pad:
        m = jnp.pad(m, ((0, pad), (0, 0)))
        w = jnp.pad(w, ((0, pad), (0, 0)))
    kp = m.shape[0] // n  # keys per device after scatter
    # (n, kp, 2C) blocks; all_to_all sends block j to device j, so the
    # leading axis afterwards indexes the SOURCE shard for THIS device's
    # key block
    m_all = jax.lax.all_to_all(m.reshape(n, kp, -1), SHARD_AXIS,
                               split_axis=0, concat_axis=0, tiled=False)
    w_all = jax.lax.all_to_all(w.reshape(n, kp, -1), SHARD_AXIS,
                               split_axis=0, concat_axis=0, tiled=False)
    cat_m = jnp.moveaxis(m_all, 0, 1).reshape(kp, -1)  # (kp, n*2C)
    cat_w = jnp.moveaxis(w_all, 0, 1).reshape(kp, -1)
    local_m, local_w = batch_tdigest._recompress(cat_m, cat_w, kp)
    # gather the compact per-block results back into the replicated view;
    # device order == key-block order by construction
    g_m = jax.lax.all_gather(local_m, SHARD_AXIS)  # (n, kp, C)
    g_w = jax.lax.all_gather(local_w, SHARD_AXIS)
    new_m = g_m.reshape(-1, g_m.shape[-1])[:num_keys]
    new_w = g_w.reshape(-1, g_w.shape[-1])[:num_keys]
    return {
        "wv": new_m * new_w,
        "weights": new_w,
        "swv": jnp.zeros_like(new_w),
        "sweights": jnp.zeros_like(new_w),
        "dmin": jax.lax.pmin(histo_state["dmin"], SHARD_AXIS),
        "dmax": jax.lax.pmax(histo_state["dmax"], SHARD_AXIS),
        "drecip": jax.lax.psum(histo_state["drecip"], SHARD_AXIS),
        "lmin": jax.lax.pmin(histo_state["lmin"], SHARD_AXIS),
        "lmax": jax.lax.pmax(histo_state["lmax"], SHARD_AXIS),
        "lsum": jax.lax.psum(histo_state["lsum"], SHARD_AXIS),
        "lweight": jax.lax.psum(histo_state["lweight"], SHARD_AXIS),
        "lrecip": jax.lax.psum(histo_state["lrecip"], SHARD_AXIS),
    }


def _merge_shards_local(state):
    """The shard_map body: collective merge of per-shard stores. Inputs
    arrive with a size-1 local shard axis, which we squeeze away."""
    state = jax.tree.map(lambda a: a[0], state)
    counters = jax.lax.psum(
        scalars.counter_values(state["counters"]), SHARD_AXIS)

    # last-set-wins across shards: highest-indexed shard that saw the gauge
    idx = jax.lax.axis_index(SHARD_AXIS)
    gset = state["gauges"]["set"]
    gval = state["gauges"]["value"]
    rank = jnp.where(gset, idx + 1, 0).astype(jnp.int32)
    best = jax.lax.pmax(rank, SHARD_AXIS)
    contrib = jnp.where(rank == jnp.maximum(best, 1), gval, 0.0)
    gauges_val = jax.lax.psum(contrib, SHARD_AXIS)
    gauges_set = best > 0

    sets = jax.lax.pmax(state["sets"].astype(jnp.int32), SHARD_AXIS).astype(
        jnp.int8)
    # lax.axis_size only exists on newer jax; psum(1) is the portable
    # spelling of the same constant
    n = (jax.lax.axis_size(SHARD_AXIS) if hasattr(jax.lax, "axis_size")
         else jax.lax.psum(1, SHARD_AXIS))
    histos = _merge_digest_keysharded(state["histos"], n)
    return {
        "counters": counters,
        "gauges": {"value": gauges_val, "set": gauges_set},
        "sets": sets,
        "histos": histos,
    }


def merge_shards(mesh: Mesh, state: Dict) -> Dict:
    """Merge every shard's interval state into the replicated global view.
    This is the flush-time 'forward + import' of the reference collapsed
    into ICI collectives."""
    spec_in = jax.tree.map(lambda _: P(SHARD_AXIS), state)
    out_specs = jax.tree.map(lambda _: P(), {
        "counters": 0, "gauges": {"value": 0, "set": 0}, "sets": 0,
        "histos": {k: 0 for k in batch_tdigest.init_state(1)}})
    # replication check off: outputs are replicated by construction
    # (derived from all_gather/psum results) but the tracker can't prove
    # it through sort
    fn = _shard_map(
        _merge_shards_local, mesh=mesh, in_specs=(spec_in,),
        out_specs=out_specs, **{_CHECK_KW: False})
    return fn(state)


def apply_shard_batches(state: Dict, batches: Dict) -> Dict:
    """Apply per-shard COO batches (leading axis = shard) to per-shard
    stores; pure data parallelism over the shard axis, no communication."""
    def one(cstate, gstate, hstate, sstate, b):
        c = scalars.apply_counters(
            cstate, b["c_rows"], b["c_vals"], b["c_rates"])
        g = scalars.apply_gauges(gstate, b["g_rows"], b["g_vals"])
        h = batch_tdigest.apply_batch(
            hstate, b["h_rows"], b["h_vals"], b["h_wts"], b["h_slots"])
        s = batch_hll.apply_batch(
            sstate, b["s_rows"], b["s_idx"], b["s_rho"])
        return c, g, h, s

    c, g, h, s = jax.vmap(one)(
        state["counters"], state["gauges"], state["histos"], state["sets"],
        batches)
    return {"counters": c, "gauges": g, "histos": h, "sets": s}


def make_shard_batches(n: int, num_keys: int, batch: int, seed: int = 0) -> Dict:
    """Synthetic per-shard sample batches (for dryrun/bench)."""
    rng = np.random.default_rng(seed)
    f32 = np.float32
    h_rows = rng.integers(0, num_keys, (n, batch)).astype(np.int32)
    h_vals = rng.normal(100, 15, (n, batch)).astype(f32)
    h_wts = np.ones((n, batch), f32)
    h_slots = np.stack(
        [batch_tdigest.batch_slots(h_rows[i], h_vals[i], h_wts[i], num_keys)
         for i in range(n)])
    return {
        "c_rows": rng.integers(0, num_keys, (n, batch)).astype(np.int32),
        "c_vals": rng.random((n, batch)).astype(f32) * 10,
        "c_rates": np.ones((n, batch), f32),
        "g_rows": rng.integers(0, num_keys, (n, batch)).astype(np.int32),
        "g_vals": rng.random((n, batch)).astype(f32),
        "h_rows": h_rows,
        "h_vals": h_vals,
        "h_wts": h_wts,
        "h_slots": h_slots,
        "s_rows": rng.integers(0, num_keys, (n, batch)).astype(np.int32),
        "s_idx": rng.integers(0, batch_hll.M, (n, batch)).astype(np.int32),
        "s_rho": rng.integers(1, 30, (n, batch)).astype(np.int32),
    }


def full_step(mesh: Mesh, state: Dict, batches: Dict) -> Tuple[Dict, Dict]:
    """One full sharded aggregation step: per-shard batch apply (data
    parallel) followed by the collective global merge — the computation
    `__graft_entry__.dryrun_multichip` compiles over the mesh."""
    state = apply_shard_batches(state, batches)
    merged = merge_shards(mesh, state)
    return state, merged
