"""Elastic resharding: live digest-range migration with WAL-backed
exactly-once cutover.

Takes the serving plane from N shards to M while ingest keeps flowing,
with zero loss provable by the strict flow ledger. Three phases:

**plan** — compute the new digest-range -> home assignment (contiguous
range partition: the only rows that change home are the ones in cells
whose range boundary moved) and background-compile the M-shard
apply/readout/merge kernels through the shape-ladder prewarmer
(core/flushexec.py) against throwaway M-shard tables, so the cutover
never pays a cold XLA retrace.

**cutover** — at a flush boundary (under the server's flush lock, with
every in-flight background readout joined first): atomically
`reshard_swap` each family's old generation, capture the merged
per-row state, WAL-append it as metricpb wire — one spool segment per
migrating digest-range cell — *before* any state moves, then merge the
captured rows back through the exact decode+merge path crash recovery
uses. Replay-as-the-only-path is what makes the cutover exactly-once:
merged device state is volatile until the segments are popped, and the
segments are popped before the flush lock is released, so a crash at
ANY point either replays a segment whose merge died with the process
or finds no segment because the merge already flushed. Post-reshard
flush output is bit-identical to a never-resharded control (counters
exact through the int64 wire, llhist/HLL registers bit-for-bit,
t-digest centroids re-compressed once — same count, quantiles within
compression tolerance).

**recover** — a crash (SIGKILL) anywhere mid-reshard leaves range
segments in the reshard spool; the next start replays them exactly
once into whatever topology the new process builds. A device-loss
event is a forced scale-down through the same machinery
(`device_loss(shard)`).

Degraded mode: with neither `reshard_spool_dir` nor
`carryover_spool_dir` configured there is no WAL — the cutover merges
from memory (zero loss absent a crash, no crash coverage) and logs
loudly. An append fault (disk error / chaos seam) degrades only the
faulted cell the same way.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from veneur_tpu.forward import rangewire
from veneur_tpu.parallel import collectives
from veneur_tpu.parallel.sharded_server import (ShardedServingPlane,
                                                local_shard_devices)
from veneur_tpu.util import chaos as chaos_mod
from veneur_tpu.util.spool import CarryoverSpool

logger = logging.getLogger("veneur_tpu.reshard")

_FULL = 1 << 64

# state machine: idle -> planning -> ready -> cutover -> idle
_STATE_IDS = {"idle": 0.0, "planning": 1.0, "ready": 2.0, "cutover": 3.0}

# fixed family encode order; per-cell l-stat sidecars are aligned with
# the cell's histogram frames, so the order must be deterministic
_FAMILY_ORDER = ("counter", "gauge", "histogram", "llhist", "set")


class ReshardError(Exception):
    """Invalid reshard request (not sharded, bad target, in progress)."""


def migration_cells(n_old: int, n_new: int) -> List[dict]:
    """The digest-range cells of an N->M reshard: the union of both
    partitions' range boundaries splits [0, 2^64) into at most N+M-1
    contiguous cells, each with ONE old home and ONE new home."""
    bounds = sorted(set(collectives.range_bounds(n_old))
                    | set(collectives.range_bounds(n_new)))
    cells = []
    for i, lo in enumerate(bounds):
        hi = bounds[i + 1] if i + 1 < len(bounds) else _FULL
        cells.append({
            "lo": lo, "hi": hi,
            "old_home": (lo * n_old) >> 64,
            "new_home": (lo * n_new) >> 64,
        })
    return cells


class _PlanStore:
    """Shim store for the plan-phase prewarmer: throwaway M-shard
    tables at the live capacities (the prewarmer only calls
    .tables())."""

    def __init__(self, tables):
        self._tables = tuple(tables)

    def tables(self):
        return self._tables


class ReshardController:
    """Owns the reshard state machine for one server. Thread-safe:
    `begin` spawns the plan thread; `cutover` runs under the server's
    flush lock; telemetry/describe are lock-free point reads."""

    def __init__(self, server):
        self._server = server
        self._lock = threading.Lock()
        self.state = "idle"
        self.epoch = 0
        self.target_shards = 0
        self.deadline_unix = 0.0
        self.last_error = ""
        self.last_cutover_seconds = 0.0
        self.segments_written = 0
        self.replayed_segments = 0
        self.append_faults = 0
        self.capture_failures = 0
        self.device_losses = 0
        self.cutovers = 0
        self._inflight = 0  # metrics captured but not yet merged back
        self._thread: Optional[threading.Thread] = None
        self._spool_obj: Optional[CarryoverSpool] = None
        cfg = getattr(server, "config", None)
        self._deadline_default = float(
            getattr(cfg, "reshard_deadline", 30.0) or 30.0)
        d = getattr(cfg, "reshard_spool_dir", "") or ""
        if not d:
            carry = getattr(cfg, "carryover_spool_dir", "") or ""
            if carry:
                d = os.path.join(carry, "reshard")
        self._spool_dir = d
        if not d:
            logger.warning(
                "reshard: no spool directory configured "
                "(reshard_spool_dir / carryover_spool_dir both empty) — "
                "cutovers will run WITHOUT a WAL: zero loss absent a "
                "crash, but a crash mid-cutover loses the migrating "
                "interval")

    # -- wiring ----------------------------------------------------------

    def _spool(self) -> Optional[CarryoverSpool]:
        if self._spool_obj is None and self._spool_dir:
            # generous bounds: a range segment holds one interval's
            # migrating rows; shedding one would be silent loss, which
            # is the one thing the reshard WAL exists to prevent
            self._spool_obj = CarryoverSpool(
                self._spool_dir, max_bytes=2 * 1024 * 1024 * 1024,
                max_segments=65536, ledger=None)
        return self._spool_obj

    def _ledger(self):
        led = getattr(self._server, "ledger", None)
        return led if (led is not None and led.enabled) else None

    def inflight_metrics(self) -> int:
        """Ledger stock `reshard_inflight`: rows captured out of the
        old generation but not yet merged into the new one. Always 0 at
        interval close — the whole cutover runs under the flush lock —
        so any nonzero closing level is itself a conservation break."""
        return self._inflight

    # -- public API ------------------------------------------------------

    def begin(self, shards: Optional[int] = None, devices=None,
              deadline_s: Optional[float] = None,
              block: bool = False) -> dict:
        """Start an elastic reshard to `shards` (or an explicit device
        list). Plans + prewarms on a background thread, then cuts over
        at the next flush boundary it can take. `block=True` joins."""
        store = self._server.store
        if store.shard_plane is None:
            raise ReshardError("store is not sharded (no serving plane)")
        if devices is None:
            if shards is None or int(shards) < 1:
                raise ReshardError("target shards must be >= 1")
            devices = local_shard_devices(int(shards))
        devices = list(devices)
        if not devices:
            raise ReshardError("no devices available for target plane")
        with self._lock:
            if self.state != "idle":
                raise ReshardError(
                    f"reshard already in progress (state={self.state})")
            self.state = "planning"
            self.target_shards = len(devices)
            self.last_error = ""
            dl = (float(deadline_s) if deadline_s is not None
                  else self._deadline_default)
            self.deadline_unix = time.time() + dl
        from veneur_tpu.util.crash import guarded
        self._thread = threading.Thread(
            target=guarded(self._run), args=(devices,),
            name="reshard-plan", daemon=True)
        self._thread.start()
        if block:
            self._thread.join()
            if self.last_error:
                raise ReshardError(self.last_error)
        return self.describe()

    def device_loss(self, shard_index: int,
                    deadline_s: Optional[float] = None,
                    block: bool = False) -> dict:
        """Forced scale-down after losing one device: reshard onto the
        surviving devices through the normal plan/cutover machinery.
        The lost shard's un-flushed interval state is gone with the
        device — what this saves is every OTHER shard's state plus the
        routing: no row keeps a dead home."""
        plane = self._server.store.shard_plane
        if plane is None:
            raise ReshardError("store is not sharded (no serving plane)")
        survivors = [d for i, d in enumerate(plane.devices)
                     if i != int(shard_index)]
        if not survivors:
            raise ReshardError("no surviving devices")
        self.device_losses += 1
        logger.error(
            "device loss on shard %d/%d: forcing scale-down to %d "
            "shards", shard_index, plane.n, len(survivors))
        return self.begin(devices=survivors, deadline_s=deadline_s,
                          block=block)

    def past_deadline(self) -> bool:
        return (self.state != "idle" and self.deadline_unix > 0
                and time.time() > self.deadline_unix)

    def describe(self) -> dict:
        plane = self._server.store.shard_plane
        return {
            "state": self.state,
            "epoch": self.epoch,
            "shards": plane.n if plane is not None else 0,
            "target_shards": self.target_shards,
            "deadline_unix": round(self.deadline_unix, 3),
            "past_deadline": self.past_deadline(),
            "durable": bool(self._spool_dir),
            "spool_dir": self._spool_dir,
            "cutovers": self.cutovers,
            "last_cutover_seconds": round(self.last_cutover_seconds, 6),
            "segments_written": self.segments_written,
            "replayed_segments": self.replayed_segments,
            "append_faults": self.append_faults,
            "capture_failures": self.capture_failures,
            "device_losses": self.device_losses,
            "inflight_metrics": self._inflight,
            "last_error": self.last_error,
        }

    def telemetry_rows(self) -> List[tuple]:
        return [
            ("reshard.state", "gauge", _STATE_IDS.get(self.state, -1.0),
             (f"state:{self.state}",)),
            ("reshard.epoch", "counter", float(self.epoch), ()),
            ("reshard.cutovers", "counter", float(self.cutovers), ()),
            ("reshard.last_cutover_seconds", "gauge",
             self.last_cutover_seconds, ()),
            ("reshard.segments_written", "counter",
             float(self.segments_written), ()),
            ("reshard.replayed_segments", "counter",
             float(self.replayed_segments), ()),
            ("reshard.append_faults", "counter",
             float(self.append_faults), ()),
            ("reshard.capture_failures", "counter",
             float(self.capture_failures), ()),
            ("reshard.device_losses", "counter",
             float(self.device_losses), ()),
            ("reshard.inflight_metrics", "gauge",
             float(self._inflight), ()),
        ]

    # -- plan ------------------------------------------------------------

    def _run(self, devices) -> None:
        try:
            chaos = getattr(self._server, "chaos", None)
            if chaos is not None:
                chaos.reshard_prewarm_delay()
            plane = ShardedServingPlane(devices)
            self._prewarm(plane)
            with self._lock:
                self.state = "ready"
            self.cutover(plane)
        except Exception as e:
            logger.exception("reshard to %d shards failed", len(devices))
            self.last_error = f"{type(e).__name__}: {e}"
            with self._lock:
                self.state = "idle"
        finally:
            self.deadline_unix = 0.0
            self.target_shards = 0

    def _prewarm(self, plane: ShardedServingPlane) -> None:
        """Compile the M-shard apply/readout/merge kernels against
        throwaway tables at the LIVE capacities, so the retopo'd real
        tables hit the process-global jit cache on their first batch.
        Best-effort: a prewarm failure costs a hot retrace, not the
        reshard."""
        from veneur_tpu.core import sharded_tables as st
        from veneur_tpu.core.flushexec import ShapeLadderPrewarmer
        classes = {
            "counter": st.ShardedCounterTable,
            "gauge": st.ShardedGaugeTable,
            "histogram": st.ShardedHistoTable,
            "llhist": st.ShardedLLHistTable,
            "set": st.ShardedSetTable,
        }
        server = self._server
        shim_tables = []
        for family, table in server.store.tables():
            cls = classes.get(family)
            if cls is None:
                continue
            try:
                shim_tables.append(
                    (family, cls(capacity=table.capacity, plane=plane)))
            except Exception:
                logger.exception(
                    "reshard plan: throwaway %s table build failed "
                    "(cutover will pay the retrace)", family)
        if not shim_tables:
            return
        pw = ShapeLadderPrewarmer(
            _PlanStore(shim_tables),
            percentiles=getattr(server, "percentiles", ()),
            need_export=(getattr(server, "is_local", False)
                         and getattr(server, "forwarder", None)
                         is not None),
            on_event=server.telemetry.record_event)
        pw.start()
        for family, table in shim_tables:
            pw._enqueue(family, table.capacity)
        remaining = max(1.0, self.deadline_unix - time.time())
        # stop() appends the queue sentinel AFTER the enqueued rungs,
        # so every rung compiles before the thread exits (or the
        # deadline expires and the daemon thread is abandoned)
        pw.stop(timeout=remaining)

    # -- cutover ---------------------------------------------------------

    def cutover(self, plane: ShardedServingPlane) -> None:
        """The atomic topology swap. Everything — join, swap, capture,
        WAL append, merge-back, segment pop — happens under the
        server's flush lock, so no flush can deliver half-migrated
        state downstream and the popped-segment invariant holds (see
        module docstring)."""
        server = self._server
        chaos = getattr(server, "chaos", None)
        t0 = time.perf_counter()
        with self._lock:
            self.state = "cutover"
        try:
            with server._flush_lock:
                # join in-flight background readouts first: a pending
                # readout applies its staged columns through the LIVE
                # routing attributes, which the retopo is about to
                # replace. Futures cache results, so the flush loop's
                # own later join is a cheap re-read.
                for rec in list(server._inflight_flushes):
                    pending = rec.get("pending")
                    if pending is not None:
                        try:
                            pending.result(timeout=120.0)
                        except Exception:
                            logger.exception(
                                "reshard: in-flight readout join "
                                "failed; its interval rides the "
                                "readout-miss carry path")
                store = server.store
                n_old = store.shard_plane.n
                n_new = plane.n
                snaps: Dict[str, dict] = {}
                for family, table in store.tables():
                    if not hasattr(table, "reshard_swap"):
                        continue  # host-only families (statuses)
                    try:
                        snaps[family] = table.reshard_swap(plane)
                    except Exception:
                        self.capture_failures += 1
                        logger.exception(
                            "reshard: %s capture failed — family "
                            "restarts empty on the new plane (its "
                            "un-flushed interval state is lost)",
                            family)
                store.shard_plane = plane
                cells = self._encode_cells(snaps, n_old, n_new)
                self._wal_and_merge(cells, chaos)
                # the merged old-mesh capture generations are dead: the
                # HBM-ledger tokens that rode each family's snap as
                # `reshard_capture` unregister here
                for family, table in store.tables():
                    snap = snaps.get(family)
                    obs = getattr(table, "_deviceobs", None)
                    if snap is not None and obs is not None:
                        obs.drop(snap.pop("_devobs", None))
                self.epoch += 1
                self.cutovers += 1
        finally:
            self.last_cutover_seconds = time.perf_counter() - t0
            with self._lock:
                self.state = "idle"
        logger.info(
            "reshard cutover complete: %d -> %d shards, epoch %d, "
            "%.3fs", n_old, n_new, self.epoch, self.last_cutover_seconds)
        try:
            server.telemetry.record_event(
                "reshard_cutover", shards_old=n_old, shards_new=n_new,
                epoch=self.epoch,
                duration_s=round(self.last_cutover_seconds, 6))
        except Exception:
            pass

    # -- capture encode --------------------------------------------------

    def _encode_cells(self, snaps: Dict[str, dict], n_old: int,
                      n_new: int) -> List[dict]:
        """Serialize every touched captured row into its digest-range
        cell's frame list. ALL touched rows are encoded — even
        zero-total counters — because touched rows emit at flush, and
        bit-identity with a never-resharded control requires the
        post-cutover flush to see the same row set."""
        cells = migration_cells(n_old, n_new)
        for cell in cells:
            cell["frames"] = []
            cell["histo_l"] = {k: [] for k in rangewire.LSTAT_FIELDS}
            cell["count"] = 0
        bounds = np.array([c["lo"] for c in cells], np.uint64)

        def rows_and_cells(snap):
            touched = snap["touched"]
            meta = snap["meta"]
            limit = min(touched.shape[0], len(meta))
            rows = np.flatnonzero(touched[:limit])
            idx = np.searchsorted(bounds, snap["digest64"][rows],
                                  side="right") - 1
            return rows.tolist(), idx.tolist(), meta

        for family in _FAMILY_ORDER:
            snap = snaps.get(family)
            if snap is None:
                continue
            if family == "counter" and "dev" in snap:
                values = (np.asarray(snap["dev"][0], np.float64)
                          - np.asarray(snap["dev"][1], np.float64))
                acc = snap.get("import_acc")
                if acc is not None:
                    values[:acc.shape[0]] += acc
                rows, idx, meta = rows_and_cells(snap)
                for row, c in zip(rows, idx):
                    cells[c]["frames"].append(rangewire.counter_to_wire(
                        meta[row], values[row]))
                    cells[c]["count"] += 1
            elif family == "gauge" and "dev" in snap:
                values = np.asarray(snap["dev"], np.float64)
                rows, idx, meta = rows_and_cells(snap)
                for row, c in zip(rows, idx):
                    cells[c]["frames"].append(rangewire.gauge_to_wire(
                        meta[row], values[row]))
                    cells[c]["count"] += 1
            elif family == "histogram" and "hstate" in snap:
                h = {k: np.asarray(v) for k, v in snap["hstate"].items()}
                weights = h["weights"]
                means = np.divide(h["wv"], weights,
                                  out=np.zeros_like(weights),
                                  where=weights > 0)
                rows, idx, meta = rows_and_cells(snap)
                for row, c in zip(rows, idx):
                    cells[c]["frames"].append(
                        rangewire.histogram_to_wire(
                            meta[row], means[row], weights[row],
                            h["dmin"][row], h["dmax"][row],
                            h["drecip"][row]))
                    cells[c]["count"] += 1
                    for k in rangewire.LSTAT_FIELDS:
                        cells[c]["histo_l"][k].append(float(h[k][row]))
            elif family == "llhist" and "bins" in snap:
                bins = np.asarray(snap["bins"])
                rows, idx, meta = rows_and_cells(snap)
                for row, c in zip(rows, idx):
                    cells[c]["frames"].append(rangewire.llhist_to_wire(
                        meta[row], bins[row]))
                    cells[c]["count"] += 1
            elif family == "set" and "regs" in snap:
                regs = np.asarray(snap["regs"])
                rows, idx, meta = rows_and_cells(snap)
                for row, c in zip(rows, idx):
                    cells[c]["frames"].append(rangewire.set_to_wire(
                        meta[row], regs[row]))
                    cells[c]["count"] += 1
        out = []
        for cell in cells:
            if not cell["frames"]:
                continue
            if cell["histo_l"]["lsum"]:
                cell["frames"].append(
                    rangewire.lstat_sidecar(cell["histo_l"]))
            out.append(cell)
        return out

    # -- WAL + merge-back ------------------------------------------------

    def _wal_and_merge(self, cells: List[dict], chaos) -> None:
        spool = self._spool()
        token = f"reshard-{self.epoch + 1:06d}"
        self._inflight = sum(cell["count"] for cell in cells)
        mem_cells: List[List[bytes]] = []
        for i, cell in enumerate(cells):
            if spool is None:
                mem_cells.append(cell["frames"])
                continue
            try:
                if chaos is not None:
                    chaos.reshard_append_seam()
                spool.append(cell["frames"], extra={
                    "kind": "reshard", "token": token, "cell": i,
                    "lo": str(cell["lo"]), "hi": str(cell["hi"]),
                    "old_home": cell["old_home"],
                    "new_home": cell["new_home"],
                    "count": cell["count"]})
                self.segments_written += 1
            except (chaos_mod.ChaosError, OSError) as e:
                self.append_faults += 1
                logger.error(
                    "reshard: range segment append failed (%s); cell "
                    "%d merges from memory — zero loss absent a "
                    "crash, but this cell has no crash coverage", e, i)
                mem_cells.append(cell["frames"])
        # the SIGKILL window the soak targets: every durable cell is on
        # disk, the retopo'd tables are empty — a kill here must replay
        # to exactly the same state the merge below produces
        if chaos is not None:
            chaos.reshard_cutover_delay()
        if spool is not None:
            for seg in spool.segments():
                extra = seg.extra or {}
                if extra.get("kind") != "reshard":
                    continue
                batch = rangewire.decode_segment(seg.read_metrics())
                self._merge_decoded(batch)
                spool.pop(seg)
        for frames in mem_cells:
            self._merge_decoded(rangewire.decode_segment(frames))
        self._inflight = 0

    def _merge_decoded(self, batch: rangewire.DecodedBatch) -> int:
        """Merge one decoded range segment into the live tables — the
        single replay path shared by cutover merge-back and crash
        recovery. Ledger: each family batch books ingest.admitted
        (key=reshard); merge_batch books agg.applied (and agg.rejected
        for cardinality-capped rows), so the ingest identity balances
        within the interval."""
        store = self._server.store
        led = self._ledger()

        def admit(n: int) -> None:
            if led is not None and n:
                led.note("ingest.admitted", n, key="reshard")

        merged = 0
        if batch.counter_stubs:
            admit(len(batch.counter_stubs))
            store.counters.merge_batch(batch.counter_stubs,
                                       batch.counter_values)
            merged += len(batch.counter_stubs)
        if batch.gauge_stubs:
            admit(len(batch.gauge_stubs))
            store.gauges.merge_batch(batch.gauge_stubs,
                                     batch.gauge_values)
            merged += len(batch.gauge_stubs)
        if batch.histo_stubs:
            from veneur_tpu.ops import batch_tdigest
            admit(len(batch.histo_stubs))
            pm, pw = batch_tdigest.pack_centroids_many(
                batch.histo_means, batch.histo_weights)
            store.histos.merge_batch(
                batch.histo_stubs, pm, pw, batch.histo_mins,
                batch.histo_maxs, batch.histo_recips)
            if batch.lstats is not None:
                if hasattr(store.histos, "merge_local_stats"):
                    store.histos.merge_local_stats(
                        batch.histo_stubs,
                        *(batch.lstats[k]
                          for k in rangewire.LSTAT_FIELDS))
                else:
                    logger.warning(
                        "reshard replay: store has no sharded "
                        "histogram table; migrated local-sample "
                        "stats (min/max/sum) dropped")
            merged += len(batch.histo_stubs)
        if batch.llhist_stubs:
            admit(len(batch.llhist_stubs))
            store.llhists.merge_batch(batch.llhist_stubs,
                                      np.stack(batch.llhist_bins))
            merged += len(batch.llhist_stubs)
        if batch.set_stubs:
            admit(len(batch.set_stubs))
            store.sets.merge_batch(batch.set_stubs,
                                   np.stack(batch.set_regs))
            merged += len(batch.set_stubs)
        if batch.parse_errors:
            logger.error("reshard replay: %d unparseable frames "
                         "dropped", batch.parse_errors)
        return merged

    # -- recovery --------------------------------------------------------

    def recover(self) -> int:
        """Replay range segments a killed predecessor left behind.
        Runs at startup before listeners: the rows merge into whatever
        topology THIS process built (the WAL stores rows, not shard
        assignments — routing is recomputed by merge_batch), so
        recovery is correct even when the restart config differs from
        the mid-flight target plane."""
        spool = self._spool()
        if spool is None:
            return 0
        replayed = 0
        for seg in spool.segments():
            extra = seg.extra or {}
            if extra.get("kind") != "reshard":
                continue
            try:
                batch = rangewire.decode_segment(seg.read_metrics())
                self._merge_decoded(batch)
            except Exception:
                logger.exception(
                    "reshard recovery: segment %s replay failed; "
                    "left in place", seg.path)
                continue
            spool.pop(seg)
            replayed += 1
            self.replayed_segments += 1
        if replayed:
            logger.warning(
                "reshard recovery: replayed %d range segment(s) from "
                "an interrupted cutover", replayed)
            try:
                self._server.telemetry.record_event(
                    "reshard_replay", segments=replayed)
            except Exception:
                pass
        return replayed
