"""SSF wire protocol: framing for streaming SSF spans.

Frame layout (parity with reference protocol/wire.go:1-230):

    [ 8 bits  - version/type of message; only 0 is defined ]
    [32 bits  - big-endian length of the SSF message in octets ]
    [<length> - protobuf-encoded ssf.SSFSpan ]

Lengths above MAX_SSF_PACKET_LENGTH (16 MB) are rejected. The protocol has
no resync hints, so any framing error is fatal to the stream: callers must
close the connection when `is_framing_error` returns True.
"""

from __future__ import annotations

import struct
from typing import BinaryIO, Optional

from google.protobuf.message import DecodeError

from veneur_tpu.ssf.protos import ssf_pb2

MAX_SSF_PACKET_LENGTH = 16 * 1024 * 1024
SSF_FRAME_LENGTH = 1 + 4
_VERSION_0 = 0
_HDR = struct.Struct(">BI")


class FramingError(IOError):
    """The stream is desynchronized and must be closed."""


class SSFDecodeError(ValueError):
    """A correctly-framed message failed protobuf decoding; the stream
    itself is still synchronized and usable."""


class InvalidTrace(ValueError):
    def __init__(self, span):
        super().__init__(f"not a valid trace span: id={span.id} "
                         f"trace_id={span.trace_id} name={span.name!r}")
        self.span = span


def is_framing_error(err: BaseException) -> bool:
    return isinstance(err, FramingError)


def valid_trace(span: ssf_pb2.SSFSpan) -> bool:
    """True iff the span can participate in a trace (wire.go:82-88)."""
    return (span.id != 0 and span.trace_id != 0
            and span.start_timestamp != 0 and span.end_timestamp != 0
            and span.name != "")


def validate_trace(span: ssf_pb2.SSFSpan) -> None:
    if not valid_trace(span):
        raise InvalidTrace(span)


def parse_ssf(packet: bytes) -> ssf_pb2.SSFSpan:
    """Decode one SSFSpan and normalize it (wire.go ParseSSF):
    a "name" tag fills an empty span name; zero sample rates become 1."""
    span = ssf_pb2.SSFSpan()
    try:
        span.ParseFromString(packet)
    except DecodeError as e:
        raise SSFDecodeError(f"invalid SSF protobuf: {e}") from e
    if not span.name and "name" in span.tags:
        span.name = span.tags["name"]
        del span.tags["name"]
    for sample in span.metrics:
        if sample.sample_rate == 0:
            sample.sample_rate = 1.0
    return span


def _read_exact(stream: BinaryIO, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = stream.read(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


def read_ssf(stream: BinaryIO,
             max_length: int = MAX_SSF_PACKET_LENGTH,
             ) -> Optional[ssf_pb2.SSFSpan]:
    """Read one framed span. Returns None on clean EOF at a frame
    boundary; raises FramingError on any mid-frame or header corruption.
    max_length caps the accepted frame body (config
    trace_max_length_bytes, reference server.go:498)."""
    first = stream.read(1)
    if not first:
        return None  # clean hang-up between messages
    version = first[0]
    if version != _VERSION_0:
        raise FramingError(f"unknown SSF frame version {version}")
    hdr = _read_exact(stream, 4)
    if hdr is None:
        raise FramingError("EOF inside SSF frame header")
    (length,) = struct.unpack(">I", hdr)
    if length > max_length:
        raise FramingError(f"SSF frame length {length} exceeds "
                           f"{max_length}")
    body = _read_exact(stream, length)
    if body is None:
        raise FramingError("EOF inside SSF frame body")
    return parse_ssf(body)


def write_ssf(stream: BinaryIO, span: ssf_pb2.SSFSpan) -> int:
    """Frame and write one span; returns bytes written."""
    frame = frame_ssf(span)
    stream.write(frame)
    return len(frame)


def frame_ssf(span: ssf_pb2.SSFSpan) -> bytes:
    body = span.SerializeToString()
    if len(body) > MAX_SSF_PACKET_LENGTH:
        raise FramingError(f"span encodes to {len(body)} bytes, over the "
                           f"{MAX_SSF_PACKET_LENGTH} frame cap")
    return _HDR.pack(_VERSION_0, len(body)) + body
