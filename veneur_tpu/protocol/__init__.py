from veneur_tpu.protocol.wire import (  # noqa: F401
    MAX_SSF_PACKET_LENGTH,
    SSF_FRAME_LENGTH,
    FramingError,
    frame_ssf,
    InvalidTrace,
    SSFDecodeError,
    is_framing_error,
    parse_ssf,
    read_ssf,
    valid_trace,
    validate_trace,
    write_ssf,
)
