"""DogStatsD wire-format rendering: the single source of truth for
`name:value|type|@rate|#tags` packets, events, and service checks.

This is the emit side of the grammar that samplers/parser.py consumes
(reference cmd/veneur-emit/main.go:594-930 createMetric / event / service
check packet builders). Shared by veneur-emit, veneur-prometheus, the
scopedstatsd self-metrics client, and the prometheus repeater sink.
"""

from __future__ import annotations

from typing import List


def render_metric_packet(name: str, value, mtype: str,
                         tags: List[str], rate: float = 1.0) -> bytes:
    parts = [f"{name}:{value}|{mtype}"]
    if rate != 1.0:
        parts.append(f"@{rate}")
    if tags:
        parts.append("#" + ",".join(tags))
    return "|".join(parts).encode()


def render_event_packet(title: str, text: str, tags: List[str],
                        aggregation_key: str = "", priority: str = "",
                        source_type: str = "", alert_type: str = "",
                        hostname: str = "", timestamp: str = "") -> bytes:
    header = f"_e{{{len(title.encode())},{len(text.encode())}}}:{title}|{text}"
    sections = []
    if timestamp:
        sections.append(f"d:{timestamp}")
    if aggregation_key:
        sections.append(f"k:{aggregation_key}")
    if priority:
        sections.append(f"p:{priority}")
    if source_type:
        sections.append(f"s:{source_type}")
    if alert_type:
        sections.append(f"t:{alert_type}")
    if hostname:
        sections.append(f"h:{hostname}")
    if tags:
        sections.append("#" + ",".join(tags))
    return ("|".join([header] + sections)).encode()


def render_service_check_packet(name: str, status: int, tags: List[str],
                                message: str = "",
                                hostname: str = "",
                                timestamp: str = "") -> bytes:
    parts = [f"_sc|{name}|{status}"]
    if timestamp:
        parts.append(f"d:{timestamp}")
    if hostname:
        parts.append(f"h:{hostname}")
    if tags:
        parts.append("#" + ",".join(tags))
    if message:
        parts.append(f"m:{message}")
    return "|".join(parts).encode()
