"""S3 sink: per-flush TSV object uploads.

Behavioral parity with reference sinks/s3/s3.go (172 LoC) + util/csv.go:
each flush encodes every InterMetric as one TSV row (same column layout
as the localfile sink), gzips it, and uploads to
s3://<bucket>/<hostname>/<timestamp>.tsv.gz. The uploader is a pluggable
boundary (the reference takes an s3iface; tests inject a fake).
"""

from __future__ import annotations

import csv
import gzip
import io
import logging
import time
from typing import List, Optional

from veneur_tpu.samplers.metrics import InterMetric
from veneur_tpu.sinks import MetricSink, register_metric_sink
from veneur_tpu.sinks.localfile import HEADERS

logger = logging.getLogger("veneur_tpu.sinks.s3")


class Uploader:
    def upload(self, bucket: str, key: str, body: bytes) -> None:
        raise NotImplementedError


class Boto3Uploader(Uploader):
    def __init__(self, region: str = "", access_key_id: str = "",
                 secret_access_key: str = ""):
        import boto3  # gated import
        # explicit static credentials when configured (reference
        # s3.go:67-75), else the SDK's default chain
        kw = {}
        if access_key_id:
            kw = {"aws_access_key_id": access_key_id,
                  "aws_secret_access_key": secret_access_key}
        self._client = boto3.client("s3", region_name=region or None, **kw)

    def upload(self, bucket: str, key: str, body: bytes) -> None:
        self._client.put_object(Bucket=bucket, Key=key, Body=body)


class InMemoryUploader(Uploader):
    """Test uploader: records (bucket, key, body)."""

    def __init__(self):
        self.objects: List[tuple] = []

    def upload(self, bucket: str, key: str, body: bytes) -> None:
        self.objects.append((bucket, key, body))


def encode_tsv(metrics: List[InterMetric], hostname: str,
               interval: float) -> bytes:
    buf = io.StringIO()
    w = csv.writer(buf, delimiter="\t")
    partition = time.strftime("%Y%m%d")
    for m in metrics:
        w.writerow([m.name, ",".join(m.tags), m.type.name.lower(),
                    m.hostname, m.timestamp, m.value, partition, hostname,
                    int(interval)])
    return buf.getvalue().encode()


class S3MetricSink(MetricSink):
    def __init__(self, name: str, uploader: Optional[Uploader], bucket: str,
                 hostname: str, interval: float):
        self._name = name
        self.uploader = uploader
        self.bucket = bucket
        self.hostname = hostname
        self.interval = interval

    def name(self) -> str:
        return self._name

    def kind(self) -> str:
        return "s3"

    def flush(self, metrics: List[InterMetric]) -> None:
        if self.uploader is None or not metrics:
            return
        body = gzip.compress(
            encode_tsv(metrics, self.hostname, self.interval))
        key = f"{self.hostname}/{int(time.time())}.tsv.gz"
        try:
            self.uploader.upload(self.bucket, key, body)
        except Exception as e:
            logger.error("s3 upload of %s failed: %s", key, e)


@register_metric_sink("s3")
def _factory(sink_config, server_config):
    c = sink_config.config
    uploader = c.get("uploader")  # tests inject one
    if uploader is None:
        try:
            uploader = Boto3Uploader(
                c.get("region", ""),
                access_key_id=str(c.get("access_key_id", "")),
                secret_access_key=str(c.get("secret_access_key", "")))
        except Exception as e:
            logger.error("s3 uploader unavailable: %s", e)
            uploader = None
    return S3MetricSink(
        sink_config.name or "s3",
        uploader=uploader,
        bucket=c.get("s3_bucket", "") or c.get("bucket", ""),
        hostname=server_config.hostname,
        interval=server_config.interval)
