"""Prometheus sink: statsd-exporter repeater or embedded exposition.

Behavioral parity with reference sinks/prometheus/prometheus.go (165 LoC):
two modes —
- repeater: re-emit each InterMetric as a statsd line to a
  statsd_exporter address (UDP/TCP),
- embedded exposition: serve the last flush in Prometheus text format on
  a local HTTP port for scraping.
"""

from __future__ import annotations

import logging
import socket
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from veneur_tpu.protocol.render import render_metric_packet
from veneur_tpu.samplers.metrics import InterMetric, MetricType
from veneur_tpu.sinks import MetricSink, register_metric_sink
from veneur_tpu.sinks.cortex import sanitize_label, sanitize_name

logger = logging.getLogger("veneur_tpu.sinks.prometheus")


def escape_label_value(v: str) -> str:
    """Exposition-format label-value escaping: backslash, double-quote,
    and line-feed (in that order — backslash first, or the escapes
    would double-escape). Round-trips through
    sources.openmetrics.parse_exposition."""
    return (v.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def exemplar_clause_for(m: InterMetric, exemplars, exemplified) -> str:
    """The OpenMetrics exemplar clause for one exposition line, or ''.
    Shared contract with the Cortex sink: COUNTER lines only (exemplars
    on gauges are invalid OpenMetrics), at most one line per exemplar
    BASE name (`exemplified` accumulates across the flush), and a
    suffix-resolved exemplar attaches only to its `.bucket` family —
    rendered cumulative smallest-le first, so the first bucket whose
    bound contains the value (for_series' le check) is the tightest,
    per the OpenMetrics contract. An exact-name entry (a heavy-hitter
    counter) attaches to its own line."""
    if exemplars is None or m.type != MetricType.COUNTER:
        return ""
    from veneur_tpu.trace.store import exemplar_base
    base = exemplar_base(m.name)
    if base in exemplified:
        return ""
    if base != m.name and m.name != base + ".bucket":
        return ""
    try:
        clause = exemplars(m.name, m.tags) or ""
    except Exception:
        return ""
    if clause:
        exemplified.add(base)
    return clause


def render_exposition(metrics: List[InterMetric],
                      exemplars=None, openmetrics: bool = False) -> str:
    """Prometheus text exposition; with an exemplar source (the
    self-trace plane's `exemplar_for`, trace/store.py) counter lines
    gain the OpenMetrics exemplar clause
    `... # {trace_id="..."} value ts` per exemplar_clause_for's
    one-per-family tightest-bucket rules. `openmetrics` switches
    timestamp units: text 0.0.4 stamps milliseconds, OpenMetrics
    stamps seconds."""
    lines = []
    exemplified = set()
    for m in metrics:
        if m.type == MetricType.STATUS:
            continue
        labels = []
        for t in m.tags:
            k, _, v = t.partition(":")
            labels.append(f'{sanitize_label(k)}="{escape_label_value(v)}"')
        label_str = "{" + ",".join(labels) + "}" if labels else ""
        clause = exemplar_clause_for(m, exemplars, exemplified)
        # backfilled series (WAL replay of a historical interval) carry
        # an explicit exposition timestamp — their value belongs to the
        # ORIGINAL interval, not scrape time. Live series stay
        # timestamp-free, the usual exposition contract.
        if m.backfilled:
            stamp = (f" {int(m.timestamp)}" if openmetrics
                     else f" {int(m.timestamp) * 1000}")
        else:
            stamp = ""
        lines.append(f"{sanitize_name(m.name)}{label_str} {m.value}"
                     f"{stamp}{clause}")
    return "\n".join(lines) + ("\n" if lines else "")


class PrometheusMetricSink(MetricSink):
    def __init__(self, name: str, repeater_address: str = "",
                 network: str = "udp", expose_address: str = ""):
        self._name = name
        self.repeater_address = repeater_address
        self.network = network
        self.expose_address = expose_address
        # plain 0.0.4 is pre-rendered per flush (the common scrape);
        # the OpenMetrics variant (exemplar clauses + EOF) renders
        # LAZILY on the first openmetrics-negotiated scrape and is
        # cached until the next flush — a mid-line `#` would break
        # 0.0.4 parsers, and most deployments never request OM
        self._exposition = ""
        self._exposition_om: Optional[str] = None
        self._om_metrics: List[InterMetric] = []
        self._om_batch = None  # FlushBatch behind the lazy OM render
        self._renderer = None  # PrometheusColumnarRenderer, built lazily
        self._lock = threading.Lock()
        self._httpd: Optional[ThreadingHTTPServer] = None
        # OpenMetrics exemplars: the owning server's self-trace plane
        # (captured in start()) annotates matching exposition lines
        # with the interval trace that produced the value
        self._exemplars = None

    def name(self) -> str:
        return self._name

    def kind(self) -> str:
        return "prometheus"

    def start(self, server) -> None:
        self.bind_server(server)
        plane = getattr(server, "trace_plane", None)
        if plane is not None:
            self._exemplars = plane.exemplar_for
        if not self.expose_address:
            return
        host, _, port = self.expose_address.rpartition(":")
        sink = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_GET(self):  # noqa: N802
                want_om = "openmetrics" in (self.headers.get("Accept")
                                            or "")
                body = (sink.exposition_openmetrics() if want_om
                        else sink.exposition_plain()).encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    "application/openmetrics-text; version=1.0.0; "
                    "charset=utf-8" if want_om
                    else "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host or "127.0.0.1", int(port)),
                                          Handler)
        threading.Thread(target=self._httpd.serve_forever,
                         name="prometheus-expose", daemon=True).start()

    @property
    def expose_port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else 0

    def exposition_plain(self) -> str:
        with self._lock:
            return self._exposition

    def exposition_openmetrics(self) -> str:
        """The OM variant for the last flush, rendered on first demand
        and cached until the next flush invalidates it."""
        with self._lock:
            if self._exposition_om is None:
                if self._om_batch is not None:
                    self._exposition_om = self._columnar_renderer().render(
                        self._om_batch, exemplars=self._exemplars,
                        openmetrics=True) + "# EOF\n"
                else:
                    self._exposition_om = render_exposition(
                        self._om_metrics, exemplars=self._exemplars,
                        openmetrics=True) + "# EOF\n"
            return self._exposition_om

    def _columnar_renderer(self):
        if self._renderer is None:
            from veneur_tpu.core.egress import PrometheusColumnarRenderer
            self._renderer = PrometheusColumnarRenderer()
        return self._renderer

    def flush_batch(self, batch) -> None:
        if self.repeater_address:
            # the repeater re-emits per-metric statsd lines, which wants
            # the object list anyway — no columnar win to chase there
            self.flush(batch.materialize())
            return
        try:
            self.flush_columnar(batch)
        except Exception:
            logger.exception("prometheus columnar flush failed; "
                             "falling back to materialize()")
            self.flush(batch.materialize())

    def flush_columnar(self, batch) -> None:
        """Columnar fast path: render the plain 0.0.4 exposition straight
        from the FlushBatch arrays (byte-identical to render_exposition
        over materialize()), and park the batch so the lazy OpenMetrics
        variant renders columnar too on first negotiated scrape."""
        import time as _time

        t0 = _time.perf_counter()
        plain = self._columnar_renderer().render(batch)
        encode_s = _time.perf_counter() - t0
        with self._lock:
            self._exposition = plain
            self._om_metrics = []
            self._om_batch = batch
            self._exposition_om = None
        self.note_egress(encode_s, 0.0)

    def flush(self, metrics: List[InterMetric]) -> None:
        import time as _time

        t0 = _time.perf_counter()
        plain = render_exposition(metrics)
        encode_s = _time.perf_counter() - t0
        with self._lock:
            self._exposition = plain
            self._om_metrics = metrics
            self._om_batch = None
            self._exposition_om = None
        if not self.repeater_address or not metrics:
            self.note_egress(encode_s, 0.0, encoder="legacy")
            return
        t1 = _time.perf_counter()
        host, _, port = self.repeater_address.rpartition(":")
        lines = []
        for m in metrics:
            if m.type == MetricType.STATUS:
                continue
            kind = "c" if m.type == MetricType.COUNTER else "g"
            lines.append(render_metric_packet(
                m.name, m.value, kind, list(m.tags)))
        payload = b"\n".join(lines)
        try:
            if self.network == "tcp":
                with socket.create_connection((host, int(port)),
                                              timeout=5.0) as s:
                    s.sendall(payload + b"\n")
            else:
                s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                try:  # chunk to stay under typical datagram limits
                    for i in range(0, len(lines), 25):
                        s.sendto(b"\n".join(lines[i:i + 25]),
                                 (host, int(port)))
                finally:
                    s.close()
        except OSError as e:
            logger.error("prometheus repeater send failed: %s", e)
        self.note_egress(encode_s, _time.perf_counter() - t1,
                         encoder="legacy")

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()


@register_metric_sink("prometheus")
def _factory(sink_config, server_config):
    c = sink_config.config
    return PrometheusMetricSink(
        sink_config.name or "prometheus",
        repeater_address=c.get("repeater_address", ""),
        network=c.get("network_type", "udp"),
        expose_address=c.get("expose_address", ""))
