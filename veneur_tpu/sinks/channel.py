"""Channel sink: delivers each flush into a queue the test reads — the
universal flush observer (pattern from reference server_test.go:183-216)."""

from __future__ import annotations

import queue
from typing import List, Optional

from veneur_tpu.samplers.metrics import InterMetric
from veneur_tpu.sinks import MetricSink, SpanSink, register_metric_sink


class ChannelMetricSink(MetricSink):
    def __init__(self, name: str = "channel", q: Optional[queue.Queue] = None):
        self._name = name
        self.queue: queue.Queue = q if q is not None else queue.Queue()

    def name(self) -> str:
        return self._name

    def kind(self) -> str:
        return "channel"

    def flush(self, metrics: List[InterMetric]) -> None:
        self.queue.put(list(metrics))

    def wait_flush(self, timeout: float = 5.0) -> List[InterMetric]:
        return self.queue.get(timeout=timeout)

    def drain(self) -> List[InterMetric]:
        """Non-blocking: every metric from every flush delivered so far."""
        out: List[InterMetric] = []
        while True:
            try:
                out.extend(self.queue.get_nowait())
            except queue.Empty:
                return out


class ChannelSpanSink(SpanSink):
    def __init__(self, name: str = "channel_span", q: Optional[queue.Queue] = None):
        self._name = name
        self.queue: queue.Queue = q if q is not None else queue.Queue()
        self.spans: List = []

    def name(self) -> str:
        return self._name

    def ingest(self, span) -> None:
        self.spans.append(span)

    def flush(self) -> None:
        self.queue.put(list(self.spans))
        self.spans = []


@register_metric_sink("channel")
def _factory(sink_config, server_config):
    return ChannelMetricSink(sink_config.name or "channel")
