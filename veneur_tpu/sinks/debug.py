"""Debug sink: logs every metric/span (reference sinks/debug/debug.go)."""

from __future__ import annotations

import logging

from veneur_tpu.sinks import MetricSink, SpanSink, register_metric_sink, register_span_sink

logger = logging.getLogger("veneur_tpu.sinks.debug")


class DebugMetricSink(MetricSink):
    def __init__(self, name: str = "debug"):
        self._name = name
        self.flushed_total = 0

    def name(self) -> str:
        return self._name

    def kind(self) -> str:
        return "debug"

    def flush(self, metrics) -> None:
        self.flushed_total += len(metrics)
        for metric in metrics:
            logger.info(
                "flushed metric name=%s value=%s type=%s tags=%s ts=%d",
                metric.name, metric.value, metric.type.name, metric.tags,
                metric.timestamp)

    def flush_other_samples(self, samples) -> None:
        for s in samples:
            logger.info("flushed other sample %r", s)


class DebugSpanSink(SpanSink):
    def __init__(self, name: str = "debug"):
        self._name = name
        self.ingested_total = 0

    def name(self) -> str:
        return self._name

    def ingest(self, span) -> None:
        self.ingested_total += 1
        logger.info("ingested span %r", span)


@register_metric_sink("debug")
def _metric_factory(sink_config, server_config):
    return DebugMetricSink(sink_config.name or "debug")


@register_span_sink("debug")
def _span_factory(sink_config, server_config):
    return DebugSpanSink(sink_config.name or "debug")
