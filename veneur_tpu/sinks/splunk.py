"""Splunk sink: spans to a HTTP Event Collector (HEC).

Behavioral parity with reference sinks/splunk/splunk.go (577 LoC): each
ingested span becomes one HEC event (newline-delimited JSON) on a
buffered submission channel; flushes batch-POST to
/services/collector/event with the `Splunk <token>` auth header.
Sampling keeps 1/N of traces by trace id, but *indicator* spans are
always kept (splunk.go's sampling rule).
"""

from __future__ import annotations

import json
import logging
import queue
import threading
from typing import List

from veneur_tpu.sinks import SpanSink, register_span_sink
from veneur_tpu.util import http as vhttp

logger = logging.getLogger("veneur_tpu.sinks.splunk")


def span_to_hec_event(span, host: str, index: str) -> dict:
    duration_ns = max(span.end_timestamp - span.start_timestamp, 0)
    return {
        "time": span.start_timestamp / 1e9,
        "host": host,
        "index": index,
        "sourcetype": span.service or "veneur",
        "event": {
            "trace_id": format(span.trace_id & ((1 << 64) - 1), "x"),
            "id": format(span.id & ((1 << 64) - 1), "x"),
            "parent_id": format(span.parent_id & ((1 << 64) - 1), "x"),
            "name": span.name,
            "service": span.service,
            "start_timestamp": span.start_timestamp,
            "end_timestamp": span.end_timestamp,
            "duration_ns": duration_ns,
            "error": bool(span.error),
            "indicator": bool(span.indicator),
            "tags": dict(span.tags),
        },
    }


class SplunkSpanSink(SpanSink):
    def __init__(self, name: str, hec_address: str, token: str,
                 hostname: str, index: str = "",
                 sample_rate: int = 1, max_buffer: int = 16_384,
                 timeout: float = 10.0, batch_size: int = 0,
                 submission_workers: int = 1):
        self._name = name
        self.url = hec_address.rstrip("/") + "/services/collector/event"
        self.token = token
        self.hostname = hostname
        self.index = index
        self.sample_rate = max(1, sample_rate)
        self.max_buffer = max_buffer
        self.timeout = timeout
        # hec_batch_size splits a flush into bodies of at most N events;
        # hec_submission_workers POST those bodies in parallel (reference
        # splunk.go:183-196's worker pool). The pool is persistent daemon
        # threads: per-flush executors would churn threads, and
        # non-daemon workers would block interpreter exit behind a hung
        # POST.
        self.batch_size = batch_size
        self.submission_workers = max(1, submission_workers)
        self._events: List[dict] = []
        self._lock = threading.Lock()
        self.dropped = 0
        # submissions that completed after their own flush's accounting
        # deadline: credited into the NEXT flush's sent/drop totals so
        # late deliveries are not over-reported as drops
        self._late_sent = 0
        self._late_failed = 0
        self._work_q: queue.Queue = queue.Queue()
        if self.submission_workers > 1:
            for i in range(self.submission_workers):
                threading.Thread(
                    target=self._worker_loop, daemon=True,
                    name=f"splunk-hec-{name}-{i}").start()

    def _worker_loop(self) -> None:
        while True:
            fn = self._work_q.get()
            try:
                fn()
            except Exception:
                logger.exception("splunk HEC worker task failed")
            finally:
                self._work_q.task_done()

    def name(self) -> str:
        return self._name

    def kind(self) -> str:
        return "splunk"

    def ingest(self, span) -> None:
        # indicator spans always submit; others sample by trace id
        if not span.indicator and self.sample_rate > 1 \
                and span.trace_id % self.sample_rate != 0:
            return
        event = span_to_hec_event(span, self.hostname, self.index)
        with self._lock:
            if len(self._events) >= self.max_buffer:
                self.dropped += 1
                return
            self._events.append(event)

    def flush(self) -> None:
        import time as _time

        flush_start = _time.perf_counter()
        reportable = getattr(self, "_statsd", None) is not None
        with self._lock:
            events, self._events = self._events, []
            # reset only when the count can actually be reported, so an
            # unreportable interval's drops aren't silently discarded
            dropped = 0
            if reportable and self.dropped:
                dropped, self.dropped = self.dropped, 0
        # late completions from a prior flush's in-flight batches are
        # drained on every flush — including empty ones, else a quiet
        # tail would leave them unreported forever
        with self._lock:
            late_sent, self._late_sent = self._late_sent, 0
            late_failed, self._late_failed = self._late_failed, 0
        if not events:
            self.emit_flush_self_metrics(
                late_sent, flush_start, dropped + late_failed)
            return
        per = self.batch_size or len(events)
        batches = [events[i:i + per] for i in range(0, len(events), per)]
        sent = [0]
        failed = [0]
        accounted = [False]  # set once this flush's totals are emitted
        sent_lock = threading.Lock()

        def submit(batch: List[dict]) -> None:
            body = "\n".join(json.dumps(e, separators=(",", ":"))
                             for e in batch).encode()
            try:
                vhttp.post(
                    self.url, body, content_type="application/json",
                    headers={"Authorization": f"Splunk {self.token}"},
                    timeout=self.timeout)
                with sent_lock:
                    if accounted[0]:
                        with self._lock:
                            self._late_sent += len(batch)
                    else:
                        sent[0] += len(batch)
            except Exception as e:
                logger.error("splunk HEC POST failed: %s", e)
                with sent_lock:
                    if accounted[0]:
                        with self._lock:
                            self._late_failed += len(batch)
                    else:
                        failed[0] += len(batch)

        if self.submission_workers > 1 and len(batches) > 1:
            done = threading.Event()
            finished = [0]

            def task(batch: List[dict]):
                def run() -> None:
                    try:
                        submit(batch)
                    finally:
                        with sent_lock:
                            finished[0] += 1
                            if finished[0] == len(batches):
                                done.set()
                return run

            for batch in batches:
                self._work_q.put(task(batch))
            # bounded wait: a hung POST must not also hang the flush
            if not done.wait(timeout=self.timeout * 2):
                with sent_lock:
                    pending = len(batches) - finished[0]
                logger.warning(
                    "%d splunk HEC submissions still in flight at "
                    "flush accounting time", pending)
        else:
            for batch in batches:
                submit(batch)
        # failed batches' events are gone and count as drops; batches
        # still in flight at the deadline are NOT drops — their submits
        # credit _late_sent/_late_failed and land in a later flush's
        # totals (the workers may well deliver them after this point)
        with sent_lock:
            accounted[0] = True
            self.emit_flush_self_metrics(
                sent[0] + late_sent, flush_start,
                dropped + failed[0] + late_failed)


@register_span_sink("splunk")
def _factory(sink_config, server_config):
    c = sink_config.config
    from veneur_tpu.config import parse_duration

    # hec_max_connection_lifetime / hec_connection_lifetime_jitter tune
    # the reference transport's connection recycling and
    # hec_tls_validate_hostname pins the TLS name; this reporter opens a
    # fresh connection per submission, so those knobs are accepted for
    # config compatibility with nothing to recycle or re-pin
    timeout = parse_duration(c.get("hec_ingest_timeout", 0) or 0) or 10.0
    return SplunkSpanSink(
        sink_config.name or "splunk",
        hec_address=c.get("hec_address", ""),
        token=str(c.get("hec_token", "")),
        hostname=server_config.hostname,
        index=c.get("hec_index", ""),
        sample_rate=int(c.get("span_sample_rate", 1)),
        max_buffer=int(c.get("hec_max_buffer", 16_384)),
        timeout=timeout,
        batch_size=int(c.get("hec_batch_size", 0)),
        submission_workers=int(c.get("hec_submission_workers", 1)))
