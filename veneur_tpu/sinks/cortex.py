"""Cortex sink: Prometheus remote-write.

Behavioral parity with reference sinks/cortex/cortex.go (464 LoC):
InterMetrics -> prometheus WriteRequest protobuf, snappy-compressed,
POSTed with X-Prometheus-Remote-Write-Version headers and optional
basic/bearer auth. Metric and label names sanitize to the Prometheus
charset ([a-zA-Z_:][a-zA-Z0-9_:]*), duplicate labels keep the last value.

The WriteRequest message is hand-encoded protobuf wire format (the schema
is 5 tiny messages; no codegen needed):
  WriteRequest{ repeated TimeSeries timeseries = 1 }
  TimeSeries{ repeated Label labels = 1; repeated Sample samples = 2;
              repeated Exemplar exemplars = 3 }
  Label{ string name = 1; string value = 2 }
  Sample{ double value = 1; int64 timestamp = 2 }  # ms
  Exemplar{ repeated Label labels = 1; double value = 2;
            int64 timestamp = 3 }  # ms

Exemplars carry the cross-tier self-trace plane's per-series
`(trace_id, raw value, timestamp)` (trace/store.py) as a
`trace_id` exemplar label — the native remote-write form of the
OpenMetrics `# {trace_id="..."}` clause the text sinks render.
"""

from __future__ import annotations

import base64
import logging
import re
import struct
from typing import Dict, List, Sequence, Tuple

from veneur_tpu.samplers.metrics import InterMetric, MetricType
from veneur_tpu.sinks import MetricSink, register_metric_sink
from veneur_tpu.util import http as vhttp

logger = logging.getLogger("veneur_tpu.sinks.cortex")

_INVALID_NAME = re.compile(r"[^a-zA-Z0-9_:]")
_INVALID_LABEL = re.compile(r"[^a-zA-Z0-9_]")


def sanitize_name(name: str) -> str:
    out = _INVALID_NAME.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def sanitize_label(name: str) -> str:
    out = _INVALID_LABEL.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


# -- protobuf wire helpers -------------------------------------------------

def _varint(n: int) -> bytes:
    out = bytearray()
    while n >= 0x80:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)
    return bytes(out)


def _field_bytes(tag: int, payload: bytes) -> bytes:
    return _varint((tag << 3) | 2) + _varint(len(payload)) + payload


def _encode_label(name: str, value: str) -> bytes:
    return (_field_bytes(1, name.encode()) +
            _field_bytes(2, value.encode()))


def _encode_sample(value: float, timestamp_ms: int) -> bytes:
    # fixed64 double field 1, varint int64 field 2
    body = bytes([(1 << 3) | 1]) + struct.pack("<d", value)
    body += bytes([2 << 3]) + _varint(timestamp_ms & ((1 << 64) - 1))
    return body


def _encode_exemplar(trace_id_hex: str, value: float,
                     ts_ms: int) -> bytes:
    body = _field_bytes(1, _encode_label("trace_id", trace_id_hex))
    body += bytes([(2 << 3) | 1]) + struct.pack("<d", value)
    body += bytes([3 << 3]) + _varint(ts_ms & ((1 << 64) - 1))
    return body


def encode_write_request(series: Sequence[tuple]) -> bytes:
    """series: [(labels, value, timestamp_ms)] or
    [(labels, value, timestamp_ms, (trace_id_hex, exemplar_value,
    exemplar_ts_ms))] -> WriteRequest bytes."""
    out = bytearray()
    for entry in series:
        labels, value, ts_ms = entry[0], entry[1], entry[2]
        exemplar = entry[3] if len(entry) > 3 else None
        ts_body = bytearray()
        for name, value_str in labels:
            ts_body += _field_bytes(1, _encode_label(name, value_str))
        ts_body += _field_bytes(2, _encode_sample(value, ts_ms))
        if exemplar is not None:
            ts_body += _field_bytes(3, _encode_exemplar(*exemplar))
        out += _field_bytes(1, bytes(ts_body))
    return bytes(out)


def decode_write_request(data: bytes):
    """Minimal decoder for tests/fakes: returns [(labels_dict, value, ts)]."""
    def read_fields(buf):
        pos = 0
        while pos < len(buf):
            tag_wire = 0
            shift = 0
            while True:
                b = buf[pos]
                pos += 1
                tag_wire |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            tag, wire = tag_wire >> 3, tag_wire & 7
            if wire == 2:
                ln = 0
                shift = 0
                while True:
                    b = buf[pos]
                    pos += 1
                    ln |= (b & 0x7F) << shift
                    if not b & 0x80:
                        break
                    shift += 7
                yield tag, buf[pos:pos + ln]
                pos += ln
            elif wire == 0:
                v = 0
                shift = 0
                while True:
                    b = buf[pos]
                    pos += 1
                    v |= (b & 0x7F) << shift
                    if not b & 0x80:
                        break
                    shift += 7
                yield tag, v
            elif wire == 1:
                yield tag, buf[pos:pos + 8]
                pos += 8
            else:
                raise ValueError(f"unsupported wire type {wire}")

    result = []
    for tag, ts_buf in read_fields(data):
        assert tag == 1
        labels: Dict[str, str] = {}
        value, ts = 0.0, 0
        for ftag, fval in read_fields(ts_buf):
            if ftag == 1:
                fields = dict(read_fields(fval))
                labels[fields[1].decode()] = fields[2].decode()
            elif ftag == 2:
                for stag, sval in read_fields(fval):
                    if stag == 1:
                        value = struct.unpack("<d", sval)[0]
                    elif stag == 2:
                        ts = sval
        result.append((labels, value, ts))
    return result


class CortexMetricSink(MetricSink):
    def __init__(self, name: str, url: str, hostname: str,
                 auth_token: str = "", basic_auth: Tuple[str, str] = ("", ""),
                 batch_write_size: int = 0, timeout: float = 30.0,
                 excluded_tags: Sequence[str] = (),
                 proxy_url: str = "",
                 convert_counters_to_monotonic: bool = False):
        self._name = name
        self.url = url
        self.hostname = hostname
        self.timeout = timeout
        self.batch_write_size = batch_write_size
        self.excluded_tags = set(excluded_tags)
        # HTTP(S) proxy for the remote-write transport (cortex.go:176-183)
        self.proxy_url = proxy_url
        # monotonic mode: counter deltas accumulate across flushes per
        # (name, sorted tags, hostname) and every flush re-emits the
        # running totals as Prometheus-style monotonic series
        # (cortex.go:337-363; like the reference, entries live for the
        # process lifetime — high-churn tag sets grow the map)
        self.convert_counters_to_monotonic = convert_counters_to_monotonic
        self._monotonic: Dict[Tuple[str, Tuple[str, ...], str], float] = {}
        self._exemplars = None  # ExemplarStore, bound in start()
        self._encoder = None    # CortexColumnarEncoder, built lazily
        self.headers = {
            "Content-Encoding": "snappy",
            "X-Prometheus-Remote-Write-Version": "0.1.0",
            "User-Agent": "veneur-tpu/cortex",
        }
        if auth_token:
            self.headers["Authorization"] = f"Bearer {auth_token}"
        elif basic_auth[0]:
            cred = base64.b64encode(
                f"{basic_auth[0]}:{basic_auth[1]}".encode()).decode()
            self.headers["Authorization"] = f"Basic {cred}"

    def name(self) -> str:
        return self._name

    def kind(self) -> str:
        return "cortex"

    def start(self, server) -> None:
        self.bind_server(server)
        # self-trace exemplars (trace/store.py): per-series
        # (trace_id, value, ts) riding the remote-write TimeSeries
        plane = getattr(server, "trace_plane", None)
        self._exemplars = getattr(plane, "exemplars", None)

    def _exemplar_entry(self, m: InterMetric, exemplified: set):
        """Same attachment contract as the Prometheus sink
        (sinks/prometheus.py exemplar_clause_for): COUNTER series only,
        one per exemplar base name per write, suffix-resolved entries
        only on their `.bucket` family (tightest containing bucket:
        buckets emit smallest-le first and for_series checks the
        bound), exact-name entries on their own line."""
        if self._exemplars is None or m.type != MetricType.COUNTER:
            return None
        from veneur_tpu.trace.store import exemplar_base
        base = exemplar_base(m.name)
        if base in exemplified:
            return None
        if base != m.name and m.name != base + ".bucket":
            return None
        entry = self._exemplars.for_series(m.name, m.tags)
        if entry is not None:
            exemplified.add(base)
        return entry

    def _series(self, m: InterMetric):
        labels: Dict[str, str] = {"__name__": sanitize_name(m.name)}
        for t in m.tags:
            k, _, v = t.partition(":")
            if k in self.excluded_tags:
                continue
            labels[sanitize_label(k)] = v  # last write wins on dupes
        if m.hostname or self.hostname:
            labels.setdefault("host", m.hostname or self.hostname)
        ordered = sorted(labels.items())
        return ordered, float(m.value), m.timestamp * 1000

    def flush(self, metrics: List[InterMetric]) -> None:
        import time as _time

        t0 = _time.perf_counter()
        series = []
        exemplified = set()
        max_ts = 0  # folded into the encode pass (no second scan)
        for m in metrics:
            if m.timestamp > max_ts:
                max_ts = m.timestamp
            if m.type == MetricType.STATUS:
                continue
            if (m.type == MetricType.COUNTER
                    and self.convert_counters_to_monotonic):
                key = (m.name, tuple(sorted(m.tags)), m.hostname)
                self._monotonic[key] = (
                    self._monotonic.get(key, 0.0) + float(m.value))
                continue
            row = self._series(m)
            entry = self._exemplar_entry(m, exemplified)
            if entry is not None:
                from veneur_tpu.trace.store import trace_id_hex
                tid, ev, ets = entry
                row = row + ((trace_id_hex(tid), float(ev),
                              int(ets * 1000)),)
            series.append(row)
        if self.convert_counters_to_monotonic:
            series.extend(self._monotonic_series(max_ts))
        if not series:
            return
        encode_s = _time.perf_counter() - t0
        t1 = _time.perf_counter()
        batch = self.batch_write_size or len(series)
        for i in range(0, len(series), batch):
            self._post_body(vhttp.snappy_encode(
                encode_write_request(series[i:i + batch])))
        self.note_egress(encode_s, _time.perf_counter() - t1,
                         encoder="legacy")

    def _monotonic_series(self, max_ts: int) -> List[tuple]:
        """Re-emit the running monotonic totals, stamped with the
        flush's own metric timestamp so they align with the gauges in
        the same remote-write batch; wall clock only when the flush
        carried no timestamped metrics at all."""
        import time as _time

        stamp = max_ts or int(_time.time())
        return [self._series(InterMetric(
            name=mname, timestamp=stamp, value=total,
            tags=list(tags), type=MetricType.COUNTER, hostname=mhost))
            for (mname, tags, mhost), total in self._monotonic.items()]

    def _post_body(self, body: bytes) -> None:
        try:
            vhttp.post(self.url, body,
                       content_type="application/x-protobuf",
                       headers=self.headers, timeout=self.timeout,
                       proxy_url=self.proxy_url)
        except Exception as e:
            logger.error("cortex remote write failed: %s", e)

    def flush_batch(self, batch) -> None:
        try:
            self.flush_columnar(batch)
        except Exception:
            logger.exception("cortex columnar flush failed; "
                             "falling back to materialize()")
            self.flush(batch.materialize())

    def flush_columnar(self, batch) -> None:
        """Columnar fast path: TimeSeries frames hand-packed from the
        FlushBatch arrays (core/egress.py); concatenated frame chunks
        are byte-identical to encode_write_request over the legacy
        series list, so chunking/snappy/POST are unchanged."""
        import time as _time

        from veneur_tpu.core.egress import CortexColumnarEncoder

        t0 = _time.perf_counter()
        enc = self._encoder
        if enc is None:
            enc = self._encoder = CortexColumnarEncoder(self)
        frames, max_ts = enc.encode(batch)
        if self.convert_counters_to_monotonic:
            frames.extend(encode_write_request([row])
                          for row in self._monotonic_series(max_ts))
        if not frames:
            return
        encode_s = _time.perf_counter() - t0
        t1 = _time.perf_counter()
        size = self.batch_write_size or len(frames)
        for i in range(0, len(frames), size):
            self._post_body(vhttp.snappy_encode(
                b"".join(frames[i:i + size])))
        self.note_egress(encode_s, _time.perf_counter() - t1)


@register_metric_sink("cortex")
def _factory(sink_config, server_config):
    c = sink_config.config
    auth = c.get("authorization", {}) or {}
    basic = c.get("basic_auth", {}) or {}
    return CortexMetricSink(
        sink_config.name or "cortex",
        url=c.get("url", ""),
        hostname=server_config.hostname,
        auth_token=str(auth.get("credentials", "")),
        basic_auth=(str(basic.get("username", "")),
                    str(basic.get("password", ""))),
        batch_write_size=int(c.get("batch_write_size", 0)),
        timeout=float(c.get("remote_timeout", 30.0)),
        excluded_tags=c.get("excluded_tags", []) or [],
        proxy_url=c.get("proxy_url", ""),
        convert_counters_to_monotonic=bool(
            c.get("convert_counters_to_monotonic", False)))
