"""Kafka sink: metrics and spans to Kafka topics.

Behavioral parity with reference sinks/kafka/kafka.go (449 LoC): an async
producer publishes each flushed InterMetric (and/or each ingested span)
to configured topics, encoded as JSON or protobuf, with optional
partition keying by metric name and span sampling by trace id.

The reference embeds sarama; here the producer is a small pluggable
transport (`Producer`) so the sink logic — encoding, topics, sampling —
is identical whether backed by a real client (`kafka-python` if
installed), a spool file, or the in-memory producer tests use.
"""

from __future__ import annotations

import json
import logging
import random
import threading
from typing import Any, List, Optional

from veneur_tpu.samplers.metrics import InterMetric, MetricType
from veneur_tpu.sinks import (
    MetricSink, SpanSink, register_metric_sink, register_span_sink,
)

logger = logging.getLogger("veneur_tpu.sinks.kafka")


class Producer:
    """Transport boundary: send(topic, key, value) then flush()."""

    def send(self, topic: str, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def flush(self) -> None:  # noqa: B027
        pass

    def close(self) -> None:  # noqa: B027
        pass


class InMemoryProducer(Producer):
    """Test producer: records (topic, key, value) tuples."""

    def __init__(self):
        self.messages: List[tuple] = []
        self._lock = threading.Lock()

    def send(self, topic: str, key: bytes, value: bytes) -> None:
        with self._lock:
            self.messages.append((topic, key, value))


class ProducerConfig:
    """Producer tuning with the reference's sarama semantics
    (sinks/kafka/kafka.go:142-187): ack level all/none/local,
    hash-or-random partitioning, bounded retries, and byte/message/time
    flush triggers."""

    def __init__(self, require_acks: str = "all", partitioner: str = "hash",
                 retry_max: int = 3, buffer_bytes: int = 0,
                 buffer_messages: int = 0, buffer_frequency_s: float = 0.0):
        if require_acks not in ("all", "none", "local"):
            logger.warning("unknown ack requirement %r, defaulting to all",
                           require_acks)
            require_acks = "all"
        if partitioner not in ("hash", "random"):
            partitioner = "hash"
        self.require_acks = require_acks
        self.partitioner = partitioner
        self.retry_max = retry_max
        self.buffer_bytes = buffer_bytes
        self.buffer_messages = buffer_messages
        self.buffer_frequency_s = buffer_frequency_s

    @classmethod
    def from_config(cls, c: dict, prefix: str) -> "ProducerConfig":
        """Reads the reference's yaml keys: metric_require_acks /
        span_require_acks, partitioner, retry_max, metric_buffer_bytes /
        metric_buffer_messages / metric_buffer_frequency and the span_
        equivalents (span_buffer_bytes, span_buffer_frequency,
        span_buffer_mesages — the reference's spelling)."""
        from veneur_tpu.config import parse_duration
        freq = c.get(f"{prefix}_buffer_frequency", 0)
        return cls(
            require_acks=c.get(f"{prefix}_require_acks", "all"),
            partitioner=c.get("partitioner", "hash"),
            retry_max=int(c.get("retry_max", c.get("retries", 3))),
            buffer_bytes=int(c.get(f"{prefix}_buffer_bytes", 0)),
            buffer_messages=int(c.get(f"{prefix}_buffer_messages",
                                      # reference spells this one
                                      # "span_buffer_mesages" (sic)
                                      c.get(f"{prefix}_buffer_mesages", 0))),
            buffer_frequency_s=parse_duration(freq) if freq else 0.0)

    def kafka_python_kwargs(self) -> dict:
        kw: dict = {
            "acks": {"all": "all", "none": 0, "local": 1}[self.require_acks],
            "retries": self.retry_max,
        }
        if self.buffer_bytes:
            kw["batch_size"] = self.buffer_bytes
        if self.buffer_frequency_s:
            kw["linger_ms"] = int(self.buffer_frequency_s * 1000)
        if self.partitioner == "random":
            def _random_partitioner(key, all_parts, available):
                return random.choice(available or all_parts)

            kw["partitioner"] = _random_partitioner
        return kw


class KafkaPythonProducer(Producer):
    """Real transport via kafka-python, when available."""

    def __init__(self, brokers: str, config: Optional[ProducerConfig] = None):
        from kafka import KafkaProducer  # gated import
        self._cfg = config or ProducerConfig()
        self._p = KafkaProducer(bootstrap_servers=brokers.split(","),
                                **self._cfg.kafka_python_kwargs())

    def send(self, topic: str, key: bytes, value: bytes) -> None:
        # sarama's Flush.Messages (buffer_messages) is an async batching
        # trigger, not a blocking flush — kafka-python's own batch_size/
        # linger_ms batching already plays that role, and even a
        # 100ms-bounded flush() here would insert caller-thread stalls
        # into the span/metric flush path whenever the broker is slow.
        # Delivery is guaranteed by the interval flush() below.
        self._p.send(topic, key=key or None, value=value)

    def flush(self) -> None:
        self._p.flush(timeout=10)

    def close(self) -> None:
        self._p.close()


def make_producer(brokers: str,
                  config: Optional[ProducerConfig] = None,
                  ) -> Optional[Producer]:
    try:
        return KafkaPythonProducer(brokers, config)
    except ImportError:
        logger.error("kafka-python not installed; kafka sink will drop "
                     "(configure an explicit producer for tests)")
        return None
    except Exception as e:
        logger.error("kafka producer connect failed: %s", e)
        return None


def encode_metric_json(m: InterMetric) -> bytes:
    return json.dumps({
        "name": m.name,
        "timestamp": m.timestamp,
        "value": m.value,
        "tags": m.tags,
        "type": m.type.name.lower(),
        "hostname": m.hostname,
    }, separators=(",", ":")).encode()


def encode_span_protobuf(span) -> bytes:
    return span.SerializeToString()


def encode_span_json(span) -> bytes:
    return json.dumps({
        "trace_id": span.trace_id, "id": span.id,
        "parent_id": span.parent_id, "service": span.service,
        "name": span.name, "start_timestamp": span.start_timestamp,
        "end_timestamp": span.end_timestamp, "error": span.error,
        "tags": dict(span.tags), "indicator": span.indicator,
    }, separators=(",", ":")).encode()


class KafkaMetricSink(MetricSink):
    def __init__(self, name: str, producer: Optional[Producer],
                 check_topic: str = "", event_topic: str = "",
                 metric_topic: str = "", partition_by_name: bool = True):
        self._name = name
        self.producer = producer
        self.metric_topic = metric_topic
        self.check_topic = check_topic
        self.event_topic = event_topic
        self.partition_by_name = partition_by_name

    def name(self) -> str:
        return self._name

    def kind(self) -> str:
        return "kafka"

    def flush(self, metrics: List[InterMetric]) -> None:
        if self.producer is None:
            return
        sent = False
        for m in metrics:
            # service checks route to check_topic (reference
            # sinks/kafka/kafka.go FlushCheck split), everything else to
            # metric_topic
            topic = (self.check_topic if m.type == MetricType.STATUS
                     else self.metric_topic)
            if not topic:
                continue
            key = m.name.encode() if self.partition_by_name else b""
            self.producer.send(topic, key, encode_metric_json(m))
            sent = True
        if sent:
            self.producer.flush()

    def flush_other_samples(self, samples) -> None:
        if self.producer is None or not self.event_topic:
            return
        for s in samples:
            body = json.dumps({
                "name": getattr(s, "name", ""),
                "message": getattr(s, "message", ""),
                "timestamp": getattr(s, "timestamp", 0),
                "tags": dict(getattr(s, "tags", {}) or {}),
            }, separators=(",", ":")).encode()
            self.producer.send(self.event_topic, b"", body)
        self.producer.flush()

    def stop(self) -> None:
        if self.producer is not None:
            self.producer.close()


class KafkaSpanSink(SpanSink):
    def __init__(self, name: str, producer: Optional[Producer],
                 span_topic: str, encoding: str = "protobuf",
                 sample_rate_percent: float = 100.0,
                 sample_tag: str = "", max_buffered: int = 16384):
        self._name = name
        self.producer = producer
        self.span_topic = span_topic
        self.encode = (encode_span_json if encoding == "json"
                       else encode_span_protobuf)
        # sampling hashes the trace id (or sample_tag value) so whole
        # traces are kept/dropped together (reference kafka.go)
        self.sample_threshold = int(sample_rate_percent * 100)
        self.sample_tag = sample_tag
        self._buffered = 0
        # backpressure bound: the reference's sarama async producer has a
        # bounded input channel; spans beyond the per-interval bound drop
        # (and are counted) instead of growing the producer buffer
        self.max_buffered = max_buffered
        self.dropped_total = 0

    def name(self) -> str:
        return self._name

    def kind(self) -> str:
        return "kafka"

    def _sampled_in(self, span) -> bool:
        if self.sample_threshold >= 100 * 100:
            return True
        if self.sample_tag:
            basis = dict(span.tags).get(self.sample_tag, "")
            if not basis:
                return False
        else:
            basis = str(span.trace_id)
        # fnv hash spreads sequential trace ids uniformly (python's int
        # hash is the identity, which would bias small-id workloads)
        from veneur_tpu.util import fnv
        return (fnv.fnv1a_32(basis.encode()) % 10_000) < self.sample_threshold

    def ingest(self, span) -> None:
        if self.producer is None or not self._sampled_in(span):
            return
        if self._buffered >= self.max_buffered:
            self.dropped_total += 1
            return
        self.producer.send(self.span_topic,
                           str(span.trace_id).encode(), self.encode(span))
        self._buffered += 1

    def flush(self) -> None:
        import time as _time

        flush_start = _time.perf_counter()
        flushed = 0
        if self.producer is not None and self._buffered:
            self.producer.flush()
            flushed, self._buffered = self._buffered, 0
        dropped = 0
        if getattr(self, "_statsd", None) is not None and self.dropped_total:
            dropped, self.dropped_total = self.dropped_total, 0
        self.emit_flush_self_metrics(flushed, flush_start, dropped)

    def stop(self) -> None:
        if self.producer is not None:
            self.producer.close()


@register_metric_sink("kafka")
def _metric_factory(sink_config, server_config):
    c = sink_config.config
    producer: Any = c.get("producer")  # tests inject one
    if producer is None:
        producer = make_producer(c.get("broker", "localhost:9092"),
                                 ProducerConfig.from_config(c, "metric"))
    return KafkaMetricSink(
        sink_config.name or "kafka",
        producer=producer,
        metric_topic=c.get("metric_topic", ""),
        check_topic=c.get("check_topic", ""),
        event_topic=c.get("event_topic", ""),
        partition_by_name=bool(c.get("partition_by_name", True)))


@register_span_sink("kafka")
def _span_factory(sink_config, server_config):
    c = sink_config.config
    producer: Any = c.get("producer")
    if producer is None:
        producer = make_producer(c.get("broker", "localhost:9092"),
                                 ProducerConfig.from_config(c, "span"))
    return KafkaSpanSink(
        sink_config.name or "kafka",
        producer=producer,
        span_topic=c.get("span_topic", "veneur_spans"),
        encoding=c.get("span_serialization_format", "protobuf"),
        sample_rate_percent=float(c.get("span_sample_rate_percent", 100.0)),
        sample_tag=c.get("span_sample_tag", ""),
        max_buffered=int(c.get("span_buffer_max", 16384)))
