"""Metric-extraction span sink: the bridge from the span pipeline back
into the aggregation path.

Parity with reference sinks/ssfmetrics/metrics.go:45-161: every ingested
span has its embedded SSFSamples converted to UDPMetrics and fed to the
column store; spans that are valid traces additionally yield SLI
indicator/objective timers (reference parser.go:180-232) and a sampled
span-name-uniqueness Set (parser.go:238-259).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, List

from veneur_tpu import protocol
from veneur_tpu.samplers.metrics import UDPMetric
from veneur_tpu.sinks import SpanSink

logger = logging.getLogger("veneur_tpu.sinks.ssfmetrics")


class MetricExtractionSink(SpanSink):
    def __init__(self, processor: Callable[[UDPMetric], None], parser,
                 indicator_timer_name: str = "",
                 objective_timer_name: str = "",
                 uniqueness_rate: float = 0.01):
        self._process = processor
        self._parser = parser
        self._indicator = indicator_timer_name
        self._objective = objective_timer_name
        self._uniqueness_rate = uniqueness_rate
        self._lock = threading.Lock()
        self.spans_processed = 0
        self.metrics_generated = 0

    def name(self) -> str:
        return "metric_extraction"

    def kind(self) -> str:
        return "metric_extraction"

    def ingest(self, span) -> None:
        generated = 0
        metrics, invalid = self._parser.convert_metrics(span)
        if invalid:
            logger.warning("could not parse %d samples from SSF span",
                           len(invalid))
        for metric in metrics:
            self._process(metric)
        generated += len(metrics)

        if protocol.valid_trace(span):
            derived: List[UDPMetric] = []
            derived.extend(self._parser.convert_indicator_metrics(
                span, self._indicator, self._objective))
            derived.extend(self._parser.convert_span_uniqueness_metrics(
                span, self._uniqueness_rate))
            for metric in derived:
                self._process(metric)
            generated += len(derived)

        with self._lock:
            self.spans_processed += 1
            self.metrics_generated += generated

    def flush(self) -> None:
        pass
