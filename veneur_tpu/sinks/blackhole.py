"""Blackhole sink: accepts and drops everything (reference
sinks/blackhole/blackhole.go). The test/benchmark baseline."""

from __future__ import annotations

from veneur_tpu.sinks import MetricSink, SpanSink, register_metric_sink, register_span_sink


class BlackholeMetricSink(MetricSink):
    def __init__(self, name: str = "blackhole"):
        self._name = name

    def name(self) -> str:
        return self._name

    def kind(self) -> str:
        return "blackhole"

    def flush(self, metrics) -> None:
        pass

    def flush_batch(self, batch) -> None:
        # columnar fast path: never materialize per-metric objects
        pass


class BlackholeSpanSink(SpanSink):
    def __init__(self, name: str = "blackhole"):
        self._name = name

    def name(self) -> str:
        return self._name

    def ingest(self, span) -> None:
        pass

    def ingest_many(self, spans) -> None:
        pass


@register_metric_sink("blackhole")
def _metric_factory(sink_config, server_config):
    return BlackholeMetricSink(sink_config.name or "blackhole")


@register_span_sink("blackhole")
def _span_factory(sink_config, server_config):
    return BlackholeSpanSink(sink_config.name or "blackhole")
