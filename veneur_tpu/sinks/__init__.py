"""Sink plugin boundary.

Interface parity with reference sinks/sinks.go:42-103: metric sinks receive
plain host-side InterMetrics per flush (the device column store is invisible
to them), span sinks ingest SSF spans one at a time and flush per interval.
Factories register by kind in MetricSinkTypes/SpanSinkTypes (reference
server.go:62-91, populated in cmd/veneur/main.go:98-170).
"""

from __future__ import annotations

import abc
import logging
from typing import Any, Callable, Dict, List, Optional, Sequence

from veneur_tpu.samplers.metrics import InterMetric

_logger = logging.getLogger("veneur_tpu.sinks")

# sink "kinds" report what they drop: a metric sink is expected to handle
# every InterMetric it receives
class MetricSink(abc.ABC):
    @abc.abstractmethod
    def name(self) -> str: ...

    @abc.abstractmethod
    def kind(self) -> str: ...

    def start(self, server) -> None:  # noqa: B027
        self.bind_server(server)

    def bind_server(self, server) -> None:
        """Capture the owning server's self-metrics client and latency
        observatory so flushes can report the encode-vs-send split
        (note_egress). Sinks that override start() call this first."""
        self._statsd = getattr(server, "statsd", None)
        self._latency = getattr(server, "latency", None)

    @abc.abstractmethod
    def flush(self, metrics: List[InterMetric]) -> None: ...

    def flush_batch(self, batch) -> None:
        """Receive a columnar FlushBatch (core/flusher.py). The default
        materializes the legacy InterMetric list (built once, shared
        across sink threads) and calls flush(); sinks that can consume
        columns directly (or discard them — blackhole) override this to
        skip object materialization entirely."""
        self.flush(batch.materialize())

    def note_egress(self, encode_s: float, send_s: float,
                    encoder: str = "columnar") -> None:
        """Report one flush's encode-vs-send split: `egress.encode_s` /
        `egress.send_s` observatory rows tagged with the sink name, plus
        span tags on the ambient `flush.sink` span so the trace
        waterfall shows whether a slow sink is CPU or network."""
        lat = getattr(self, "_latency", None)
        if lat is not None:
            try:
                lat.note_egress(self.name(), encode_s, send_s)
            except Exception:
                _logger.exception("egress latency report failed")
        try:
            from veneur_tpu.trace import context as trace_ctx
            span = trace_ctx.current_span()
            if span is not None:
                span.set_tag("egress.encoder", encoder)
                span.set_tag("egress.encode_s", f"{encode_s:.6f}")
                span.set_tag("egress.send_s", f"{send_s:.6f}")
        except Exception:
            pass

    def flush_other_samples(self, samples: Sequence[Any]) -> None:  # noqa: B027
        """Receive events/service-check samples that aren't InterMetrics."""

    def stop(self) -> None:  # noqa: B027
        pass


class SpanSink(abc.ABC):
    @abc.abstractmethod
    def name(self) -> str: ...

    def kind(self) -> str:
        return self.name()

    def start(self, server) -> None:  # noqa: B027
        # default: bind the server's self-metrics client so flush() can
        # emit the standard span-sink keys (reference sinks.go:58-67)
        self._statsd = getattr(server, "statsd", None)

    def emit_flush_self_metrics(self, flushed: int, flush_start: float,
                                dropped: int = 0) -> None:
        """Standard per-sink flush self-metrics (reference sinks.go:58-67:
        sink.spans_flushed_total / sink.span_flush_total_duration_ns,
        plus drop accounting), tagged with the sink name."""
        import time as _time

        statsd = getattr(self, "_statsd", None)
        if statsd is None or (not flushed and not dropped):
            return
        tags = [f"sink:{self.name()}"]
        if flushed:
            statsd.count("sink.spans_flushed_total", flushed, tags=tags)
        if dropped:
            statsd.count("sink.spans_dropped_total", dropped, tags=tags)
        statsd.gauge(
            "sink.span_flush_total_duration_ns",
            int((_time.perf_counter() - flush_start) * 1e9), tags=tags)

    @abc.abstractmethod
    def ingest(self, span) -> None: ...

    def ingest_many(self, spans) -> None:
        """Batch ingest: the span sink workers hand over whole decoded
        chunks, so a sink that can take spans wholesale (buffer appends,
        no-ops) overrides this and pays one Python call per chunk rather
        than per span. The default delegates per-span and isolates
        failures, so one poison span costs exactly one span (the
        pre-batching contract)."""
        for span in spans:
            try:
                self.ingest(span)
            except Exception:
                _logger.exception("span sink %s ingest failed",
                                  self.name())

    def flush(self) -> None:  # noqa: B027
        pass

    def stop(self) -> None:  # noqa: B027
        pass


# kind -> factory(config: SinkConfig, server_config: Config) -> sink
MetricSinkTypes: Dict[str, Callable] = {}
SpanSinkTypes: Dict[str, Callable] = {}


def register_metric_sink(kind: str):
    def deco(factory):
        MetricSinkTypes[kind] = factory
        return factory
    return deco


def register_span_sink(kind: str):
    def deco(factory):
        SpanSinkTypes[kind] = factory
        return factory
    return deco


def register_builtin_sinks() -> None:
    """Import every built-in sink module for its registration side effect."""
    from veneur_tpu.sinks import (  # noqa: F401
        blackhole, channel, debug, localfile,
    )
    for mod in ("datadog", "prometheus", "cortex", "signalfx", "kafka",
                "splunk", "s3", "cloudwatch", "xray", "newrelic",
                "lightstep", "falconer", "ssfmetrics"):
        try:
            __import__(f"veneur_tpu.sinks.{mod}")
        except ImportError:
            pass
