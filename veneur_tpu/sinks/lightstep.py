"""LightStep sink: spans to a LightStep collector.

Behavioral parity with reference sinks/lightstep/lightstep.go (264 LoC)
for buffering, striping, and accounting: the reference wraps the
official LightStep tracer library, which speaks the LightStep collector
protocol (protobuf collector.proto over HTTPS/gRPC).

COLLECTOR-SHAPE-UNVERIFIED: this rebuild posts a homegrown JSON report
(span fields + access token) rather than the tracer library's wire
protocol, and no fixture captured from a real LightStep collector
validates it. Use it as a structural stand-in — buffering/striping/drop
semantics match the reference — but verify the report shape against a
live collector (or swap in an OTLP exporter, which current
LightStep/ServiceNow collectors accept) before production use. The
vendor-schema pins in tests/test_vendor_payloads.py deliberately do NOT
cover this sink for that reason."""

from __future__ import annotations

import logging
import threading
from typing import List

from veneur_tpu.sinks import SpanSink, register_span_sink
from veneur_tpu.util import http as vhttp

logger = logging.getLogger("veneur_tpu.sinks.lightstep")


class LightStepSpanSink(SpanSink):
    def __init__(self, name: str, access_token: str, collector_url: str,
                 num_clients: int = 1, timeout: float = 10.0,
                 maximum_spans: int = 0):
        self._name = name
        self.access_token = access_token
        # one buffer per "client" stripe, keyed by trace id, mirroring the
        # reference's multiple tracer clients (lightstep.go)
        self.num_clients = max(1, num_clients)
        self.collector_url = collector_url
        self.timeout = timeout
        self._buffers: List[List[dict]] = [[] for _ in range(self.num_clients)]
        self._lock = threading.Lock()
        self.spans_handled = 0
        # lightstep_maximum_spans -> the tracer's MaxBufferedSpans
        # (lightstep.go:117); enforced per client stripe between flushes
        self.maximum_spans = maximum_spans
        self.dropped_total = 0

    def name(self) -> str:
        return self._name

    def kind(self) -> str:
        return "lightstep"

    def ingest(self, span) -> None:
        report = {
            "span_guid": format(span.id & ((1 << 64) - 1), "x"),
            "trace_guid": format(span.trace_id & ((1 << 64) - 1), "x"),
            "span_name": span.name,
            "oldest_micros": span.start_timestamp // 1000,
            "youngest_micros": span.end_timestamp // 1000,
            "attributes": [{"Key": k, "Value": v}
                           for k, v in dict(span.tags).items()]
            + [{"Key": "service", "Value": span.service},
               {"Key": "error", "Value": str(bool(span.error)).lower()}],
        }
        if span.parent_id:
            report["attributes"].append(
                {"Key": "parent_span_guid",
                 "Value": format(span.parent_id & ((1 << 64) - 1), "x")})
        with self._lock:
            buf = self._buffers[span.trace_id % self.num_clients]
            if self.maximum_spans and len(buf) >= self.maximum_spans:
                self.dropped_total += 1
                return
            buf.append(report)
            self.spans_handled += 1

    def flush(self) -> None:
        import time as _time

        flush_start = _time.perf_counter()
        with self._lock:
            buffers = self._buffers
            self._buffers = [[] for _ in range(self.num_clients)]
        sent = 0
        total = sum(len(spans) for spans in buffers)
        for spans in buffers:
            if not spans or not self.collector_url:
                continue
            payload = {"auth": {"access_token": self.access_token},
                       "span_records": spans}
            try:
                vhttp.post_json(f"{self.collector_url}/api/v0/reports",
                                payload, compress="gzip",
                                timeout=self.timeout)
                sent += len(spans)
            except Exception as e:
                logger.error("lightstep report failed: %s", e)
        # spans swapped out but not delivered are gone: count as drops,
        # along with ingest-side maximum_spans overflow
        with self._lock:
            overflow, self.dropped_total = self.dropped_total, 0
        self.emit_flush_self_metrics(
            sent, flush_start, (total - sent) + overflow)


@register_span_sink("lightstep")
def _factory(sink_config, server_config):
    c = sink_config.config
    # lightstep_reconnect_period tunes the reference tracer's transport
    # recycling; this HTTP reporter opens a fresh connection per flush,
    # so the knob is accepted for config compatibility and has nothing
    # to recycle
    return LightStepSpanSink(
        sink_config.name or "lightstep",
        access_token=str(c.get("lightstep_access_token",
                               c.get("access_token", ""))),
        collector_url=c.get("lightstep_collector_host",
                            c.get("collector_host", "")),
        num_clients=int(c.get("lightstep_num_clients",
                              c.get("num_clients", 1))),
        maximum_spans=int(c.get("lightstep_maximum_spans", 0)))
