"""LightStep sink: spans to a LightStep / ServiceNow Cloud Observability
collector over OTLP/HTTP JSON.

Behavioral parity with reference sinks/lightstep/lightstep.go (264 LoC)
for buffering, striping, and accounting: the reference wraps the
official LightStep tracer library (one buffer per tracer client,
`lightstep_num_clients` stripes keyed by trace id, MaxBufferedSpans
overflow drops, flush-time delivery). The tracer's proprietary
collector protocol was retired by the vendor in favor of OTLP, which
current LightStep/ServiceNow collectors ingest natively at /v1/traces
(access token in the `lightstep-access-token` header) — so this rebuild
speaks OTLP/HTTP JSON, the OpenTelemetry ExportTraceServiceRequest
shape. The payload schema is pinned in tests/test_vendor_payloads.py.
"""

from __future__ import annotations

import logging
import threading
from typing import List

from veneur_tpu.sinks import SpanSink, register_span_sink
from veneur_tpu.util import http as vhttp

logger = logging.getLogger("veneur_tpu.sinks.lightstep")


def _hex_id(value: int, width: int) -> str:
    """OTLP JSON carries trace/span ids as fixed-width lowercase hex
    (16 bytes / 8 bytes); SSF ids are 64-bit, so trace ids zero-extend
    into the high 8 bytes."""
    return format(value & ((1 << 64) - 1), f"0{width}x")


def span_to_otlp(span) -> dict:
    """One SSF span -> one OTLP JSON Span object (trace.v1.Span)."""
    attributes = [
        {"key": k, "value": {"stringValue": str(v)}}
        for k, v in dict(span.tags).items()
    ]
    out = {
        "traceId": _hex_id(span.trace_id, 32),
        "spanId": _hex_id(span.id, 16),
        "name": span.name or "unknown",
        # SPAN_KIND_INTERNAL: SSF spans carry no client/server direction
        "kind": 1,
        "startTimeUnixNano": str(span.start_timestamp),
        "endTimeUnixNano": str(span.end_timestamp),
        "attributes": attributes,
    }
    if span.parent_id:
        out["parentSpanId"] = _hex_id(span.parent_id, 16)
    if span.error:
        out["status"] = {"code": 2}  # STATUS_CODE_ERROR
    if span.indicator:
        attributes.append(
            {"key": "indicator", "value": {"boolValue": True}})
    return out


class LightStepSpanSink(SpanSink):
    def __init__(self, name: str, access_token: str, collector_url: str,
                 num_clients: int = 1, timeout: float = 10.0,
                 maximum_spans: int = 0):
        self._name = name
        self.access_token = access_token
        # one buffer per "client" stripe, keyed by trace id, mirroring the
        # reference's multiple tracer clients (lightstep.go)
        self.num_clients = max(1, num_clients)
        # explicit YAML null reaches here as None; flush() skips falsy
        self.collector_url = (collector_url or "").rstrip("/")
        self.timeout = timeout
        self._buffers: List[List[dict]] = [[] for _ in range(self.num_clients)]
        self._lock = threading.Lock()
        self.spans_handled = 0
        # lightstep_maximum_spans -> the tracer's MaxBufferedSpans
        # (lightstep.go:117); enforced per client stripe between flushes
        self.maximum_spans = maximum_spans
        self.dropped_total = 0

    def name(self) -> str:
        return self._name

    def kind(self) -> str:
        return "lightstep"

    def ingest(self, span) -> None:
        otlp = span_to_otlp(span)
        otlp["_service"] = span.service or "unknown"  # grouped at flush
        with self._lock:
            buf = self._buffers[span.trace_id % self.num_clients]
            if self.maximum_spans and len(buf) >= self.maximum_spans:
                self.dropped_total += 1
                return
            buf.append(otlp)
            self.spans_handled += 1

    def _report_of(self, spans: List[dict]) -> dict:
        """Buffered spans -> one ExportTraceServiceRequest: spans group
        into a resourceSpans entry per service.name (OTLP's resource is
        the emitting entity; SSF carries it per span)."""
        by_service: dict = {}
        for s in spans:
            by_service.setdefault(s.pop("_service"), []).append(s)
        return {"resourceSpans": [
            {
                "resource": {"attributes": [
                    {"key": "service.name",
                     "value": {"stringValue": service}},
                ]},
                "scopeSpans": [{
                    "scope": {"name": "veneur-tpu"},
                    "spans": group,
                }],
            }
            for service, group in sorted(by_service.items())
        ]}

    def flush(self) -> None:
        import time as _time

        flush_start = _time.perf_counter()
        with self._lock:
            buffers = self._buffers
            self._buffers = [[] for _ in range(self.num_clients)]
        sent = 0
        total = sum(len(spans) for spans in buffers)
        for spans in buffers:
            if not spans or not self.collector_url:
                continue
            payload = self._report_of(spans)
            try:
                vhttp.post_json(f"{self.collector_url}/v1/traces",
                                payload, compress="gzip",
                                timeout=self.timeout,
                                headers={"lightstep-access-token":
                                         self.access_token})
                sent += len(spans)
            except Exception as e:
                logger.error("lightstep report failed: %s", e)
        # spans swapped out but not delivered are gone: count as drops,
        # along with ingest-side maximum_spans overflow
        with self._lock:
            overflow, self.dropped_total = self.dropped_total, 0
        self.emit_flush_self_metrics(
            sent, flush_start, (total - sent) + overflow)


@register_span_sink("lightstep")
def _factory(sink_config, server_config):
    c = sink_config.config
    # lightstep_reconnect_period tunes the reference tracer's transport
    # recycling; this HTTP reporter opens a fresh connection per flush,
    # so the knob is accepted for config compatibility and has nothing
    # to recycle
    return LightStepSpanSink(
        sink_config.name or "lightstep",
        access_token=str(c.get("lightstep_access_token",
                               c.get("access_token", ""))),
        collector_url=c.get("lightstep_collector_host",
                            c.get("collector_host", "")),
        num_clients=int(c.get("lightstep_num_clients",
                              c.get("num_clients", 1))),
        maximum_spans=int(c.get("lightstep_maximum_spans", 0)))
