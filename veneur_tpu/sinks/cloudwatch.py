"""CloudWatch sink: PutMetricData.

Behavioral parity with reference sinks/cloudwatch/cloudwatch.go (174 LoC):
InterMetrics become CloudWatch MetricDatum entries (dimensions from tags,
20 datums per request — the API cap the reference also chunks to) POSTed
to the monitoring Query API as form-encoded PutMetricData calls, signed
with SigV4 when credentials are configured (the reference gets signing
from the AWS SDK; here it is a ~40-line stdlib implementation). Tests
point `endpoint` at a local fake and skip signing.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import logging
import urllib.parse
from typing import Dict, List, Optional, Tuple

from veneur_tpu.config import parse_duration
from veneur_tpu.samplers.metrics import InterMetric, MetricType
from veneur_tpu.sinks import MetricSink, register_metric_sink
from veneur_tpu.util import http as vhttp

logger = logging.getLogger("veneur_tpu.sinks.cloudwatch")

MAX_DATUMS_PER_CALL = 20  # PutMetricData API limit


def sigv4_headers(method: str, url: str, body: bytes, region: str,
                  access_key: str, secret_key: str,
                  service: str = "monitoring",
                  now: Optional[datetime.datetime] = None) -> Dict[str, str]:
    """Minimal AWS Signature Version 4 for a form-encoded POST."""
    parsed = urllib.parse.urlparse(url)
    now = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    date_stamp = now.strftime("%Y%m%d")
    payload_hash = hashlib.sha256(body).hexdigest()
    canonical_headers = (f"host:{parsed.netloc}\n"
                         f"x-amz-date:{amz_date}\n")
    signed_headers = "host;x-amz-date"
    canonical_request = "\n".join([
        method, parsed.path or "/", parsed.query, canonical_headers,
        signed_headers, payload_hash])
    scope = f"{date_stamp}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canonical_request.encode()).hexdigest()])

    def _hmac(key: bytes, msg: str) -> bytes:
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = _hmac(f"AWS4{secret_key}".encode(), date_stamp)
    k = _hmac(k, region)
    k = _hmac(k, service)
    k = _hmac(k, "aws4_request")
    signature = hmac.new(k, string_to_sign.encode(),
                         hashlib.sha256).hexdigest()
    return {
        "X-Amz-Date": amz_date,
        "Authorization": (
            f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
            f"SignedHeaders={signed_headers}, Signature={signature}"),
    }


DEFAULT_STANDARD_UNIT_TAG = "cloudwatch_standard_unit"  # cloudwatch.go:24


def datum_params(index: int, m: InterMetric,
                 standard_unit_tag: str = DEFAULT_STANDARD_UNIT_TAG,
                 default_unit: str = "None") -> Dict[str, str]:
    """Flatten one MetricDatum into Query-API form params. A tag named
    `standard_unit_tag` supplies the datum's Unit (falling back to
    `default_unit`) and is excluded from dimensions; tags without a
    colon are dropped as illegal (reference cloudwatch.go:137-152)."""
    unit = default_unit
    dims = []
    for tag in m.tags:
        k, sep, v = tag.partition(":")
        if not sep:
            continue  # drop illegal tag
        if k == standard_unit_tag:
            unit = v or default_unit
            continue
        # the API rejects empty dimension values; valued-but-empty tags
        # keep the historical "true" placeholder
        dims.append((k, v or "true"))
    p = {f"MetricData.member.{index}.MetricName": m.name,
         f"MetricData.member.{index}.Value": repr(float(m.value)),
         f"MetricData.member.{index}.Unit": unit,
         f"MetricData.member.{index}.Timestamp":
             datetime.datetime.fromtimestamp(
                 m.timestamp, datetime.timezone.utc).strftime(
                 "%Y-%m-%dT%H:%M:%SZ")}
    for di, (k, v) in enumerate(dims[:30], start=1):  # API cap: 30 dims
        p[f"MetricData.member.{index}.Dimensions.member.{di}.Name"] = k
        p[f"MetricData.member.{index}.Dimensions.member.{di}.Value"] = v
    return p


class CloudWatchMetricSink(MetricSink):
    def __init__(self, name: str, endpoint: str, namespace: str,
                 region: str = "", credentials: Tuple[str, str] = ("", ""),
                 standard_unit_tag: str = DEFAULT_STANDARD_UNIT_TAG,
                 default_unit: str = "None",
                 timeout: float = 10.0, disable_retries: bool = False):
        self._name = name
        self.endpoint = endpoint
        self.namespace = namespace
        self.region = region
        self.credentials = credentials
        self.standard_unit_tag = standard_unit_tag
        self.default_unit = default_unit
        self.timeout = timeout
        # aws_disable_retries maps to the SDK's NopRetryer
        # (cloudwatch.go:123-125); default is one retry pass
        self.max_attempts = 1 if disable_retries else 3

    def name(self) -> str:
        return self._name

    def kind(self) -> str:
        return "cloudwatch"

    def flush(self, metrics: List[InterMetric]) -> None:
        datums = [m for m in metrics if m.type != MetricType.STATUS]
        for i in range(0, len(datums), MAX_DATUMS_PER_CALL):
            chunk = datums[i:i + MAX_DATUMS_PER_CALL]
            params = {"Action": "PutMetricData", "Version": "2010-08-01",
                      "Namespace": self.namespace}
            for j, m in enumerate(chunk, start=1):
                params.update(datum_params(
                    j, m, self.standard_unit_tag, self.default_unit))
            body = urllib.parse.urlencode(params).encode()
            headers = {}
            if self.credentials[0]:
                headers = sigv4_headers(
                    "POST", self.endpoint, body, self.region,
                    *self.credentials)
            for attempt in range(1, self.max_attempts + 1):
                try:
                    vhttp.post(
                        self.endpoint, body,
                        content_type="application/x-www-form-urlencoded",
                        headers=headers, timeout=self.timeout)
                    break
                except Exception as e:
                    if (isinstance(e, vhttp.HTTPError)
                            and 400 <= e.status < 500):
                        # non-retryable: an identical resend is doomed
                        logger.error(
                            "cloudwatch PutMetricData rejected (%d): %s",
                            e.status, e)
                        break
                    if attempt == self.max_attempts:
                        logger.error(
                            "cloudwatch PutMetricData failed: %s", e)


@register_metric_sink("cloudwatch")
def _factory(sink_config, server_config):
    c = sink_config.config
    region = c.get("aws_region", "us-east-1")
    return CloudWatchMetricSink(
        sink_config.name or "cloudwatch",
        endpoint=(c.get("cloudwatch_endpoint", "")
                  or c.get("aws_endpoint",
                           f"https://monitoring.{region}.amazonaws.com/")),
        namespace=c.get("cloudwatch_namespace", "veneur"),
        region=region,
        credentials=(str(c.get("aws_access_key_id", "")),
                     str(c.get("aws_secret_access_key", ""))),
        standard_unit_tag=c.get("cloudwatch_standard_unit_tag_name",
                                DEFAULT_STANDARD_UNIT_TAG),
        default_unit=c.get("cloudwatch_standard_unit", "None"),
        timeout=parse_duration(c.get("remote_timeout", 0) or 0) or 10.0,
        disable_retries=bool(c.get("aws_disable_retries", False)))
