"""Falconer sink: spans streamed to a falconer trace store over gRPC.

Behavioral parity with reference sinks/falconer/falconer.go (193 LoC):
dial the falconer target and send each ingested span. The reference uses
falconer's generated client; here the SSFSpan protobuf is sent over a
unary-per-span grpc channel using a generic method path, with a
pluggable `sender` boundary so tests can capture spans without a live
falconer."""

from __future__ import annotations

import logging
from typing import Callable, List, Optional

from veneur_tpu.protocol import valid_trace
from veneur_tpu.sinks import SpanSink, register_span_sink

logger = logging.getLogger("veneur_tpu.sinks.falconer")


class GrpcSpanSender:
    """Sends serialized SSFSpans over a grpc channel (route parity with
    the reference's generated client: /falconer.SpanSink/SendSpan,
    reference sinks/falconer/grpc_sink.pb.go:108, with the trace id in
    x-veneur-trace-id request metadata, falconer.go:134-138)."""

    METHOD = "/falconer.SpanSink/SendSpan"

    def __init__(self, target: str):
        import grpc
        self._channel = grpc.insecure_channel(target)
        self._send = self._channel.unary_unary(
            self.METHOD,
            request_serializer=lambda span: span.SerializeToString(),
            response_deserializer=lambda b: b)

    def __call__(self, span) -> None:
        self._send(span, timeout=5.0, metadata=(
            ("x-veneur-trace-id", format(span.trace_id, "x")),))

    def close(self) -> None:
        self._channel.close()


class FalconerSpanSink(SpanSink):
    def __init__(self, name: str, target: str = "",
                 sender: Optional[Callable] = None):
        self._name = name
        self.target = target
        self.sender = sender
        self.spans_handled = 0
        self.errors = 0

    def name(self) -> str:
        return self._name

    def kind(self) -> str:
        return "falconer"

    def start(self, server) -> None:
        if self.sender is None and self.target:
            try:
                self.sender = GrpcSpanSender(self.target)
            except Exception as e:
                logger.error("falconer dial %s failed: %s", self.target, e)

    def ingest(self, span) -> None:
        if self.sender is None:
            return
        if not valid_trace(span):
            # reference validates before sending (falconer.go:130-132,
            # protocol/wire.go:82-88)
            return
        try:
            self.sender(span)
            self.spans_handled += 1
        except Exception:
            self.errors += 1

    def stop(self) -> None:
        close = getattr(self.sender, "close", None)
        if close is not None:
            close()


@register_span_sink("falconer")
def _factory(sink_config, server_config):
    c = sink_config.config
    return FalconerSpanSink(
        sink_config.name or "falconer",
        target=c.get("target", ""),
        sender=c.get("sender"))
