"""Local-file sink: appends flushed metrics as TSV lines
(reference sinks/localfile/localfile.go + util/csv.go column layout)."""

from __future__ import annotations

import csv
import logging
import time

from veneur_tpu.sinks import MetricSink, register_metric_sink

logger = logging.getLogger("veneur_tpu.sinks.localfile")

# TSV column layout, matching the reference's S3/localfile encoder
# (util/csv.go): name, tags, type, hostname, timestamp, value, interval
HEADERS = ["Name", "Tags", "MetricType", "Hostname", "Timestamp", "Value",
           "Partition", "VeneurHostname", "Interval"]


class LocalFileSink(MetricSink):
    def __init__(self, name: str, path: str, hostname: str, interval: float,
                 delimiter: str = "\t"):
        self._name = name
        self.path = path
        self.hostname = hostname
        self.interval = interval
        self.delimiter = delimiter

    def name(self) -> str:
        return self._name

    def kind(self) -> str:
        return "localfile"

    def flush(self, metrics) -> None:
        if not metrics:
            return
        try:
            with open(self.path, "a", newline="") as f:
                w = csv.writer(f, delimiter=self.delimiter)
                partition = time.strftime("%Y%m%d")
                for metric in metrics:
                    w.writerow([
                        metric.name, ",".join(metric.tags), metric.type.name.lower(),
                        metric.hostname, metric.timestamp, metric.value,
                        partition, self.hostname, int(self.interval)])
        except OSError as e:
            logger.error("could not flush to %s: %s", self.path, e)


@register_metric_sink("localfile")
def _factory(sink_config, server_config):
    return LocalFileSink(
        sink_config.name or "localfile",
        path=sink_config.config.get("flush_file", "/tmp/veneur-tpu.tsv"),
        hostname=server_config.hostname,
        interval=server_config.interval,
        delimiter=sink_config.config.get("delimiter", "\t"))
