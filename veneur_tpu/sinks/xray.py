"""AWS X-Ray sink: spans as segment JSON over UDP to the X-Ray daemon.

Behavioral parity with reference sinks/xray/xray.go (307 LoC): each span
becomes an X-Ray segment document prefixed with the daemon header line
`{"format": "json", "version": 1}\\n`, sent as one UDP datagram to the
local daemon. Trace ids render in X-Ray's `1-<epoch hex>-<24 hex>`
format; spans sample by trace id percentage; annotations come from a
configured tag allowlist.
"""

from __future__ import annotations

import json
import logging
import socket
from typing import Optional, Sequence

from veneur_tpu.sinks import SpanSink, register_span_sink

logger = logging.getLogger("veneur_tpu.sinks.xray")

HEADER = b'{"format": "json", "version": 1}\n'


def xray_trace_id(span) -> str:
    """X-Ray trace id: 1-{epoch:8hex}-{traceid:24hex}. Segments only
    assemble into one trace when their ids match, so the timestamp
    component comes from the root span when the client sent one
    (exact, like the reference), else from a ~4-minute bucket of the
    span's own start (low byte of the epoch seconds cleared). Exact
    parity with reference xray.go:290-306, including its caveats:
    bucketing is probabilistic (spans straddling a 256 s boundary split)
    and a trace whose root lacks root_start_timestamp while children
    carry it splits — clients fix both by always setting the field."""
    root_ns = getattr(span, "root_start_timestamp", 0)
    if root_ns:
        epoch = root_ns // 10**9
    else:
        epoch = (span.start_timestamp // 10**9) & 0xFFFFFFFFFFFF00
    tid = span.trace_id & ((1 << 96) - 1)
    return f"1-{epoch & 0xFFFFFFFF:08x}-{tid:024x}"


def span_to_segment(span, annotation_tags: Sequence[str]) -> dict:
    tags = dict(span.tags)
    seg = {
        "name": (span.service or "unknown")[:200],
        "id": format(span.id & ((1 << 64) - 1), "016x"),
        "trace_id": xray_trace_id(span),
        "start_time": span.start_timestamp / 1e9,
        "end_time": span.end_timestamp / 1e9,
        "error": bool(span.error),
        "annotations": {k.replace("-", "_"): v for k, v in tags.items()
                        if k in annotation_tags},
        "metadata": {"name": span.name, "tags": tags},
    }
    if span.parent_id:
        seg["parent_id"] = format(span.parent_id & ((1 << 64) - 1), "016x")
        seg["type"] = "subsegment"
    return seg


class XRaySpanSink(SpanSink):
    def __init__(self, name: str, daemon_address: str,
                 sample_percentage: float = 100.0,
                 annotation_tags: Sequence[str] = ()):
        self._name = name
        host, _, port = daemon_address.rpartition(":")
        self.daemon_addr = (host or "127.0.0.1", int(port))
        self.sample_threshold = int(sample_percentage * 100)
        self.annotation_tags = list(annotation_tags)
        self._sock: Optional[socket.socket] = None
        self.spans_handled = 0
        self.spans_dropped = 0

    def name(self) -> str:
        return self._name

    def kind(self) -> str:
        return "xray"

    def start(self, server) -> None:
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)

    def ingest(self, span) -> None:
        if self._sock is None:
            return
        if (span.trace_id % 10_000) >= self.sample_threshold:
            self.spans_dropped += 1
            return
        seg = span_to_segment(span, self.annotation_tags)
        try:
            self._sock.sendto(
                HEADER + json.dumps(seg, separators=(",", ":")).encode(),
                self.daemon_addr)
            self.spans_handled += 1
        except OSError as e:
            logger.error("xray daemon send failed: %s", e)
            self.spans_dropped += 1

    def stop(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None


@register_span_sink("xray")
def _factory(sink_config, server_config):
    c = sink_config.config
    return XRaySpanSink(
        sink_config.name or "xray",
        daemon_address=c.get("address", "127.0.0.1:2000"),
        sample_percentage=float(c.get("sample_percentage", 100.0)),
        annotation_tags=c.get("annotation_tags", []) or [])
