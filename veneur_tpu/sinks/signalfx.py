"""SignalFx sink.

Behavioral parity with reference sinks/signalfx/signalfx.go (681 LoC):
InterMetrics become SignalFx datapoints with dimensions; a `vary_key_by`
tag routes each metric to a per-token client (reference's dynamic
per-token clients, signalfx.go:491-588); counters are cumulative counts,
gauges and status checks gauges (signalfx.go:573-582); counters can drop
the hostname dimension when a configured tag is present
(drop_host_with_tag_key, signalfx.go:566-571); batches chunk at
flush_max_per_body (collection.submit, signalfx.go:96-141). DogStatsD
events flush to /v2/event with name/description truncation and
Datadog-markdown stripping (signalfx.go:601-681). Datapoints POST to
/v2/datapoint as JSON (the reference uses the sfx protobuf client; the
JSON ingest API carries the same datapoint model).
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Sequence

from veneur_tpu.samplers.metrics import InterMetric, MetricType
from veneur_tpu.samplers.parser import EVENT_IDENTIFIER_KEY
from veneur_tpu.sinks import MetricSink, register_metric_sink
from veneur_tpu.util import http as vhttp

logger = logging.getLogger("veneur_tpu.sinks.signalfx")

EVENT_NAME_MAX_LENGTH = 256  # reference signalfx.go:30
EVENT_DESCRIPTION_MAX_LENGTH = 256  # reference signalfx.go:31


class SignalFxMetricSink(MetricSink):
    def __init__(self, name: str, api_key: str, endpoint: str,
                 hostname: str, hostname_tag: str = "host",
                 vary_key_by: str = "", per_tag_tokens: Dict[str, str] = None,
                 excluded_tags: Sequence[str] = (),
                 drop_host_with_tag_key: str = "",
                 flush_max_per_body: int = 0, timeout: float = 10.0):
        self._name = name
        self.api_key = api_key
        self.endpoint = endpoint.rstrip("/")
        self.hostname = hostname
        self.hostname_tag = hostname_tag
        self.vary_key_by = vary_key_by
        self.per_tag_tokens = per_tag_tokens or {}
        self.excluded_tags = set(excluded_tags)
        self.drop_host_with_tag_key = drop_host_with_tag_key
        self.flush_max_per_body = flush_max_per_body
        self.timeout = timeout

    def name(self) -> str:
        return self._name

    def kind(self) -> str:
        return "signalfx"

    def flush(self, metrics: List[InterMetric]) -> None:
        # datapoints grouped by access token (vary_key_by routing)
        by_token: Dict[str, Dict[str, list]] = {}
        for m in metrics:
            dims = {self.hostname_tag: m.hostname or self.hostname}
            token = self.api_key
            for t in m.tags:
                k, _, v = t.partition(":")
                if k in self.excluded_tags:
                    continue
                if self.vary_key_by and k == self.vary_key_by:
                    token = self.per_tag_tokens.get(v, self.api_key)
                dims[k] = v
            if (m.type == MetricType.COUNTER and self.drop_host_with_tag_key
                    and self.drop_host_with_tag_key in dims):
                dims.pop(self.hostname_tag, None)
            point = {
                "metric": m.name,
                "value": m.value,
                "timestamp": m.timestamp * 1000,
                "dimensions": dims,
            }
            bucket = by_token.setdefault(token, {"counter": [], "gauge": []})
            if m.type == MetricType.COUNTER:
                bucket["counter"].append(point)
            else:
                # gauges and status checks both emit as gauges
                # (signalfx.go:573-582)
                bucket["gauge"].append(point)
        threads = []
        for token, payload in by_token.items():
            for chunk in self._chunk(payload):
                t = threading.Thread(
                    target=self._post_datapoints, args=(token, chunk),
                    daemon=True)
                t.start()
                threads.append(t)
        for t in threads:
            t.join()

    def _chunk(self, payload: Dict[str, list]) -> List[Dict[str, list]]:
        """Split a token's datapoints at flush_max_per_body (the
        reference's collection.submit batching)."""
        per = self.flush_max_per_body
        total = sum(len(v) for v in payload.values())
        if not per or total <= per:
            out = {k: v for k, v in payload.items() if v}
            return [out] if out else []
        flat = [(kind, p) for kind, pts in payload.items() for p in pts]
        chunks = []
        for i in range(0, len(flat), per):
            chunk: Dict[str, list] = {}
            for kind, p in flat[i:i + per]:
                chunk.setdefault(kind, []).append(p)
            chunks.append(chunk)
        return chunks

    def _post_datapoints(self, token: str, payload: Dict[str, list]) -> None:
        try:
            vhttp.post_json(
                f"{self.endpoint}/v2/datapoint", payload,
                headers={"X-SF-Token": token}, compress="gzip",
                timeout=self.timeout)
        except Exception as e:
            logger.error("signalfx POST failed: %s", e)

    def flush_other_samples(self, samples: Sequence[Any]) -> None:
        """DogStatsD events -> SignalFx /v2/event (reference
        signalfx.go:601-681 FlushOtherSamples/reportEvent); non-event
        samples are ignored."""
        events = []
        for s in samples:
            tags = dict(getattr(s, "tags", {}) or {})
            if EVENT_IDENTIFIER_KEY not in tags:
                continue
            tags.pop(EVENT_IDENTIFIER_KEY, None)
            dims = {self.hostname_tag: self.hostname}
            for k, v in tags.items():
                if k not in self.excluded_tags:
                    dims[k] = v
            name = getattr(s, "name", "")[:EVENT_NAME_MAX_LENGTH]
            message = getattr(s, "message", "")
            if len(message) > EVENT_DESCRIPTION_MAX_LENGTH:
                message = message[:EVENT_DESCRIPTION_MAX_LENGTH]
            # strip the Datadog markdown fences SignalFx has no use for
            message = message.replace("%%% \n", "", 1)
            message = message.replace("\n %%%", "", 1)
            message = message.strip()
            events.append({
                "eventType": name,
                "category": "USER_DEFINED",
                "dimensions": dims,
                "timestamp": getattr(s, "timestamp", 0) * 1000,
                "properties": {"description": message},
            })
        if not events:
            return
        try:
            vhttp.post_json(
                f"{self.endpoint}/v2/event", events,
                headers={"X-SF-Token": self.api_key}, compress="gzip",
                timeout=self.timeout)
        except Exception as e:
            logger.error("signalfx event POST failed: %s", e)


@register_metric_sink("signalfx")
def _factory(sink_config, server_config):
    c = sink_config.config
    per_tag = {str(i.get("value", "")): str(i.get("api_key", ""))
               for i in (c.get("per_tag_api_keys", []) or [])}
    return SignalFxMetricSink(
        sink_config.name or "signalfx",
        api_key=str(c.get("api_key", "")),
        endpoint=c.get("endpoint_base", "https://ingest.signalfx.com"),
        hostname=server_config.hostname,
        hostname_tag=c.get("hostname_tag", "host"),
        vary_key_by=c.get("vary_key_by", ""),
        per_tag_tokens=per_tag,
        excluded_tags=c.get("excluded_tags", []) or [],
        drop_host_with_tag_key=c.get("drop_host_with_tag_key", ""),
        flush_max_per_body=int(c.get("flush_max_per_body", 0)))
