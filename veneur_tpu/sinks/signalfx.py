"""SignalFx sink.

Behavioral parity with reference sinks/signalfx/signalfx.go (681 LoC):
InterMetrics become SignalFx datapoints with dimensions; a `vary_key_by`
tag routes each metric to a per-token client (reference's dynamic
per-token clients, signalfx.go:491-588); counters are cumulative counts,
gauges and status checks gauges (signalfx.go:573-582); counters can drop
the hostname dimension when a configured tag is present
(drop_host_with_tag_key, signalfx.go:566-571); batches chunk at
flush_max_per_body (collection.submit, signalfx.go:96-141). DogStatsD
events flush to /v2/event with name/description truncation and
Datadog-markdown stripping (signalfx.go:601-681). Datapoints POST to
/v2/datapoint as JSON (the reference uses the sfx protobuf client; the
JSON ingest API carries the same datapoint model).
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Sequence

from veneur_tpu.config import parse_duration
from veneur_tpu.samplers.metrics import InterMetric, MetricType
from veneur_tpu.samplers.parser import EVENT_IDENTIFIER_KEY
from veneur_tpu.sinks import MetricSink, register_metric_sink
from veneur_tpu.util import http as vhttp

logger = logging.getLogger("veneur_tpu.sinks.signalfx")

EVENT_NAME_MAX_LENGTH = 256  # reference signalfx.go:30
EVENT_DESCRIPTION_MAX_LENGTH = 256  # reference signalfx.go:31


class SignalFxMetricSink(MetricSink):
    def __init__(self, name: str, api_key: str, endpoint: str,
                 hostname: str, hostname_tag: str = "host",
                 vary_key_by: str = "", per_tag_tokens: Dict[str, str] = None,
                 excluded_tags: Sequence[str] = (),
                 drop_host_with_tag_key: str = "",
                 flush_max_per_body: int = 0, timeout: float = 10.0,
                 metric_tag_prefix_drops: Sequence[str] = (),
                 preferred_vary_key_by: str = "",
                 api_endpoint: str = "https://api.signalfx.com",
                 dynamic_per_tag_tokens: bool = False,
                 dynamic_refresh_period_s: float = 0.0):
        self._name = name
        self.api_key = api_key
        self.endpoint = endpoint.rstrip("/")
        self.hostname = hostname
        self.hostname_tag = hostname_tag
        self.vary_key_by = vary_key_by
        self.per_tag_tokens = per_tag_tokens or {}
        self.excluded_tags = set(excluded_tags)
        self.drop_host_with_tag_key = drop_host_with_tag_key
        self.flush_max_per_body = flush_max_per_body
        self.timeout = timeout
        # metrics carrying a tag with any of these prefixes are skipped
        # outright (signalfx.go:510-518)
        self.metric_tag_prefix_drops = tuple(metric_tag_prefix_drops or ())
        # token-routing dimension that beats vary_key_by when both are
        # present on a metric (signalfx.go:543-560; the reference also
        # parses vary_key_by_favor_common_dimensions but never reads it,
        # so it is accepted-and-ignored here too)
        self.preferred_vary_key_by = preferred_vary_key_by
        self.skipped_total = 0
        # dynamic per-tag tokens: a refresher polls the SignalFx org
        # token API and swaps the routing table (signalfx.go:352-445)
        self.api_endpoint = api_endpoint.rstrip("/")
        self._tokens_lock = threading.Lock()
        self._refresher: threading.Thread = None
        if dynamic_per_tag_tokens and dynamic_refresh_period_s > 0:
            self._refresher = threading.Thread(
                target=self._refresh_tokens_loop,
                args=(dynamic_refresh_period_s,),
                name=f"sfx-token-refresh-{name}", daemon=True)
            self._refresher.start()

    def _refresh_tokens_loop(self, period_s: float) -> None:
        import time as _time
        while True:
            _time.sleep(period_s)
            try:
                tokens = fetch_api_keys(
                    self.api_endpoint, self.api_key, timeout=self.timeout)
            except Exception as e:
                logger.warning("failed to fetch tokens from SignalFx: %s", e)
                continue
            with self._tokens_lock:
                self.per_tag_tokens.update(tokens)

    def name(self) -> str:
        return self._name

    def kind(self) -> str:
        return "signalfx"

    def flush(self, metrics: List[InterMetric]) -> None:
        # datapoints grouped by access token (vary_key_by routing)
        by_token: Dict[str, Dict[str, list]] = {}
        prefix_drops = self.metric_tag_prefix_drops
        for m in metrics:
            if prefix_drops and any(
                    t.startswith(p) for p in prefix_drops for t in m.tags):
                self.skipped_total += 1
                continue
            dims = {self.hostname_tag: m.hostname or self.hostname}
            for t in m.tags:
                k, _, v = t.partition(":")
                dims[k] = v
            # preferred_vary_key_by beats vary_key_by when its dimension
            # is present; routing sees the full dimension set — excluded
            # tags are deleted only after key selection
            # (signalfx.go:534-564)
            vary_val = ""
            if self.preferred_vary_key_by:
                vary_val = dims.get(self.preferred_vary_key_by, "")
            if not vary_val and self.vary_key_by:
                vary_val = dims.get(self.vary_key_by, "")
            if vary_val:
                with self._tokens_lock:
                    token = self.per_tag_tokens.get(vary_val, self.api_key)
            else:
                token = self.api_key
            for k in self.excluded_tags:
                dims.pop(k, None)
            if (m.type == MetricType.COUNTER and self.drop_host_with_tag_key
                    and self.drop_host_with_tag_key in dims):
                dims.pop(self.hostname_tag, None)
            point = {
                "metric": m.name,
                "value": m.value,
                "timestamp": m.timestamp * 1000,
                "dimensions": dims,
            }
            bucket = by_token.setdefault(token, {"counter": [], "gauge": []})
            if m.type == MetricType.COUNTER:
                bucket["counter"].append(point)
            else:
                # gauges and status checks both emit as gauges
                # (signalfx.go:573-582)
                bucket["gauge"].append(point)
        threads = []
        for token, payload in by_token.items():
            for chunk in self._chunk(payload):
                t = threading.Thread(
                    target=self._post_datapoints, args=(token, chunk),
                    daemon=True)
                t.start()
                threads.append(t)
        for t in threads:
            t.join()

    def _chunk(self, payload: Dict[str, list]) -> List[Dict[str, list]]:
        """Split a token's datapoints at flush_max_per_body (the
        reference's collection.submit batching)."""
        per = self.flush_max_per_body
        total = sum(len(v) for v in payload.values())
        if not per or total <= per:
            out = {k: v for k, v in payload.items() if v}
            return [out] if out else []
        flat = [(kind, p) for kind, pts in payload.items() for p in pts]
        chunks = []
        for i in range(0, len(flat), per):
            chunk: Dict[str, list] = {}
            for kind, p in flat[i:i + per]:
                chunk.setdefault(kind, []).append(p)
            chunks.append(chunk)
        return chunks

    def _post_datapoints(self, token: str, payload: Dict[str, list]) -> None:
        try:
            vhttp.post_json(
                f"{self.endpoint}/v2/datapoint", payload,
                headers={"X-SF-Token": token}, compress="gzip",
                timeout=self.timeout)
        except Exception as e:
            logger.error("signalfx POST failed: %s", e)

    def flush_other_samples(self, samples: Sequence[Any]) -> None:
        """DogStatsD events -> SignalFx /v2/event (reference
        signalfx.go:601-681 FlushOtherSamples/reportEvent); non-event
        samples are ignored."""
        events = []
        for s in samples:
            tags = dict(getattr(s, "tags", {}) or {})
            if EVENT_IDENTIFIER_KEY not in tags:
                continue
            tags.pop(EVENT_IDENTIFIER_KEY, None)
            dims = {self.hostname_tag: self.hostname}
            for k, v in tags.items():
                if k not in self.excluded_tags:
                    dims[k] = v
            name = getattr(s, "name", "")[:EVENT_NAME_MAX_LENGTH]
            message = getattr(s, "message", "")
            if len(message) > EVENT_DESCRIPTION_MAX_LENGTH:
                message = message[:EVENT_DESCRIPTION_MAX_LENGTH]
            # strip the Datadog markdown fences SignalFx has no use for
            message = message.replace("%%% \n", "", 1)
            message = message.replace("\n %%%", "", 1)
            message = message.strip()
            events.append({
                "eventType": name,
                "category": "USER_DEFINED",
                "dimensions": dims,
                "timestamp": getattr(s, "timestamp", 0) * 1000,
                "properties": {"description": message},
            })
        if not events:
            return
        try:
            vhttp.post_json(
                f"{self.endpoint}/v2/event", events,
                headers={"X-SF-Token": self.api_key}, compress="gzip",
                timeout=self.timeout)
        except Exception as e:
            logger.error("signalfx event POST failed: %s", e)


def fetch_api_keys(api_endpoint: str, api_token: str,
                   timeout: float = 10.0) -> Dict[str, str]:
    """Page through the SignalFx org-token API and return {name: secret}
    (reference signalfx.go:422-445 fetchAPIKeys: limit-200 pages from
    /v2/token until an empty page)."""
    import json as _json

    tokens: Dict[str, str] = {}
    offset = 0
    while True:
        status, body = vhttp.get(
            f"{api_endpoint}/v2/token?limit=200&name=&offset={offset}",
            headers={"X-SF-Token": api_token,
                     "Content-Type": "application/json"},
            timeout=timeout)
        if status != 200:
            raise RuntimeError(
                f"signalfx api returned unknown response code: {status}")
        results = _json.loads(body).get("results")
        if not isinstance(results, list):
            raise RuntimeError(
                "unknown results structure returned from signalfx api")
        for r in results:
            if not isinstance(r, dict) or "name" not in r or "secret" not in r:
                raise RuntimeError("failed to extract token from result")
            tokens[str(r["name"])] = str(r["secret"])
        if not results:
            return tokens
        offset += 200


@register_metric_sink("signalfx")
def _factory(sink_config, server_config):
    c = sink_config.config
    if (c.get("dynamic_per_tag_api_keys_enable")
            and not c.get("dynamic_per_tag_api_keys_refresh_period")):
        # reference signalfx.go:286-291 refuses this combination
        raise ValueError(
            "per tag API keys are enabled, but the refresh period is unset")
    per_tag = {str(i.get("value", "")): str(i.get("api_key", ""))
               for i in (c.get("per_tag_api_keys", []) or [])}
    return SignalFxMetricSink(
        sink_config.name or "signalfx",
        api_key=str(c.get("api_key", "")),
        endpoint=c.get("endpoint_base", "https://ingest.signalfx.com"),
        hostname=server_config.hostname,
        hostname_tag=c.get("hostname_tag", "host"),
        vary_key_by=c.get("vary_key_by", ""),
        per_tag_tokens=per_tag,
        excluded_tags=c.get("excluded_tags", []) or [],
        drop_host_with_tag_key=c.get("drop_host_with_tag_key", ""),
        flush_max_per_body=int(c.get("flush_max_per_body", 0)),
        metric_tag_prefix_drops=c.get("metric_tag_prefix_drops", []) or [],
        preferred_vary_key_by=c.get("preferred_vary_key_by", ""),
        api_endpoint=c.get("endpoint_api", "https://api.signalfx.com"),
        dynamic_per_tag_tokens=bool(
            c.get("dynamic_per_tag_api_keys_enable", False)),
        dynamic_refresh_period_s=parse_duration(
            c.get("dynamic_per_tag_api_keys_refresh_period", 0) or 0))
