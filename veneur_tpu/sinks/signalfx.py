"""SignalFx sink.

Behavioral parity with reference sinks/signalfx/signalfx.go (681 LoC):
InterMetrics become SignalFx datapoints with dimensions; a `vary_key_by`
tag routes each metric to a per-token client (reference's dynamic
per-token clients); counters are cumulative counts, gauges gauges.
Datapoints POST to /v2/datapoint as JSON (the reference uses the sfx
protobuf client; the JSON ingest API carries the same datapoint model).
"""

from __future__ import annotations

import logging
from typing import Dict, List, Sequence

from veneur_tpu.samplers.metrics import InterMetric, MetricType
from veneur_tpu.sinks import MetricSink, register_metric_sink
from veneur_tpu.util import http as vhttp

logger = logging.getLogger("veneur_tpu.sinks.signalfx")


class SignalFxMetricSink(MetricSink):
    def __init__(self, name: str, api_key: str, endpoint: str,
                 hostname: str, hostname_tag: str = "host",
                 vary_key_by: str = "", per_tag_tokens: Dict[str, str] = None,
                 excluded_tags: Sequence[str] = (), timeout: float = 10.0):
        self._name = name
        self.api_key = api_key
        self.endpoint = endpoint.rstrip("/")
        self.hostname = hostname
        self.hostname_tag = hostname_tag
        self.vary_key_by = vary_key_by
        self.per_tag_tokens = per_tag_tokens or {}
        self.excluded_tags = set(excluded_tags)
        self.timeout = timeout

    def name(self) -> str:
        return self._name

    def kind(self) -> str:
        return "signalfx"

    def flush(self, metrics: List[InterMetric]) -> None:
        # datapoints grouped by access token (vary_key_by routing)
        by_token: Dict[str, Dict[str, list]] = {}
        for m in metrics:
            if m.type == MetricType.STATUS:
                continue
            dims = {self.hostname_tag: m.hostname or self.hostname}
            token = self.api_key
            for t in m.tags:
                k, _, v = t.partition(":")
                if k in self.excluded_tags:
                    continue
                if self.vary_key_by and k == self.vary_key_by:
                    token = self.per_tag_tokens.get(v, self.api_key)
                dims[k] = v
            point = {
                "metric": m.name,
                "value": m.value,
                "timestamp": m.timestamp * 1000,
                "dimensions": dims,
            }
            bucket = by_token.setdefault(token, {"counter": [], "gauge": []})
            if m.type == MetricType.COUNTER:
                bucket["counter"].append(point)
            else:
                bucket["gauge"].append(point)
        for token, payload in by_token.items():
            payload = {k: v for k, v in payload.items() if v}
            try:
                vhttp.post_json(
                    f"{self.endpoint}/v2/datapoint", payload,
                    headers={"X-SF-Token": token}, compress="gzip",
                    timeout=self.timeout)
            except Exception as e:
                logger.error("signalfx POST failed: %s", e)


@register_metric_sink("signalfx")
def _factory(sink_config, server_config):
    c = sink_config.config
    per_tag = {str(i.get("value", "")): str(i.get("api_key", ""))
               for i in (c.get("per_tag_api_keys", []) or [])}
    return SignalFxMetricSink(
        sink_config.name or "signalfx",
        api_key=str(c.get("api_key", "")),
        endpoint=c.get("endpoint_base", "https://ingest.signalfx.com"),
        hostname=server_config.hostname,
        hostname_tag=c.get("hostname_tag", "host"),
        vary_key_by=c.get("vary_key_by", ""),
        per_tag_tokens=per_tag,
        excluded_tags=c.get("excluded_tags", []) or [])
