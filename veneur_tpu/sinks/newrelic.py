"""New Relic sink: metrics and spans via the telemetry ingest APIs.

Behavioral parity with reference sinks/newrelic/*.go (484 LoC), which
wraps the NR telemetry SDK. The telemetry SDK's wire format is plain
JSON over HTTPS, implemented here directly:
- metrics -> POST https://metric-api.newrelic.com/metric/v1
  [{"common": {...}, "metrics": [{name, type, value, timestamp, attributes}]}]
- spans   -> POST https://trace-api.newrelic.com/trace/v1
  [{"common": {...}, "spans": [{id, trace.id, timestamp, attributes}]}]
Both carry the Api-Key header; counters submit as NR "count" with the
flush interval, gauges as "gauge".
"""

from __future__ import annotations

import logging
import threading
from typing import List, Sequence

from veneur_tpu.samplers.metrics import InterMetric, MetricType
from veneur_tpu.sinks import (
    MetricSink, SpanSink, register_metric_sink, register_span_sink,
)
from veneur_tpu.util import http as vhttp

logger = logging.getLogger("veneur_tpu.sinks.newrelic")


def _attributes(tags: Sequence[str]) -> dict:
    out = {}
    for t in tags:
        k, _, v = t.partition(":")
        out[k] = v or True
    return out


DEFAULT_EVENT_TYPE = "veneur"  # reference newrelic.go:15
DEFAULT_SERVICE_CHECK_EVENT_TYPE = "veneurCheck"  # newrelic.go:16
_STATUS_NAMES = {0: "OK", 1: "WARNING", 2: "CRITICAL"}  # else UNKNOWN


class NewRelicMetricSink(MetricSink):
    def __init__(self, name: str, insert_key: str, hostname: str,
                 interval: float, metric_url: str, tags: Sequence[str] = (),
                 timeout: float = 10.0, account_id: int = 0,
                 event_type: str = DEFAULT_EVENT_TYPE,
                 service_check_event_type: str =
                 DEFAULT_SERVICE_CHECK_EVENT_TYPE,
                 event_url: str = ""):
        self._name = name
        self.insert_key = insert_key
        self.hostname = hostname
        self.interval = interval
        self.metric_url = metric_url
        self.common_tags = _attributes(tags)
        self.timeout = timeout
        # custom-event plane: service checks and DogStatsD events go to
        # the account-scoped Events API (reference metric.go:92,173-196;
        # the NR SDK's BatchMode needs the account id)
        self.account_id = account_id
        self.event_type = event_type or DEFAULT_EVENT_TYPE
        self.service_check_event_type = (
            service_check_event_type or DEFAULT_SERVICE_CHECK_EVENT_TYPE)
        self.event_url = event_url or (
            f"https://insights-collector.newrelic.com/v1/accounts/"
            f"{account_id}/events" if account_id else "")

    def name(self) -> str:
        return self._name

    def kind(self) -> str:
        return "newrelic"

    def _post_events(self, events: List[dict], what: str) -> None:
        if not events:
            return
        if not self.event_url:
            logger.warning("%d %s queued but New Relic event client "
                           "disabled (no account_id), dropping",
                           len(events), what)
            return
        try:
            vhttp.post_json(self.event_url, events,
                            headers={"Api-Key": self.insert_key},
                            compress="gzip", timeout=self.timeout)
        except Exception as e:
            logger.error("newrelic event POST failed: %s", e)

    def flush(self, metrics: List[InterMetric]) -> None:
        out = []
        checks = []
        for m in metrics:
            if m.type == MetricType.STATUS:
                # service checks -> custom events with status name
                # (reference metric.go:173-196)
                code = int(m.value)
                checks.append({
                    "eventType": self.service_check_event_type,
                    "name": m.name,
                    "timestamp": m.timestamp,
                    "statusCode": code,
                    "status": _STATUS_NAMES.get(code, "UNKNOWN"),
                    "host": m.hostname or self.hostname,
                    **_attributes(m.tags),
                })
                continue
            entry = {
                "name": m.name,
                "value": m.value,
                "timestamp": m.timestamp,
                "attributes": {"host": m.hostname or self.hostname,
                               **_attributes(m.tags)},
            }
            if m.type == MetricType.COUNTER:
                entry["type"] = "count"
                entry["interval.ms"] = int(self.interval * 1000)
            else:
                entry["type"] = "gauge"
            out.append(entry)
        self._post_events(checks, "service checks")
        if not out:
            return
        payload = [{"common": {"attributes": self.common_tags},
                    "metrics": out}]
        try:
            vhttp.post_json(self.metric_url, payload,
                            headers={"Api-Key": self.insert_key},
                            compress="gzip", timeout=self.timeout)
        except Exception as e:
            logger.error("newrelic metric POST failed: %s", e)

    def flush_other_samples(self, samples: Sequence) -> None:
        """DogStatsD events -> NR custom events with the configured
        eventType and flattened tags (reference metric.go:210-246)."""
        events = []
        for s in samples:
            evt = {
                "eventType": self.event_type,
                "name": getattr(s, "name", ""),
                "timestamp": getattr(s, "timestamp", 0),
                "message": getattr(s, "message", ""),
            }
            for k, v in dict(getattr(s, "tags", {}) or {}).items():
                evt[k] = v
            events.append(evt)
        self._post_events(events, "events")


class NewRelicSpanSink(SpanSink):
    def __init__(self, name: str, insert_key: str, trace_url: str,
                 common_tags: Sequence[str] = (), timeout: float = 10.0,
                 max_buffered: int = 16384):
        self._name = name
        self.insert_key = insert_key
        self.trace_url = trace_url
        self.common_tags = _attributes(common_tags)
        self.timeout = timeout
        self._spans: List[dict] = []
        self._lock = threading.Lock()
        # bounded between flushes; overflow drops (and counts) rather
        # than growing without limit under sustained span load
        self.max_buffered = max_buffered
        self.dropped_total = 0

    def name(self) -> str:
        return self._name

    def kind(self) -> str:
        return "newrelic"

    def ingest(self, span) -> None:
        with self._lock:
            if len(self._spans) >= self.max_buffered:
                self.dropped_total += 1
                return
        duration_ms = max(span.end_timestamp - span.start_timestamp, 0) / 1e6
        entry = {
            "id": format(span.id & ((1 << 64) - 1), "x"),
            "trace.id": format(span.trace_id & ((1 << 64) - 1), "x"),
            "timestamp": span.start_timestamp // 10**6,
            "attributes": {
                "name": span.name,
                "service.name": span.service,
                "duration.ms": duration_ms,
                "error": bool(span.error),
                **dict(span.tags),
            },
        }
        if span.parent_id:
            entry["attributes"]["parent.id"] = format(
                span.parent_id & ((1 << 64) - 1), "x")
        with self._lock:
            if len(self._spans) >= self.max_buffered:
                self.dropped_total += 1
                return
            self._spans.append(entry)
        # (bound re-checked above after building the entry: another
        # thread may have filled the buffer in between)

    def flush(self) -> None:
        import time as _time

        flush_start = _time.perf_counter()
        dropped = 0
        with self._lock:
            spans, self._spans = self._spans, []
            # reset only once the count can actually be reported, so an
            # operator inspecting dropped_total without a statsd client
            # still sees the cumulative number
            if getattr(self, "_statsd", None) is not None                     and self.dropped_total:
                dropped, self.dropped_total = self.dropped_total, 0
        if not spans:
            self.emit_flush_self_metrics(0, flush_start, dropped)
            return
        payload = [{"common": {"attributes": self.common_tags},
                    "spans": spans}]
        try:
            vhttp.post_json(self.trace_url, payload,
                            headers={"Api-Key": self.insert_key},
                            compress="gzip", timeout=self.timeout)
        except Exception as e:
            logger.error("newrelic trace POST failed: %s", e)
            self.emit_flush_self_metrics(0, flush_start,
                                         dropped + len(spans))
            return
        self.emit_flush_self_metrics(len(spans), flush_start, dropped)


@register_metric_sink("newrelic")
def _metric_factory(sink_config, server_config):
    c = sink_config.config
    return NewRelicMetricSink(
        sink_config.name or "newrelic",
        insert_key=str(c.get("insert_key", "")),
        hostname=server_config.hostname,
        interval=server_config.interval,
        metric_url=c.get("metric_url",
                         "https://metric-api.newrelic.com/metric/v1"),
        tags=c.get("common_tags", []) or [],
        account_id=int(c.get("account_id", 0)),
        event_type=c.get("event_type", DEFAULT_EVENT_TYPE),
        service_check_event_type=c.get(
            "service_check_event_type", DEFAULT_SERVICE_CHECK_EVENT_TYPE),
        event_url=c.get("event_url", ""))


@register_span_sink("newrelic")
def _span_factory(sink_config, server_config):
    c = sink_config.config
    # trace_observer_url (Infinite Tracing) overrides the standard trace
    # API endpoint when set (reference span.go:22,62)
    trace_url = (c.get("trace_observer_url", "")
                 or c.get("trace_url",
                          "https://trace-api.newrelic.com/trace/v1"))
    return NewRelicSpanSink(
        sink_config.name or "newrelic",
        insert_key=str(c.get("insert_key", "")),
        trace_url=trace_url,
        common_tags=c.get("common_tags", []) or [],
        max_buffered=int(c.get("span_buffer_max", 16384)))
