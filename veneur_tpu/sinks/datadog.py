"""Datadog sink: metrics, events, service checks, and APM spans.

Behavioral parity with reference sinks/datadog/datadog.go (660 LoC):
- InterMetrics serialize to DDMetric JSON; counters convert to Datadog
  "rate" (value/interval) (datadog.go DDMetric conversion), gauges stay
  gauges, status checks go to /api/v1/check_run.
- A flush is chunked across `flush_max_per_body` and POSTed in parallel
  (reference chunks across num_workers goroutines, datadog.go:182-207).
- `device:` / `host:` magic tags move into dedicated DDMetric fields.
- Events (from flush_other_samples) post to the events intake.
- Spans buffer in a bounded ring (2^14, reference datadog.go spanBuffer)
  and flush to the APM traces endpoint.
"""

from __future__ import annotations

import collections
import logging
import threading
from typing import Any, Dict, List, Sequence

from veneur_tpu.samplers.metrics import InterMetric, MetricType
from veneur_tpu.sinks import (
    MetricSink, SpanSink, register_metric_sink, register_span_sink,
)
from veneur_tpu.util import http as vhttp

logger = logging.getLogger("veneur_tpu.sinks.datadog")

DATADOG_SPAN_BUFFER_CAP = 1 << 14  # reference datadog.go datadogSpanBufferSize


class DatadogMetricSink(MetricSink):
    def __init__(self, name: str, api_key: str, api_url: str, hostname: str,
                 interval: float, flush_max_per_body: int = 25_000,
                 num_workers: int = 4, tags: Sequence[str] = (),
                 metric_name_prefix_drops: Sequence[str] = (),
                 excluded_tag_prefixes: Sequence[str] = (),
                 exclude_tags_prefix_by_prefix_metric: Dict[str, Sequence[str]] = None,
                 timeout: float = 10.0):
        self._name = name
        self.api_key = api_key
        self.api_url = api_url.rstrip("/")
        self.hostname = hostname
        self.interval = max(interval, 1e-9)
        self.flush_max_per_body = flush_max_per_body
        self.num_workers = num_workers
        self.tags = list(tags)
        # reference datadog.go:313-317: drop whole metrics by name prefix
        self.metric_name_prefix_drops = list(metric_name_prefix_drops)
        # reference datadog.go:345-352: drop tags by prefix, globally
        self.excluded_tag_prefixes = list(excluded_tag_prefixes)
        # reference datadog.go:323-331: per-metric-prefix tag exclusion
        self.exclude_tags_prefix_by_prefix_metric = dict(
            exclude_tags_prefix_by_prefix_metric or {})
        self.timeout = timeout
        self._encoder = None  # DatadogColumnarEncoder, built lazily

    def name(self) -> str:
        return self._name

    def kind(self) -> str:
        return "datadog"

    # -- serialization ----------------------------------------------------

    def _dd_metric(self, m: InterMetric) -> Dict[str, Any]:
        tags = list(self.tags)
        host = m.hostname or self.hostname
        device = ""
        per_metric_excludes: Sequence[str] = ()
        for prefix, excludes in self.exclude_tags_prefix_by_prefix_metric.items():
            if m.name.startswith(prefix):
                per_metric_excludes = excludes
                break
        for t in m.tags:
            if t.startswith("host:"):
                host = t[5:]
            elif t.startswith("device:"):
                device = t[7:]
            elif (any(t.startswith(p) for p in self.excluded_tag_prefixes)
                  or any(t.startswith(p) for p in per_metric_excludes)):
                continue
            else:
                tags.append(t)
        if m.type == MetricType.COUNTER:
            # Datadog rate: counts divide by the flush interval
            dd_type, value = "rate", m.value / self.interval
        else:
            dd_type, value = "gauge", m.value
        out = {
            "metric": m.name,
            "points": [[m.timestamp, value]],
            "type": dd_type,
            "host": host,
            "interval": int(self.interval) or 1,
            "tags": tags,
        }
        if device:
            out["device"] = device
        return out

    # -- flush ------------------------------------------------------------

    def flush(self, metrics: List[InterMetric]) -> None:
        import time as _time

        # single encode pass: name-prefix drop, status split, and
        # series conversion fold into one scan of the metric list
        t0 = _time.perf_counter()
        drops = self.metric_name_prefix_drops
        checks: List[InterMetric] = []
        series: List[dict] = []
        for m in metrics:
            if drops and any(m.name.startswith(p) for p in drops):
                continue
            if m.type == MetricType.STATUS:
                checks.append(m)
            else:
                series.append(self._dd_metric(m))
        encode_s = _time.perf_counter() - t0
        t1 = _time.perf_counter()
        if series:
            chunks = [series[i:i + self.flush_max_per_body]
                      for i in range(0, len(series), self.flush_max_per_body)]
            self._post_parallel(chunks, self._post_series_safe)
        self._post_checks(checks)
        self.note_egress(encode_s, _time.perf_counter() - t1,
                         encoder="legacy")

    def flush_batch(self, batch) -> None:
        try:
            self.flush_columnar(batch)
        except Exception:
            logger.exception("datadog columnar flush failed; "
                             "falling back to materialize()")
            self.flush(batch.materialize())

    def flush_columnar(self, batch) -> None:
        """Columnar fast path: pre-encoded JSON series parts straight
        from the FlushBatch arrays (core/egress.py), gzip-POSTed as raw
        bodies — no per-InterMetric dicts, no json.dumps of the flush."""
        import time as _time

        from veneur_tpu.core.egress import DatadogColumnarEncoder

        t0 = _time.perf_counter()
        enc = self._encoder
        if enc is None:
            enc = self._encoder = DatadogColumnarEncoder(self)
        parts, checks = enc.encode(batch)
        encode_s = _time.perf_counter() - t0
        t1 = _time.perf_counter()
        if parts:
            bodies = [b'{"series":[' +
                      b",".join(parts[i:i + self.flush_max_per_body]) +
                      b"]}"
                      for i in range(0, len(parts),
                                     self.flush_max_per_body)]
            self._post_parallel(bodies, self._post_series_body_safe)
        self._post_checks(checks)
        self.note_egress(encode_s, _time.perf_counter() - t1)

    def _post_parallel(self, chunks, post_one) -> None:
        # concurrency capped at num_workers POSTs (reference
        # datadog.go:182-207 chunks a flush across num_workers)
        it = iter(chunks)

        def worker():
            while True:
                try:
                    chunk = next(it)
                except StopIteration:
                    return
                post_one(chunk)

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(min(self.num_workers, len(chunks)) - 1)]
        for t in threads:
            t.start()
        worker()
        for t in threads:
            t.join()

    def _post_checks(self, checks: List[InterMetric]) -> None:
        for check in checks:
            self._post_safe("/api/v1/check_run", {
                "check": check.name,
                "host_name": check.hostname or self.hostname,
                "status": int(check.value),
                "message": check.message,
                "timestamp": check.timestamp,
                "tags": list(self.tags) + list(check.tags),
            })

    def _post_series_body_safe(self, body: bytes) -> None:
        url = f"{self.api_url}/api/v1/series?api_key={self.api_key}"
        try:
            vhttp.post(url, body, compress="gzip", timeout=self.timeout)
        except Exception as e:
            logger.error("datadog POST /api/v1/series failed: %s", e)

    def _post_series_safe(self, series: List[dict]) -> None:
        self._post_safe("/api/v1/series", {"series": series})

    def _post_safe(self, path: str, payload: dict) -> None:
        url = f"{self.api_url}{path}?api_key={self.api_key}"
        try:
            vhttp.post_json(url, payload, compress="gzip",
                            timeout=self.timeout)
        except Exception as e:
            logger.error("datadog POST %s failed: %s", path, e)

    # -- events / service checks -----------------------------------------

    def flush_other_samples(self, samples: Sequence[Any]) -> None:
        """DogStatsD events -> the nonpublic events intake (reference
        datadog.go FlushOtherSamples)."""
        events = []
        for s in samples:
            tags = dict(getattr(s, "tags", {}) or {})
            events.append({
                "title": getattr(s, "name", ""),
                "text": getattr(s, "message", ""),
                "date_happened": getattr(s, "timestamp", 0),
                "hostname": tags.pop("host", self.hostname),
                "aggregation_key": tags.pop("aggregation_key", ""),
                "priority": tags.pop("priority", "normal"),
                "source_type_name": tags.pop("source_type_name", ""),
                "alert_type": tags.pop("alert_type", "info"),
                "tags": [f"{k}:{v}" if v else k for k, v in tags.items()]
                + list(self.tags),
            })
        if events:
            self._post_safe("/intake", {"events": {self._name: events}})


# timestamp plausibility window, adapted to this pipeline's nanosecond
# span timestamps (the reference's constants at datadog.go:536-538 target
# second-scale values): spans outside 2001..2100 count as scale errors
_SPAN_TS_TOO_EARLY = 978_307_200 * 10**9
_SPAN_TS_TOO_LATE = 4_102_444_800 * 10**9

_DD_SPAN_TYPE = "web"  # reference datadog.go:31 datadogSpanType
_DD_RESOURCE_KEY = "resource"  # datadog.go:27


class DatadogSpanSink(SpanSink):
    """Bounded span ring -> Datadog APM traces (reference datadog.go
    span path, :453-660): the ring overwrites its oldest entry when full
    (overflow is counted, not blocked on), flush converts each span to
    the DD trace-span shape — resource tag promoted out of meta with an
    "unknown" default, root spans get parent_id 0, errors map to code 2,
    span type "web" — groups spans by trace id, and PUTs the
    two-dimensional trace array uncompressed (the traces endpoint does
    not accept compressed bodies). Flush self-metrics match the
    reference sink keys: sink.spans_flushed_total (tagged per service)
    and sink.span_flush_total_duration_ns."""

    def __init__(self, name: str, trace_api_url: str, hostname: str,
                 buffer_size: int = DATADOG_SPAN_BUFFER_CAP,
                 timeout: float = 10.0):
        self._name = name
        self.trace_api_url = trace_api_url.rstrip("/")
        self.hostname = hostname
        self.buffer: "collections.deque" = collections.deque(maxlen=buffer_size)
        self.timeout = timeout
        self._lock = threading.Lock()
        self.overwritten_total = 0  # ring overflow accounting
        self.timestamp_errors = 0

    def name(self) -> str:
        return self._name

    def kind(self) -> str:
        return "datadog"

    def ingest(self, span) -> None:
        if not span.trace_id:
            return
        with self._lock:
            if len(self.buffer) == self.buffer.maxlen:
                # ring semantics: the append below evicts the oldest
                self.overwritten_total += 1
            self.buffer.append(span)

    def ingest_many(self, spans) -> None:
        good = [s for s in spans if s.trace_id]
        if not good:
            return
        with self._lock:
            room = self.buffer.maxlen - len(self.buffer)
            if len(good) > room:
                self.overwritten_total += len(good) - room
            self.buffer.extend(good)

    def _to_dd_span(self, s) -> dict:
        meta = dict(s.tags)
        resource = meta.pop(_DD_RESOURCE_KEY, "") or "unknown"
        if (s.start_timestamp < _SPAN_TS_TOO_EARLY
                or s.start_timestamp > _SPAN_TS_TOO_LATE):
            self.timestamp_errors += 1
        return {
            "trace_id": s.trace_id,
            "span_id": s.id,
            "parent_id": max(s.parent_id, 0),  # root spans -> 0
            "service": s.service,
            "name": s.name or "unknown",
            "resource": resource,
            "start": s.start_timestamp,
            "duration": max(s.end_timestamp - s.start_timestamp, 0),
            "type": _DD_SPAN_TYPE,
            "error": 2 if s.error else 0,
            "meta": meta,
            # numeric span tags; always present in the DD wire shape
            # (reference DatadogTraceSpan.Metrics, datadog.go:434)
            "metrics": {},
        }

    def flush(self) -> None:
        import time as _time

        flush_start = _time.perf_counter()
        with self._lock:
            spans, self.buffer = list(self.buffer), collections.deque(
                maxlen=self.buffer.maxlen)
        if not spans:
            return
        traces: Dict[int, List[dict]] = {}
        service_counts: Dict[str, int] = {}
        for s in spans:
            traces.setdefault(s.trace_id, []).append(self._to_dd_span(s))
            service_counts[s.service] = service_counts.get(s.service, 0) + 1
        try:
            vhttp.put_json(f"{self.trace_api_url}/v0.3/traces",
                           list(traces.values()), timeout=self.timeout)
        except Exception as e:
            logger.error("datadog trace PUT failed: %s", e)
            return
        statsd = getattr(self, "_statsd", None)
        if statsd is not None:
            # per-service flushed counts are datadog-specific (reference
            # datadog.go:654); duration + ring-overwrite drops go through
            # the shared helper
            for service, count in service_counts.items():
                statsd.count(
                    "sink.spans_flushed_total", count,
                    tags=[f"sink:{self._name}", f"service:{service}"])
            ts_errors, self.timestamp_errors = self.timestamp_errors, 0
            if ts_errors:
                statsd.count(
                    "worker.trace.sink.timestamp_error", ts_errors,
                    tags=[f"sink:{self._name}"])
            dropped, self.overwritten_total = self.overwritten_total, 0
            if dropped:
                statsd.count("sink.spans_dropped_total", dropped,
                             tags=[f"sink:{self._name}"])
            statsd.gauge(
                "sink.span_flush_total_duration_ns",
                int((_time.perf_counter() - flush_start) * 1e9),
                tags=[f"sink:{self._name}"])


@register_metric_sink("datadog")
def _metric_factory(sink_config, server_config):
    c = sink_config.config
    return DatadogMetricSink(
        sink_config.name or "datadog",
        api_key=str(c.get("datadog_api_key", c.get("api_key", ""))),
        api_url=c.get("datadog_api_hostname",
                      c.get("api_hostname",
                            "https://app.datadoghq.com")),
        hostname=server_config.hostname,
        interval=server_config.interval,
        flush_max_per_body=int(c.get("datadog_flush_max_per_body", 25_000)),
        num_workers=int(c.get("datadog_num_workers",
                              server_config.num_workers) or 4),
        tags=c.get("tags", []) or [],
        metric_name_prefix_drops=c.get(
            "datadog_metric_name_prefix_drops", []) or [],
        excluded_tag_prefixes=c.get("datadog_excluded_tags", []) or [],
        exclude_tags_prefix_by_prefix_metric={
            str(e.get("metric_prefix", "")): list(e.get("tags", []) or [])
            for e in (c.get(
                "datadog_exclude_tags_prefix_by_prefix_metric", []) or [])})


@register_span_sink("datadog")
def _span_factory(sink_config, server_config):
    c = sink_config.config
    return DatadogSpanSink(
        sink_config.name or "datadog",
        trace_api_url=c.get("datadog_trace_api_address",
                            "http://127.0.0.1:8126"),
        hostname=server_config.hostname,
        buffer_size=int(c.get("datadog_span_buffer_size",
                              DATADOG_SPAN_BUFFER_CAP)))
