"""Secret string wrapper that redacts on serialization
(reference util/stringSecret.go behavior: marshals as "REDACTED")."""

from __future__ import annotations


class StringSecret:
    __slots__ = ("value",)

    REDACTED = "REDACTED"

    def __init__(self, value: str = ""):
        self.value = value

    def __bool__(self) -> bool:
        return bool(self.value)

    def __str__(self) -> str:
        return self.REDACTED if self.value else ""

    def __repr__(self) -> str:
        return f"StringSecret({self.REDACTED if self.value else ''!r})"

    def __eq__(self, other) -> bool:
        if isinstance(other, StringSecret):
            return self.value == other.value
        return NotImplemented

    def reveal(self) -> str:
        return self.value
