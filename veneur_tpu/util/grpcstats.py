"""Per-RPC latency and error accounting for gRPC servers.

Parity with reference proxy/grpcstats/server.go: every RPC is timed and
counted by method and outcome, and the aggregates are emitted as
self-metrics (rpc.count / rpc.duration_ns / rpc.errors in the reference).
Handlers are wrapped explicitly (the servers here build their method
handlers by hand), which keeps the recorder independent of grpc's
interceptor API.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict


class RpcStats:
    """Thread-safe per-method RPC aggregates."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stats: Dict[str, Dict[str, float]] = {}

    def record(self, method: str, duration_s: float, ok: bool) -> None:
        with self._lock:
            s = self._stats.setdefault(method, {
                "count": 0, "errors": 0, "total_s": 0.0, "max_s": 0.0})
            s["count"] += 1
            if not ok:
                s["errors"] += 1
            s["total_s"] += duration_s
            s["max_s"] = max(s["max_s"], duration_s)

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {k: dict(v) for k, v in self._stats.items()}

    def drain(self) -> Dict[str, Dict[str, float]]:
        """Snapshot-and-reset: the interval's deltas (so repeated emits
        never re-count earlier RPCs)."""
        with self._lock:
            out, self._stats = self._stats, {}
            return out

    def emit(self, statsd, prefix: str = "rpc") -> None:
        """Emit one interval's deltas through a scopedstatsd-style client
        (gauge / count interface), tagged by method — the reference's
        grpcstats metric surface. Resets the aggregates, so each flush
        emits only what happened since the previous one."""
        for method, s in self.drain().items():
            tags = [f"method:{method}"]
            statsd.count(f"{prefix}.count", int(s["count"]), tags=tags)
            statsd.count(f"{prefix}.errors", int(s["errors"]), tags=tags)
            avg_ns = (s["total_s"] / s["count"] * 1e9) if s["count"] else 0
            statsd.gauge(f"{prefix}.avg_duration_ns", int(avg_ns), tags=tags)
            statsd.gauge(f"{prefix}.max_duration_ns",
                         int(s["max_s"] * 1e9), tags=tags)

    def timed(self, method: str, behavior: Callable) -> Callable:
        """Wrap a gRPC method behavior (request, context) -> response."""
        def wrapped(request_or_iterator, context):
            t0 = time.perf_counter()
            try:
                out = behavior(request_or_iterator, context)
            except Exception:
                self.record(method, time.perf_counter() - t0, ok=False)
                raise
            self.record(method, time.perf_counter() - t0, ok=True)
            return out

        return wrapped
