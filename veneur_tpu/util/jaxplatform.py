"""Force-platform recipe for environments that pin a TPU plugin.

Hosting environments may register an accelerator plugin via sitecustomize
at interpreter startup AND pin `jax_platforms` programmatically, so
selecting a platform requires overriding BOTH the environment and the jax
config before any backend initializes. This module is the single home of
that recipe (used by __graft_entry__.dryrun_multichip, bench.py's CPU
fallback, and mirrored by tests/conftest.py, which must stay import-free
of this package).
"""

from __future__ import annotations

import os
import re


def force_cpu(n_devices: int | None = None) -> None:
    """Pin JAX to the host platform, optionally with `n_devices` virtual
    devices (xla_force_host_platform_device_count). Must be called before
    the first backend use; safe to call whether or not jax is imported."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+", "", flags)
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")


def honor_env_platform() -> None:
    """Re-assert JAX_PLATFORMS from the environment over any programmatic
    pin the host's sitecustomize applied (env alone loses to
    jax.config.update done at interpreter startup)."""
    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    import jax

    jax.config.update("jax_platforms", want)
