"""FNV-1a hashing, used for metric-key digests and worker sharding.

Behavioral parity: the reference keys workers by a 32-bit fnv1a digest of
name, type and joined tags (reference samplers/parser.go:44-61 via
segmentio/fasthash). We additionally provide a 64-bit variant used as the
host dictionary key for the device column store (lower collision rate) and
for HLL member hashing.
"""

_FNV32_OFFSET = 0x811C9DC5
_FNV32_PRIME = 0x01000193
_FNV64_OFFSET = 0xCBF29CE484222325
_FNV64_PRIME = 0x100000001B3

_M32 = 0xFFFFFFFF
_M64 = 0xFFFFFFFFFFFFFFFF


def fnv1a_32(data: bytes, h: int = _FNV32_OFFSET) -> int:
    for b in data:
        h = ((h ^ b) * _FNV32_PRIME) & _M32
    return h


def fnv1a_64(data: bytes, h: int = _FNV64_OFFSET) -> int:
    for b in data:
        h = ((h ^ b) * _FNV64_PRIME) & _M64
    return h


def init32() -> int:
    return _FNV32_OFFSET


def init64() -> int:
    return _FNV64_OFFSET
