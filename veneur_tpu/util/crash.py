"""Crash reporting: the ConsumePanic pattern.

Behavioral parity with reference sentry.go:22-60: every long-lived
goroutine (thread here) wraps its body in ConsumePanic, which reports
the exception (to a pluggable reporter — Sentry in the reference, a
structured log + optional hook here), flushes, then re-raises so the
process dies loudly and the supervisor restarts it (crash = recovery,
SURVEY §5). A logging hook forwards every ERROR+ record to the reporter
(reference cmd/veneur/main.go:71-79 logrus hook).
"""

from __future__ import annotations

import functools
import logging
import threading
import traceback
from typing import Callable, List, Optional

logger = logging.getLogger("veneur_tpu.crash")

# pluggable reporter: receives (exc, formatted traceback)
_reporters: List[Callable[[BaseException, str], None]] = []


def register_reporter(cb: Callable[[BaseException, str], None]) -> None:
    _reporters.append(cb)


def clear_reporters() -> None:
    _reporters.clear()


def consume_panic(exc: BaseException) -> None:
    """Report a fatal exception to every reporter, then re-raise."""
    tb = "".join(traceback.format_exception(
        type(exc), exc, exc.__traceback__))
    logger.critical("panic: %s\n%s", exc, tb)
    for reporter in list(_reporters):
        try:
            reporter(exc, tb)
        except Exception:
            logger.exception("crash reporter failed")
    raise exc


def guarded(fn: Callable) -> Callable:
    """Wrap a thread body so fatal exceptions hit consume_panic."""
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except Exception as e:  # noqa: BLE001
            consume_panic(e)
    return wrapper


class ReportingHandler(logging.Handler):
    """Forwards ERROR+ log records to the crash reporters (non-fatal;
    the reference's logrus Sentry hook)."""

    def __init__(self, level=logging.ERROR):
        super().__init__(level)

    def emit(self, record: logging.LogRecord) -> None:
        for reporter in list(_reporters):
            try:
                exc = record.exc_info[1] if record.exc_info else None
                reporter(exc or RuntimeError(record.getMessage()),
                         self.format(record))
            except Exception:
                pass


def spawn_guarded(target: Callable, name: str = "",
                  daemon: bool = True, args=()) -> threading.Thread:
    t = threading.Thread(target=guarded(target), name=name,
                         daemon=daemon, args=args)
    t.start()
    return t
