"""Scoped self-metrics client.

Behavioral parity with reference scopedstatsd/client.go:13-119: a statsd
client wrapper that appends the `veneurlocalonly` / `veneurglobalonly`
magic tag to each metric according to per-method scope configuration
(`veneur_metrics_scopes`: gauges default local, counts default global),
plus `veneur_metrics_additional_tags` on everything. Metrics emit as
DogStatsD packets to `stats_address`, or into a callback (the server's
internal loopback, so self-metrics re-enter its own pipeline).
"""

from __future__ import annotations

import socket
import time
from typing import Callable, Dict, List, Optional, Sequence

from veneur_tpu.protocol.render import render_metric_packet

TAG_LOCAL_ONLY = "veneurlocalonly"
TAG_GLOBAL_ONLY = "veneurglobalonly"

_SCOPE_TAGS = {"local": TAG_LOCAL_ONLY, "global": TAG_GLOBAL_ONLY}


class ScopedClient:
    def __init__(self, address: str = "",
                 packet_cb: Optional[Callable[[bytes], None]] = None,
                 scopes: Optional[Dict[str, str]] = None,
                 additional_tags: Sequence[str] = (),
                 registry=None):
        """scopes maps metric kind to "local"/"global"/"" using the
        reference's YAML keys — "counter"/"gauge"/"histogram" (config.go
        VeneurMetricsScopes; timings scope by Histogram, scopedstatsd/
        client.go:91-110). The pre-parity aliases "count"/"timing" stay
        accepted.

        `registry` is an optional core.telemetry.Registry every emission
        tees into (with the caller's tags, before scope/additional tags)
        so the pull endpoints see each self-metric without any call-site
        rewrites — including on NullClient, which drops the push half."""
        scopes = dict(scopes or {})
        for ref_key, alias in (("counter", "count"), ("histogram", "timing")):
            if ref_key not in scopes and alias in scopes:
                scopes[ref_key] = scopes[alias]
        self.scopes = scopes
        self.additional_tags = list(additional_tags)
        self.registry = registry
        self._cb = packet_cb
        self._sock = None
        self._addr = None
        if address and packet_cb is None:
            host, _, port = address.rpartition(":")
            self._addr = (host or "127.0.0.1", int(port))
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)

    def _emit(self, name: str, value, kind: str, tags: Sequence[str],
              rate: float) -> None:
        final = list(tags) + self.additional_tags
        scope_tag = _SCOPE_TAGS.get(self.scopes.get(
            {"c": "counter", "g": "gauge", "ms": "histogram"}[kind], ""))
        if scope_tag:
            final.append(scope_tag)
        packet = render_metric_packet(name, value, kind, final, rate)
        if self._cb is not None:
            self._cb(packet)
        elif self._sock is not None:
            try:
                self._sock.sendto(packet, self._addr)
            except OSError:
                pass

    def count(self, name: str, value: int = 1,
              tags: Sequence[str] = (), rate: float = 1.0) -> None:
        if self.registry is not None:
            self.registry.record_statsd(name, int(value), "c", tags, rate)
        self._emit(name, int(value), "c", tags, rate)

    def gauge(self, name: str, value: float,
              tags: Sequence[str] = (), rate: float = 1.0) -> None:
        if self.registry is not None:
            self.registry.record_statsd(name, value, "g", tags, rate)
        self._emit(name, value, "g", tags, rate)

    def timing(self, name: str, seconds: float,
               tags: Sequence[str] = (), rate: float = 1.0) -> None:
        if self.registry is not None:
            self.registry.record_statsd(
                name, seconds * 1000, "ms", tags, rate)
        self._emit(name, f"{seconds * 1000:.3f}", "ms", tags, rate)

    def timer(self, name: str, tags: Sequence[str] = ()):
        """Context manager: times the with-block."""
        client = self

        class _Timer:
            def __enter__(self):
                self.start = time.perf_counter()
                return self

            def __exit__(self, *exc):
                client.timing(name, time.perf_counter() - self.start, tags)

        return _Timer()

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None


class NullClient(ScopedClient):
    """Drops every packet (trace.NeutralizeClient analog for tests); a
    registry, when given, still captures — the pull endpoints stay live
    even with no stats_address configured."""

    def __init__(self, registry=None):
        super().__init__(registry=registry)

    def _emit(self, *a, **kw) -> None:
        pass
