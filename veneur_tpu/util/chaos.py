"""Config/env-driven fault injection at the egress AND ingest seams.

Every resilience behavior (retry, breaker trip/recover, carryover,
spill, admission shed, watermark ladder) must be testable
deterministically, without a flaky network under the test. This module
plants three egress seams — `forward_send`, `sink_flush`, `http_post` —
and injects probabilistic errors and delays at them from a SEEDED
generator, so a 30 %-fault soak replays identically run to run.

Ingest-side chaos (PR 3) rides the same plan object:

- `mangle_packets(batch)`: per-packet drop / truncate / duplicate rolls
  (`chaos_ingest_drop_rate` / `chaos_ingest_truncate_rate` /
  `chaos_ingest_duplicate_rate`), applied by the server's packet intake
  before parsing — the UDP pathologies (loss, runt datagrams,
  duplication) without a lossy network under the test. At most one
  action per packet, so a soak can account exactly for every fault.
- `simulated_rss_bytes()`: extra bytes (`chaos_ingest_rss_bytes`,
  settable at runtime via `set_simulated_rss`) the overload watermark
  monitor adds to real RSS — memory pressure on demand, no ballooning.

Two ways to turn it on:

- config: `chaos_enabled: true` plus `chaos_error_rate` / `chaos_delay`
  / `chaos_delay_rate` / `chaos_seams` / `chaos_seed` (each also
  reachable as `VENEUR_CHAOS_*` through the normal env overlay);
- tests: construct a `Chaos` directly and `install()` it (or pass it to
  the component under test).

The server owns its instance (two servers in one test process chaos
independently); the module-global `install()`ed instance backs the
seams with no object to hang state on (util.http.post).
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Dict, Optional, Sequence

logger = logging.getLogger("veneur_tpu.util.chaos")

SEAMS = ("forward_send", "sink_flush", "http_post", "health_probe")


class ChaosError(RuntimeError):
    """The injected fault. Deliberately a plain exception (not an
    RpcError/HTTPError): every egress path must survive arbitrary
    transport blowups, not just the ones it expected."""

    def __init__(self, seam: str):
        super().__init__(f"chaos: injected fault at seam {seam!r}")
        self.seam = seam


class Chaos:
    """One fault-injection plan: per-seam probabilistic error/delay from
    a seeded RNG. Thread-safe; counters are exported as telemetry."""

    def __init__(self, enabled: bool = True, error_rate: float = 0.0,
                 delay_rate: float = 0.0, delay: float = 0.0,
                 seams: Sequence[str] = SEAMS, seed: int = 0,
                 forward_latency_ms: float = 0.0,
                 ingest_drop_rate: float = 0.0,
                 ingest_truncate_rate: float = 0.0,
                 ingest_duplicate_rate: float = 0.0,
                 ingest_rss_bytes: int = 0,
                 ledger_leak: int = 0,
                 reshard_prewarm_delay_s: float = 0.0,
                 reshard_append_fault_nth: int = 0,
                 reshard_cutover_delay_s: float = 0.0,
                 sleep=time.sleep):
        self.enabled = bool(enabled)
        self.error_rate = min(1.0, max(0.0, float(error_rate)))
        self.delay_rate = min(1.0, max(0.0, float(delay_rate)))
        self.delay = max(0.0, float(delay))
        # deterministic slow-destination seam: EVERY forward_send (and
        # every proxy destination send, which shares the seam) sleeps
        # this long before the real I/O — no RNG roll, so hedging
        # latency budgets and health-probe timeouts are testable without
        # a probabilistic soak. Independent of delay/delay_rate.
        self.forward_latency_ms = max(0.0, float(forward_latency_ms))
        self.seams = frozenset(seams or SEAMS)
        self.ingest_drop_rate = min(1.0, max(0.0, float(ingest_drop_rate)))
        self.ingest_truncate_rate = min(
            1.0, max(0.0, float(ingest_truncate_rate)))
        self.ingest_duplicate_rate = min(
            1.0, max(0.0, float(ingest_duplicate_rate)))
        self._ingest_rss_bytes = max(0, int(ingest_rss_bytes))
        # ledger drill: every Nth admitted sample is SILENTLY dropped
        # (no shed accounting) so the flow ledger's conservation check
        # has a deterministic bug to catch. The leak count is kept so
        # the drill itself can assert the ledger found exactly it.
        self.ledger_leak = max(0, int(ledger_leak))
        self._leak_roll = 0
        self.leaked_samples = 0
        # reshard seams (all deterministic, no RNG roll — a kill/restore
        # soak must be able to hit the same crossing every run):
        # - prewarm_delay: sleep injected in the PLAN thread before the
        #   background compile, so a deadline overrun (and the 503 ready
        #   answer it triggers) is reproducible;
        # - append_fault_nth: every Nth reshard WAL range-segment append
        #   raises ChaosError("reshard_append") — the faulted-append
        #   degradation path;
        # - cutover_delay: sleep between the range segments becoming
        #   durable and the merge-back — the widest SIGKILL window where
        #   ALL migrating state exists only in the WAL.
        self.reshard_prewarm_delay_s = max(0.0, float(reshard_prewarm_delay_s))
        self.reshard_append_fault_nth = max(0, int(reshard_append_fault_nth))
        self.reshard_cutover_delay_s = max(0.0, float(reshard_cutover_delay_s))
        self._reshard_append_roll = 0
        self.reshard_faulted_appends = 0
        self.reshard_injected_delays = 0
        self._rng = random.Random(seed)
        self._sleep = sleep
        self._lock = threading.Lock()
        self.injected_errors: Dict[str, int] = {}
        self.injected_delays: Dict[str, int] = {}
        # per-action packet fault counts (drop/truncate/duplicate)
        self.packet_faults: Dict[str, int] = {}

    @classmethod
    def from_config(cls, config) -> Optional["Chaos"]:
        """Build from a Config's chaos_* fields; None when disabled."""
        if not getattr(config, "chaos_enabled", False):
            return None
        return cls(enabled=True,
                   error_rate=config.chaos_error_rate,
                   delay_rate=config.chaos_delay_rate,
                   delay=config.chaos_delay,
                   seams=config.chaos_seams or SEAMS,
                   seed=config.chaos_seed,
                   forward_latency_ms=getattr(
                       config, "chaos_forward_latency_ms", 0.0),
                   ingest_drop_rate=getattr(
                       config, "chaos_ingest_drop_rate", 0.0),
                   ingest_truncate_rate=getattr(
                       config, "chaos_ingest_truncate_rate", 0.0),
                   ingest_duplicate_rate=getattr(
                       config, "chaos_ingest_duplicate_rate", 0.0),
                   ingest_rss_bytes=getattr(
                       config, "chaos_ingest_rss_bytes", 0),
                   ledger_leak=getattr(config, "chaos_ledger_leak", 0),
                   reshard_prewarm_delay_s=getattr(
                       config, "chaos_reshard_prewarm_delay_s", 0.0),
                   reshard_append_fault_nth=getattr(
                       config, "chaos_reshard_append_fault_nth", 0),
                   reshard_cutover_delay_s=getattr(
                       config, "chaos_reshard_cutover_delay_s", 0.0))

    def inject(self, seam: str) -> None:
        """Run the seam: maybe sleep, maybe raise ChaosError. Called on
        the egress thread right before the real I/O."""
        if not self.enabled or seam not in self.seams:
            return
        if self.forward_latency_ms > 0 and seam == "forward_send":
            # deterministic (not rolled) slow-destination delay; counted
            # with the probabilistic delays so a soak's accounting sums
            with self._lock:
                self.injected_delays[seam] = \
                    self.injected_delays.get(seam, 0) + 1
            self._sleep(self.forward_latency_ms / 1000.0)
        with self._lock:
            delay = (self.delay_rate > 0 and self.delay > 0
                     and self._rng.random() < self.delay_rate)
            fail = self.error_rate > 0 and self._rng.random() < self.error_rate
            if delay:
                self.injected_delays[seam] = \
                    self.injected_delays.get(seam, 0) + 1
            if fail:
                self.injected_errors[seam] = \
                    self.injected_errors.get(seam, 0) + 1
        if delay:
            self._sleep(self.delay)
        if fail:
            raise ChaosError(seam)

    # -- ingest-side faults ------------------------------------------------

    @property
    def ingest_faults_planned(self) -> bool:
        return (self.ingest_drop_rate > 0 or self.ingest_truncate_rate > 0
                or self.ingest_duplicate_rate > 0)

    def mangle_packets(self, batch):
        """Apply per-packet drop/truncate/duplicate rolls to a list of
        raw datagrams; returns the surviving (possibly mangled) batch.
        Exactly ONE action fires per packet (one uniform roll against
        stacked rate bands), so a soak's accounting is exact:
        surviving = sent - dropped + duplicated, of which `truncated`
        survive shortened by at least one byte (a single-metric line
        whose every prefix is invalid therefore parse-errors)."""
        if not self.enabled or not self.ingest_faults_planned:
            return batch
        out = []
        d, t = self.ingest_drop_rate, self.ingest_truncate_rate
        u = self.ingest_duplicate_rate
        for pkt in batch:
            with self._lock:
                roll = self._rng.random()
                if roll < d:
                    action = "drop"
                elif roll < d + t:
                    if len(pkt) < 2:
                        # 1-byte packets can't shorten; pass untouched
                        # rather than counting a fault that wasn't
                        out.append(pkt)
                        continue
                    action = "truncate"
                elif roll < d + t + u:
                    action = "duplicate"
                else:
                    out.append(pkt)
                    continue
                self.packet_faults[action] = \
                    self.packet_faults.get(action, 0) + 1
                cut = (1 + self._rng.randrange(len(pkt) - 1)
                       if action == "truncate" else 0)
            if action == "truncate":
                # runt datagram: cut mid-line, never the full packet
                out.append(pkt[:cut])
            elif action == "duplicate":
                out.append(pkt)
                out.append(pkt)
            # drop: the packet simply vanishes (counted above)
        return out

    def leak_sample(self) -> bool:
        """The deliberate silent-drop seam: True for every
        `ledger_leak`-th call (deterministic, no RNG), meaning the
        caller must drop the sample WITHOUT any shed accounting — the
        exact bug class the flow ledger exists to catch."""
        if not self.enabled or self.ledger_leak <= 0:
            return False
        with self._lock:
            self._leak_roll += 1
            if self._leak_roll >= self.ledger_leak:
                self._leak_roll = 0
                self.leaked_samples += 1
                return True
        return False

    # -- reshard seams -----------------------------------------------------

    def reshard_prewarm_delay(self) -> None:
        """Plan-thread crossing: deterministic sleep before the
        background prewarm compile starts."""
        if not self.enabled or self.reshard_prewarm_delay_s <= 0:
            return
        with self._lock:
            self.reshard_injected_delays += 1
        self._sleep(self.reshard_prewarm_delay_s)

    def reshard_append_seam(self) -> None:
        """Cutover crossing: every `reshard_append_fault_nth`-th range
        segment append raises (deterministic counter, no RNG)."""
        if not self.enabled or self.reshard_append_fault_nth <= 0:
            return
        with self._lock:
            self._reshard_append_roll += 1
            if self._reshard_append_roll >= self.reshard_append_fault_nth:
                self._reshard_append_roll = 0
                self.reshard_faulted_appends += 1
                raise ChaosError("reshard_append")

    def reshard_cutover_delay(self) -> None:
        """Handoff crossing: deterministic sleep after the range
        segments are durable, before any state merges back — the
        kill-window trigger for the soak's SIGKILL."""
        if not self.enabled or self.reshard_cutover_delay_s <= 0:
            return
        with self._lock:
            self.reshard_injected_delays += 1
        self._sleep(self.reshard_cutover_delay_s)

    def simulated_rss_bytes(self) -> int:
        """Extra bytes the watermark monitor adds to real RSS."""
        if not self.enabled:
            return 0
        with self._lock:
            return self._ingest_rss_bytes

    def set_simulated_rss(self, nbytes: int) -> None:
        """Dial memory pressure up/down at runtime (soak control)."""
        with self._lock:
            self._ingest_rss_bytes = max(0, int(nbytes))

    def telemetry_rows(self):
        """(name, kind, value, tags) rows for the /metrics collectors."""
        with self._lock:
            rows = [("chaos.injected_errors", "counter", float(n),
                     [f"seam:{seam}"])
                    for seam, n in self.injected_errors.items()]
            rows.extend(("chaos.injected_delays", "counter", float(n),
                         [f"seam:{seam}"])
                        for seam, n in self.injected_delays.items())
            rows.extend(("chaos.packet_faults", "counter", float(n),
                         [f"action:{action}"])
                        for action, n in self.packet_faults.items())
            if self.leaked_samples:
                rows.append(("chaos.ledger_leaked", "counter",
                             float(self.leaked_samples), ()))
            if self.reshard_faulted_appends:
                rows.append(("chaos.injected_errors", "counter",
                             float(self.reshard_faulted_appends),
                             ["seam:reshard_append"]))
            if self.reshard_injected_delays:
                rows.append(("chaos.injected_delays", "counter",
                             float(self.reshard_injected_delays),
                             ["seam:reshard"]))
        return rows


# -- module-global instance (backs seams with no owning object) -----------

_active: Optional[Chaos] = None
_active_lock = threading.Lock()


def install(chaos: Optional[Chaos]) -> None:
    """Make `chaos` the process-global plan (None uninstalls). The server
    installs its instance at start when chaos_enabled, so the http_post
    seam inside util.http sees it too."""
    global _active
    with _active_lock:
        if chaos is not None:
            logger.warning(
                "CHAOS ENABLED: error_rate=%.2f delay_rate=%.2f "
                "delay=%.3fs seams=%s", chaos.error_rate,
                chaos.delay_rate, chaos.delay, sorted(chaos.seams))
        _active = chaos


def active() -> Optional[Chaos]:
    return _active


def inject(seam: str) -> None:
    """Module-level seam: no-op unless a plan is installed."""
    c = _active
    if c is not None:
        c.inject(seam)
