"""Config/env-driven fault injection at the egress seams.

Every resilience behavior (retry, breaker trip/recover, carryover,
spill) must be testable deterministically, without a flaky network under
the test. This module plants three seams — `forward_send`, `sink_flush`,
`http_post` — and injects probabilistic errors and delays at them from a
SEEDED generator, so a 30 %-fault soak replays identically run to run.

Two ways to turn it on:

- config: `chaos_enabled: true` plus `chaos_error_rate` / `chaos_delay`
  / `chaos_delay_rate` / `chaos_seams` / `chaos_seed` (each also
  reachable as `VENEUR_CHAOS_*` through the normal env overlay);
- tests: construct a `Chaos` directly and `install()` it (or pass it to
  the component under test).

The server owns its instance (two servers in one test process chaos
independently); the module-global `install()`ed instance backs the
seams with no object to hang state on (util.http.post).
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Dict, Optional, Sequence

logger = logging.getLogger("veneur_tpu.util.chaos")

SEAMS = ("forward_send", "sink_flush", "http_post")


class ChaosError(RuntimeError):
    """The injected fault. Deliberately a plain exception (not an
    RpcError/HTTPError): every egress path must survive arbitrary
    transport blowups, not just the ones it expected."""

    def __init__(self, seam: str):
        super().__init__(f"chaos: injected fault at seam {seam!r}")
        self.seam = seam


class Chaos:
    """One fault-injection plan: per-seam probabilistic error/delay from
    a seeded RNG. Thread-safe; counters are exported as telemetry."""

    def __init__(self, enabled: bool = True, error_rate: float = 0.0,
                 delay_rate: float = 0.0, delay: float = 0.0,
                 seams: Sequence[str] = SEAMS, seed: int = 0,
                 sleep=time.sleep):
        self.enabled = bool(enabled)
        self.error_rate = min(1.0, max(0.0, float(error_rate)))
        self.delay_rate = min(1.0, max(0.0, float(delay_rate)))
        self.delay = max(0.0, float(delay))
        self.seams = frozenset(seams or SEAMS)
        self._rng = random.Random(seed)
        self._sleep = sleep
        self._lock = threading.Lock()
        self.injected_errors: Dict[str, int] = {}
        self.injected_delays: Dict[str, int] = {}

    @classmethod
    def from_config(cls, config) -> Optional["Chaos"]:
        """Build from a Config's chaos_* fields; None when disabled."""
        if not getattr(config, "chaos_enabled", False):
            return None
        return cls(enabled=True,
                   error_rate=config.chaos_error_rate,
                   delay_rate=config.chaos_delay_rate,
                   delay=config.chaos_delay,
                   seams=config.chaos_seams or SEAMS,
                   seed=config.chaos_seed)

    def inject(self, seam: str) -> None:
        """Run the seam: maybe sleep, maybe raise ChaosError. Called on
        the egress thread right before the real I/O."""
        if not self.enabled or seam not in self.seams:
            return
        with self._lock:
            delay = (self.delay_rate > 0 and self.delay > 0
                     and self._rng.random() < self.delay_rate)
            fail = self.error_rate > 0 and self._rng.random() < self.error_rate
            if delay:
                self.injected_delays[seam] = \
                    self.injected_delays.get(seam, 0) + 1
            if fail:
                self.injected_errors[seam] = \
                    self.injected_errors.get(seam, 0) + 1
        if delay:
            self._sleep(self.delay)
        if fail:
            raise ChaosError(seam)

    def telemetry_rows(self):
        """(name, kind, value, tags) rows for the /metrics collectors."""
        with self._lock:
            rows = [("chaos.injected_errors", "counter", float(n),
                     [f"seam:{seam}"])
                    for seam, n in self.injected_errors.items()]
            rows.extend(("chaos.injected_delays", "counter", float(n),
                         [f"seam:{seam}"])
                        for seam, n in self.injected_delays.items())
        return rows


# -- module-global instance (backs seams with no owning object) -----------

_active: Optional[Chaos] = None
_active_lock = threading.Lock()


def install(chaos: Optional[Chaos]) -> None:
    """Make `chaos` the process-global plan (None uninstalls). The server
    installs its instance at start when chaos_enabled, so the http_post
    seam inside util.http sees it too."""
    global _active
    with _active_lock:
        if chaos is not None:
            logger.warning(
                "CHAOS ENABLED: error_rate=%.2f delay_rate=%.2f "
                "delay=%.3fs seams=%s", chaos.error_rate,
                chaos.delay_rate, chaos.delay, sorted(chaos.seams))
        _active = chaos


def active() -> Optional[Chaos]:
    return _active


def inject(seam: str) -> None:
    """Module-level seam: no-op unless a plan is installed."""
    c = _active
    if c is not None:
        c.inject(seam)
