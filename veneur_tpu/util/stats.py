"""Thread-safe telemetry counters.

Stats increments are read-modify-write; with multiple reader threads,
unlocked `dict[key] += n` loses counts. One small lock serializes all
increments (the reference uses atomics, server.go:921-945); reads return
a consistent snapshot.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict


class StatCounters:
    """A locked counter map. Increment with `inc`; read with `[]` or
    `snapshot()`. Supports seeding initial keys so snapshots always
    include the canonical counters even when zero."""

    def __init__(self, *seed_keys: str):
        self._lock = threading.Lock()
        self._counts: Dict[str, float] = defaultdict(float)
        for key in seed_keys:
            self._counts[key] = 0.0

    def inc(self, key: str, n: float = 1) -> None:
        with self._lock:
            self._counts[key] += n

    def __getitem__(self, key: str) -> float:
        with self._lock:
            return self._counts[key]

    def keys(self):
        with self._lock:
            return list(self._counts.keys())

    def items(self):
        with self._lock:
            return list(self._counts.items())

    def __iter__(self):
        return iter(self.keys())

    def get(self, key: str, default: float = 0.0) -> float:
        with self._lock:
            return self._counts.get(key, default)

    def __contains__(self, key: str) -> bool:
        with self._lock:
            return key in self._counts

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._counts)
