"""Shared egress resilience: retry, circuit breaking, lossless carryover.

veneur bills itself as *distributed and fault-tolerant*, but the seed's
egress paths were fail-and-forget: a dropped forward interval permanently
lost counter deltas, and each destination/sink grew its own ad-hoc
failure counter. This module is the one implementation all egress paths
share:

- `RetryPolicy`: jittered exponential backoff whose total spend is
  bounded by the remaining flush-interval budget — a retry storm can
  never push a flush past its interval.
- `CircuitBreaker`: per-destination closed/open/half-open with a single
  probe in half-open (the classic Nygard shape). Deliberately free of
  I/O: callers ask `allow()` and report `record_success`/
  `record_failure`; `state_code` is exported as a gauge.
- `Carryover`: because every forwarded family merges associatively
  (counters sum, t-digest centroids concatenate-and-recompress — Dunning
  is explicit that the merge is lossless up to compression — HLL
  registers max, gauges last-write-wins), a FAILED forward interval can
  be folded into the next interval's snapshot instead of dropped.
  Bounded to N intervals; beyond that it sheds loudly.

Everything here is stdlib+numpy and thread-safe; no jax, no grpc — the
proxy tier imports this without dragging in the TPU stack.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

logger = logging.getLogger("veneur_tpu.util.resilience")


# --------------------------------------------------------------------------
# RetryPolicy
# --------------------------------------------------------------------------


class RetryPolicy:
    """Jittered exponential backoff bounded by a wall-clock budget.

    `delays(budget)` yields the sleep before each RETRY (so a policy with
    max_attempts=3 yields at most 2 delays). A delay that would overrun
    the remaining budget is never yielded — the caller's last attempt
    always lands inside its flush interval. Full jitter (AWS-style):
    each delay is uniform in (0, min(cap, base * mult**n)], which spreads
    a thundering herd of locals re-forwarding after a global-tier blip.
    """

    def __init__(self, max_attempts: int = 3, base_delay: float = 0.2,
                 max_delay: float = 5.0, multiplier: float = 2.0,
                 rng: Optional[random.Random] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.max_attempts = max(1, int(max_attempts))
        self.base_delay = max(0.0, float(base_delay))
        self.max_delay = max(self.base_delay, float(max_delay))
        self.multiplier = max(1.0, float(multiplier))
        self._rng = rng or random.Random()
        self._clock = clock

    def delays(self, budget: float) -> Iterator[float]:
        """Backoff delays for one operation, stopping when either the
        attempt count or the remaining `budget` (seconds) is exhausted.
        The deadline anchors HERE, not at the first next() — generators
        run lazily, and anchoring on first use would restart the budget
        after the first (possibly budget-consuming) attempt."""
        deadline = self._clock() + max(0.0, budget)

        def gen():
            for n in range(self.max_attempts - 1):
                cap = min(self.max_delay,
                          self.base_delay * self.multiplier ** n)
                delay = self._rng.uniform(0.0, cap) if cap > 0 else 0.0
                if self._clock() + delay >= deadline:
                    return
                yield delay

        return gen()


# --------------------------------------------------------------------------
# CircuitBreaker
# --------------------------------------------------------------------------

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"
# gauge encoding for /metrics: closed=0, open=1, half-open=2
STATE_CODES = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}


class CircuitBreaker:
    """Per-destination closed/open/half-open breaker, single half-open probe.

    - CLOSED: calls flow; `failure_threshold` consecutive failures open it.
    - OPEN: calls are refused for `recovery_time` seconds.
    - HALF_OPEN: exactly one caller wins the probe (`allow()` returns True
      once); its success closes the breaker, its failure re-opens it.

    `is_dispatchable` is the non-consuming check ("would a call stand any
    chance?") for producers that only want to shed while open — it never
    claims the half-open probe.
    """

    def __init__(self, failure_threshold: int = 3,
                 recovery_time: float = 30.0, name: str = "",
                 clock: Callable[[], float] = time.monotonic,
                 on_transition: Optional[
                     Callable[[str, str, str], None]] = None):
        self.name = name
        self.failure_threshold = max(1, int(failure_threshold))
        self.recovery_time = max(0.0, float(recovery_time))
        self._clock = clock
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        self.open_total = 0        # lifetime open transitions
        self.refused_total = 0     # calls refused while open/probing

    # -- state -----------------------------------------------------------

    def _transition(self, new_state: str) -> None:
        old, self._state = self._state, new_state
        if old != new_state:
            if new_state == OPEN:
                self.open_total += 1
                self._opened_at = self._clock()
            logger.info("circuit breaker %s: %s -> %s",
                        self.name or "?", old, new_state)
            if self._on_transition is not None:
                try:
                    self._on_transition(self.name, old, new_state)
                except Exception:
                    pass

    def _tick_locked(self) -> None:
        if (self._state == OPEN
                and self._clock() - self._opened_at >= self.recovery_time):
            self._probe_inflight = False
            self._transition(HALF_OPEN)

    @property
    def state(self) -> str:
        with self._lock:
            self._tick_locked()
            return self._state

    @property
    def state_code(self) -> int:
        return STATE_CODES[self.state]

    @property
    def is_dispatchable(self) -> bool:
        """Non-consuming: False only while OPEN (a half-open breaker is
        dispatchable — somebody may still win the probe)."""
        with self._lock:
            self._tick_locked()
            return self._state != OPEN

    @property
    def likely_dispatchable(self) -> bool:
        """Lock-free fast path for per-metric ROUTING decisions: the
        common healthy case (CLOSED) answers with a single racy state
        read and zero lock round-trips; only an OPEN breaker pays the
        lock (to tick into half-open when recovery has elapsed). Racy
        by design — the send path re-checks `is_dispatchable`
        authoritatively, so a stale answer costs at worst one metric
        routed to a node that sheds it (counted)."""
        if self._state != OPEN:
            return True
        return self.is_dispatchable

    @property
    def consecutive_failures(self) -> int:
        """Current failure streak (0 while healthy) — producers use it
        to stop extending courtesies (blocking waits) to a peer that is
        already failing but hasn't tripped yet."""
        with self._lock:
            return self._failures

    # -- calls -----------------------------------------------------------

    def allow(self) -> bool:
        """May this call proceed? Consumes the half-open probe slot."""
        with self._lock:
            self._tick_locked()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN and not self._probe_inflight:
                self._probe_inflight = True
                return True
            self.refused_total += 1
            return False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probe_inflight = False
            if self._state != CLOSED:
                self._transition(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._probe_inflight = False
            if self._state == HALF_OPEN:
                self._transition(OPEN)
                return
            self._failures += 1
            if self._state == CLOSED and \
                    self._failures >= self.failure_threshold:
                self._transition(OPEN)


# --------------------------------------------------------------------------
# Carryover: associative merge of ForwardableState
# --------------------------------------------------------------------------


def _meta_key(meta) -> Tuple[str, str, str]:
    """Row identity stable across evict/re-intern cycles (RowMeta objects
    are per-row caches and may be recreated between intervals)."""
    return (meta.name, meta.joined_tags, meta.wire_type)


def merge_centroids(means_a: np.ndarray, weights_a: np.ndarray,
                    means_b: np.ndarray, weights_b: np.ndarray,
                    slots: int, compression: float
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """Concatenate two centroid sets and recompress onto the arcsine
    k-scale (the same bucketing batch_tdigest.compact uses on device):
    sort by mean, bucket by floor(k) of each centroid's weighted midpoint
    quantile, segment-reduce. At most `compression`+1 buckets survive, so
    the result always fits back into `slots` (C=128 >= 101). Weight is
    conserved exactly up to float32 summation — the property the
    carryover-equivalence tests pin."""
    means = np.concatenate([np.asarray(means_a, np.float64),
                            np.asarray(means_b, np.float64)])
    weights = np.concatenate([np.asarray(weights_a, np.float64),
                              np.asarray(weights_b, np.float64)])
    live = weights > 0
    means, weights = means[live], weights[live]
    out_m = np.zeros(slots, np.float32)
    out_w = np.zeros(slots, np.float32)
    if weights.size == 0:
        return out_m, out_w
    order = np.argsort(means, kind="stable")
    means, weights = means[order], weights[order]
    total = weights.sum()
    mid_q = (np.cumsum(weights) - weights / 2.0) / total
    k = np.floor(compression * (np.arcsin(np.clip(2.0 * mid_q - 1.0,
                                                  -1.0, 1.0)) / np.pi
                                + 0.5)).astype(np.int64)
    _, inv = np.unique(k, return_inverse=True)
    n = int(inv.max()) + 1
    w_out = np.zeros(n, np.float64)
    wv_out = np.zeros(n, np.float64)
    np.add.at(w_out, inv, weights)
    np.add.at(wv_out, inv, weights * means)
    n = min(n, slots)
    out_w[:n] = w_out[:n]
    out_m[:n] = (wv_out[:n] / w_out[:n])
    return out_m, out_w


def merge_forwardable(newer, older):
    """Merge `older` (a previously failed interval's ForwardableState)
    into `newer` (this interval's snapshot), in place on `newer`:

    - counters: values SUM (they are deltas; this is the lossless part),
    - gauges: last-write-wins — `newer` wins; old-only rows are carried,
    - histograms: centroids concatenate-and-recompress; min/max fold,
      reciprocal sums add,
    - sets: HLL registers take the elementwise max.

    Returns `newer`."""
    from veneur_tpu.ops.batch_tdigest import C, COMPRESSION

    def index(rows) -> Dict[tuple, int]:
        return {_meta_key(meta_val[0]): i
                for i, meta_val in enumerate(rows)}

    idx = index(newer.counters)
    for meta, value in older.counters:
        i = idx.get(_meta_key(meta))
        if i is None:
            newer.counters.append((meta, value))
        else:
            m, v = newer.counters[i]
            newer.counters[i] = (m, v + value)

    idx = index(newer.gauges)
    for meta, value in older.gauges:
        if _meta_key(meta) not in idx:
            newer.gauges.append((meta, value))

    idx = index(newer.histograms)
    for entry in older.histograms:
        meta, means, weights, dmin, dmax, drecip = entry
        i = idx.get(_meta_key(meta))
        if i is None:
            newer.histograms.append(entry)
            continue
        nm, nmeans, nweights, ndmin, ndmax, ndrecip = newer.histograms[i]
        slots = max(C, nmeans.shape[0], means.shape[0])
        mm, ww = merge_centroids(nmeans, nweights, means, weights,
                                 slots, COMPRESSION)
        newer.histograms[i] = (nm, mm, ww, min(ndmin, dmin),
                               max(ndmax, dmax), ndrecip + drecip)

    idx = index(newer.sets)
    for meta, registers in older.sets:
        i = idx.get(_meta_key(meta))
        if i is None:
            newer.sets.append((meta, registers))
        else:
            m, regs = newer.sets[i]
            newer.sets[i] = (m, np.maximum(regs, registers))

    idx = index(newer.llhists)
    for meta, bins in older.llhists:
        # log-linear histograms are the family the carryover story is
        # EXACT for: registers add in int64, no recompression loss
        i = idx.get(_meta_key(meta))
        if i is None:
            newer.llhists.append((meta, bins))
        else:
            m, cur = newer.llhists[i]
            newer.llhists[i] = (m, np.asarray(cur, np.int64)
                                + np.asarray(bins, np.int64))
    return newer


class Carryover:
    """Holds the mergeable state of failed forward intervals and folds it
    into the next interval's snapshot. Bounded: after `max_intervals`
    consecutive failed intervals the pending state is SHED (loudly,
    counted) — under a long outage memory stays O(one interval of keys)
    and staleness is bounded.

    Thread-safe; the forward path is single-threaded per server, but the
    telemetry scraper reads `depth` concurrently.
    """

    def __init__(self, max_intervals: int = 3, spill=None, ledger=None):
        self.max_intervals = max(0, int(max_intervals))
        # flow ledger (core/ledger.py): the carryover is an inventory
        # stock of the forward conservation identity; the EXPLAINED
        # shrinkage when two intervals' rows merge associatively (same
        # key -> one row) is stamped as forward.merged_away, sheds as
        # forward.shed. Notes always fire OUTSIDE self._lock (the
        # ledger lock is a leaf; the ledger's stock probe takes
        # self._lock at interval close).
        self.ledger = ledger
        # optional durable overflow (util/spool.py, wired by the forward
        # client): state that would be SHED at the age bound is handed
        # to `spill(state)` instead — serialized to the on-disk spool
        # and re-delivered when the destination recovers. A spill that
        # raises falls back to the loud shed, never silent loss of the
        # loss-accounting.
        self.spill = spill
        self._lock = threading.Lock()
        self._pending = None          # merged ForwardableState of failures
        self._age = 0                 # consecutive failed intervals held
        self.stashed_total = 0        # intervals stashed
        self.merged_total = 0         # metrics re-merged into a snapshot
        self.shed_total = 0           # metrics dropped at the age bound
        self.spilled_total = 0        # metrics handed to the spill hook

    @property
    def depth(self) -> int:
        """Consecutive failed intervals currently held (0 = clean)."""
        with self._lock:
            return self._age

    @property
    def pending_metrics(self) -> int:
        """Metric rows currently held — the ledger's stock level."""
        with self._lock:
            return len(self._pending) if self._pending is not None else 0

    def _note(self, stage: str, n: int, key: str = "") -> None:
        led = self.ledger
        if led is not None and n:
            led.note(stage, n, key=key)

    def stash(self, fwd) -> None:
        """Remember a failed interval's state. Merges into any pending
        state rather than replacing it: besides the forward thread's
        drain-merge-send-stash cycle, the flush loop stashes intervals
        it could not even dispatch (previous forward still hung), and
        those writers race."""
        overflow = None
        merged_away = 0
        with self._lock:
            if self.max_intervals <= 0:
                self.shed_total += len(fwd)
                logger.error(
                    "carryover disabled: dropping %d forwardable metrics",
                    len(fwd))
                self._note("forward.shed", len(fwd),
                           key="carryover_disabled")
                return
            if self._pending is not None:
                before = len(fwd) + len(self._pending)
                fwd = merge_forwardable(fwd, self._pending)
                merged_away = before - len(fwd)
            # any pre-encoded wire frames describe the UNMERGED state
            if hasattr(fwd, "invalidate_wire"):
                fwd.invalidate_wire()
            self._pending = fwd
            self._age += 1
            self.stashed_total += 1
            if self._age > self.max_intervals:
                overflow, self._pending = self._pending, None
                self._age = 0
        self._note("forward.merged_away", merged_away, key="stash")
        if overflow is None:
            return
        # past the age bound: spill to the durable spool when one is
        # wired, shed loudly otherwise. The spill (serialization + disk
        # write) runs OUTSIDE the lock — telemetry scrapers reading
        # `depth` must never wait on an fsync.
        if self.spill is not None:
            try:
                spilled = self.spill(overflow)
                with self._lock:
                    self.spilled_total += len(overflow)
                if spilled is not None and spilled < len(overflow):
                    # serialization dropped rows (empty digests and the
                    # like): they left the pipeline here, account them
                    self._note("forward.shed", len(overflow) - spilled,
                               key="convert")
                logger.warning(
                    "carryover exceeded %d intervals: spilled %d "
                    "forwardable metrics to the durable spool",
                    self.max_intervals, len(overflow))
                return
            except Exception:
                logger.exception("carryover spill failed; shedding")
        with self._lock:
            self.shed_total += len(overflow)
        self._note("forward.shed", len(overflow), key="carryover_bound")
        logger.error(
            "carryover exceeded %d intervals: shedding %d "
            "forwardable metrics (counter deltas in them are "
            "permanently lost)", self.max_intervals, len(overflow))

    def drain_into(self, fwd):
        """Fold any pending carryover into this interval's snapshot and
        clear it; the caller now owns the merged state (and must stash it
        back if the send fails). Returns `fwd`."""
        with self._lock:
            pending, self._pending = self._pending, None
            age = self._age
        if pending is None:
            return fwd
        self.merged_total += len(pending)
        logger.info("carryover: merging %d metrics from %d failed "
                    "interval(s) into this flush", len(pending), age)
        before = len(fwd) + len(pending)
        fwd = merge_forwardable(fwd, pending)
        # the merge changed row contents: any wire frames pre-encoded on
        # the readout executor are stale, force a re-encode at send time
        if hasattr(fwd, "invalidate_wire"):
            fwd.invalidate_wire()
        self._note("forward.merged_away", before - len(fwd), key="drain")
        return fwd

    def clear_age(self) -> None:
        """A successful send ends the failure streak."""
        with self._lock:
            self._age = 0
