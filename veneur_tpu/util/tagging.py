"""Tag extension: merge operator-configured `extend_tags` into every metric.

Behavioral parity with reference tagging/extend_tags.go: configured tags
override caller tags with the same key prefix (text before the first ':'),
the result is always sorted, empty caller tags are preserved, and empty
configured tags are dropped.
"""

from __future__ import annotations

from typing import Dict, List, Sequence


def parse_tag_slice_to_map(tags: Sequence[str]) -> Dict[str, str]:
    """Split "key:value" tags into a dict; bare "key" maps to ""."""
    out: Dict[str, str] = {}
    for tag in tags:
        if not tag:
            continue
        key, sep, value = tag.partition(":")
        out[key] = value if sep else ""
    return out


class ExtendTags:
    __slots__ = ("extra_tags", "extra_tags_map", "_prefixes")

    def __init__(self, tags: Sequence[str] = ()):
        self.extra_tags: List[str] = sorted(t for t in tags if t)
        self.extra_tags_map = parse_tag_slice_to_map(tags)
        self._prefixes = [t.partition(":")[0] for t in tags if t]

    def _should_drop(self, tag: str) -> bool:
        for pre in self._prefixes:
            if tag == pre:
                return True
            if len(pre) < len(tag) and tag.startswith(pre) and tag[len(pre)] == ":":
                return True
        return False

    def extend(self, tags: Sequence[str]) -> List[str]:
        """Return sorted(tags + configured), configured winning key conflicts."""
        if not tags and not self.extra_tags:
            return []
        if not tags:
            return list(self.extra_tags)
        if not self.extra_tags:
            return sorted(tags)
        ret = [t for t in tags if t == "" or not self._should_drop(t)]
        ret.extend(self.extra_tags)
        ret.sort()
        return ret

    def extend_map(self, tags: Dict[str, str]) -> Dict[str, str]:
        ret = dict(tags)
        ret.update(self.extra_tags_map)
        return ret


EMPTY = ExtendTags()
