"""Minimal protobuf wire-format reading, shared by the hand-rolled
decoders (the OTLP source today; the cortex test decoder and the
llhist/hll wire codecs keep their local specialized forms).

stdlib-only. Varints are bounded (10 bytes / 70 bits of shift) so a
malicious stream cannot spin the decode loop into unbounded bigints.
"""

from __future__ import annotations

from typing import Iterator, Tuple


class WireError(ValueError):
    pass


def get_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    """Decode one varint at `pos`; returns (value, next_pos)."""
    val = 0
    shift = 0
    n = len(buf)
    while True:
        if pos >= n:
            raise WireError("truncated varint")
        b = buf[pos]
        pos += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, pos
        shift += 7
        if shift > 70:
            raise WireError("varint overflow")


def zigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def read_fields(buf: bytes) -> Iterator[Tuple[int, int, object]]:
    """Yield (field_number, wire_type, payload): int for varint (wire
    0), raw 8/4-byte slices for fixed64/fixed32 (wires 1/5), bytes for
    length-delimited (wire 2)."""
    pos = 0
    n = len(buf)
    while pos < n:
        tag_wire, pos = get_varint(buf, pos)
        field, wire = tag_wire >> 3, tag_wire & 7
        if wire == 0:
            val, pos = get_varint(buf, pos)
            yield field, wire, val
        elif wire == 1:
            if pos + 8 > n:
                raise WireError("truncated fixed64")
            yield field, wire, buf[pos:pos + 8]
            pos += 8
        elif wire == 2:
            ln, pos = get_varint(buf, pos)
            if pos + ln > n:
                raise WireError("truncated length-delimited field")
            yield field, wire, buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            if pos + 4 > n:
                raise WireError("truncated fixed32")
            yield field, wire, buf[pos:pos + 4]
            pos += 4
        else:
            raise WireError(f"unsupported wire type {wire}")
