"""HTTP POST helpers shared by HTTP sinks.

Behavioral parity with reference http/http.go (282 LoC): JSON/protobuf
POST with optional gzip/deflate compression, timeout, and a tiny
pure-Python snappy *block-format* encoder for Prometheus remote-write
(reference sinks/cortex/cortex.go uses github.com/golang/snappy).

Everything here is stdlib-only: urllib for transport so sinks work in the
hermetic test environment without `requests`.
"""

from __future__ import annotations

import gzip
import json
import time
import urllib.error
import urllib.request
import zlib
from typing import Any, Dict, Optional, Tuple

# vendor responses worth another attempt: throttling (429) and transient
# unavailability (503); everything else (auth, bad payload, 5xx bugs) is
# structural and retrying it only doubles the damage
RETRYABLE_STATUSES = frozenset((429, 503))


class HTTPError(Exception):
    def __init__(self, status: int, body: bytes = b"",
                 retry_after: Optional[float] = None):
        super().__init__(f"HTTP {status}: {body[:200]!r}")
        self.status = status
        self.body = body
        # parsed Retry-After (seconds), when the server sent one
        self.retry_after = retry_after

    @property
    def retryable(self) -> bool:
        return self.status in RETRYABLE_STATUSES


def _parse_retry_after(value: Optional[str]) -> Optional[float]:
    """Retry-After per RFC 9110: delta-seconds or an HTTP-date."""
    if not value:
        return None
    value = value.strip()
    try:
        return max(0.0, float(value))
    except ValueError:
        pass
    try:
        from email.utils import parsedate_to_datetime
        when = parsedate_to_datetime(value)
        return max(0.0, when.timestamp() - time.time())
    except (TypeError, ValueError):
        return None


def snappy_encode(data: bytes) -> bytes:
    """Encode `data` in snappy block format using only literal elements.

    The snappy format permits a stream consisting entirely of literals
    (no back-references); any conformant decoder accepts it. Layout:
    uvarint(len(data)) then literal chunks. A literal tag byte has low
    bits 00 and encodes lengths <=60 inline; longer literals store the
    length in 1-4 little-endian bytes selected by tag values 60-63.
    """
    out = bytearray()
    # preamble: uncompressed length as uvarint
    n = len(data)
    while n >= 0x80:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)
    pos = 0
    total = len(data)
    while pos < total:
        chunk = data[pos:pos + 65536]
        ln = len(chunk) - 1
        if ln < 60:
            out.append(ln << 2)
        elif ln < (1 << 8):
            out.append(60 << 2)
            out.append(ln)
        else:  # chunk capped at 65536 so two bytes always suffice
            out.append(61 << 2)
            out += ln.to_bytes(2, "little")
        out += chunk
        pos += len(chunk)
    return bytes(out)


def snappy_decode(data: bytes) -> bytes:
    """Decode snappy block format (full format: literals + copies).

    Used only by tests and the cortex test fake; kept complete so any
    real snappy writer's output round-trips too.
    """
    # uvarint preamble
    ulen = 0
    shift = 0
    pos = 0
    while True:
        b = data[pos]
        pos += 1
        ulen |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    out = bytearray()
    while pos < len(data):
        tag = data[pos]
        pos += 1
        elem_type = tag & 0x03
        if elem_type == 0:  # literal
            ln = tag >> 2
            if ln >= 60:
                extra = ln - 59
                ln = int.from_bytes(data[pos:pos + extra], "little")
                pos += extra
            ln += 1
            out += data[pos:pos + ln]
            pos += ln
        elif elem_type == 1:  # copy, 1-byte offset
            ln = ((tag >> 2) & 0x7) + 4
            offset = ((tag >> 5) << 8) | data[pos]
            pos += 1
            _copy(out, offset, ln)
        elif elem_type == 2:  # copy, 2-byte offset
            ln = (tag >> 2) + 1
            offset = int.from_bytes(data[pos:pos + 2], "little")
            pos += 2
            _copy(out, offset, ln)
        else:  # copy, 4-byte offset
            ln = (tag >> 2) + 1
            offset = int.from_bytes(data[pos:pos + 4], "little")
            pos += 4
            _copy(out, offset, ln)
    if len(out) != ulen:
        raise ValueError(f"snappy: length mismatch {len(out)} != {ulen}")
    return bytes(out)


def _copy(out: bytearray, offset: int, length: int) -> None:
    if offset <= 0 or offset > len(out):
        raise ValueError("snappy: bad copy offset")
    for _ in range(length):  # may overlap; copy byte-wise
        out.append(out[-offset])


def post(url: str, body: bytes, *,
         content_type: str = "application/json",
         headers: Optional[Dict[str, str]] = None,
         compress: Optional[str] = None,
         timeout: float = 10.0, method: str = "POST",
         proxy_url: str = "") -> Tuple[int, bytes]:
    """Send `body` (POST by default), optionally compressed
    ("gzip"/"deflate"), returning (status, response body). Raises
    HTTPError on non-2xx. proxy_url routes the request through an
    explicit HTTP(S) proxy, overriding environment proxies."""
    hdrs = {"Content-Type": content_type}
    if compress == "gzip":
        body = gzip.compress(body, compresslevel=6)
        hdrs["Content-Encoding"] = "gzip"
    elif compress == "deflate":
        body = zlib.compress(body, 6)
        hdrs["Content-Encoding"] = "deflate"
    if headers:
        hdrs.update(headers)
    req = urllib.request.Request(url, data=body, headers=hdrs,
                                 method=method)
    opener = urllib.request.urlopen
    if proxy_url:
        opener = urllib.request.build_opener(urllib.request.ProxyHandler(
            {"http": proxy_url, "https": proxy_url})).open
    # fault-injection seam: no-op unless a chaos plan is installed
    from veneur_tpu.util import chaos as chaos_mod
    chaos_mod.inject("http_post")
    try:
        with opener(req, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        raise HTTPError(e.code, e.read(),
                        retry_after=_parse_retry_after(
                            e.headers.get("Retry-After"))) from e


def post_with_retry(url: str, body: bytes, *,
                    retry=None, budget: float = 10.0,
                    **kwargs) -> Tuple[int, bytes]:
    """`post` with the shared backoff policy (util/resilience.py):
    retries 429/503 (honoring Retry-After), connection errors, and
    injected chaos, never spending more than `budget` seconds total —
    sinks call this from their per-sink flush thread, whose own bound is
    one flush interval."""
    from veneur_tpu.util.chaos import ChaosError
    from veneur_tpu.util.resilience import RetryPolicy
    retry = retry or RetryPolicy()
    deadline = time.monotonic() + budget
    delays = retry.delays(budget)
    while True:
        try:
            return post(url, body, **kwargs)
        except (HTTPError, urllib.error.URLError, ChaosError) as e:
            retryable = (isinstance(e, (urllib.error.URLError, ChaosError))
                         or getattr(e, "retryable", False))
            delay = next(delays, None) if retryable else None
            if delay is None:
                raise
            # a server-provided Retry-After overrides (extends) backoff,
            # still inside the budget
            retry_after = getattr(e, "retry_after", None)
            if retry_after:
                delay = max(delay, retry_after)
            if time.monotonic() + delay >= deadline:
                raise
            time.sleep(delay)


def post_json(url: str, obj: Any, *, headers: Optional[Dict[str, str]] = None,
              compress: Optional[str] = "gzip",
              timeout: float = 10.0) -> Tuple[int, bytes]:
    return post(url, json.dumps(obj, separators=(",", ":")).encode(),
                headers=headers, compress=compress, timeout=timeout)


def put_json(url: str, obj: Any, *,
             headers: Optional[Dict[str, str]] = None,
             timeout: float = 10.0) -> Tuple[int, bytes]:
    """Uncompressed JSON PUT (the Datadog traces endpoint rejects
    compressed bodies, reference datadog.go:638-643)."""
    return post(url, json.dumps(obj, separators=(",", ":")).encode(),
                headers=headers, compress=None, timeout=timeout,
                method="PUT")


def get(url: str, *, headers: Optional[Dict[str, str]] = None,
        timeout: float = 10.0, ssl_context=None) -> Tuple[int, bytes]:
    req = urllib.request.Request(url, headers=headers or {}, method="GET")
    try:
        with urllib.request.urlopen(req, timeout=timeout,
                                    context=ssl_context) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        raise HTTPError(e.code, e.read()) from e
