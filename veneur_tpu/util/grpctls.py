"""mTLS for the gRPC forward plane (proxy, import server, clients).

Parity with reference proxy/proxy.go:33-120 (the proxy terminates TLS on
its gRPC server and dials destinations with client credentials) and
util/tls.go (cert bundle loading). Like the TCP-ingest TLS config
(core.networking.build_tls_context), every field accepts either an
inline PEM string — matching the reference's YAML — or a file path.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


def _pem_bytes(value) -> Optional[bytes]:
    """Inline PEM or file path -> PEM bytes (None when unset)."""
    if value is None:
        return None
    if hasattr(value, "reveal"):  # StringSecret
        value = value.reveal()
    if not value:
        return None
    if "-----BEGIN" in value:
        return value.encode()
    with open(value, "rb") as f:
        return f.read()


@dataclass
class GrpcTLS:
    """One side's credential bundle.

    certificate/key: this side's cert chain and private key.
    authority: CA bundle used to verify the peer; on the server side its
    presence additionally REQUIRES client certificates (mutual auth),
    mirroring tls_authority_certificate on the TCP plane.
    """

    certificate: str = ""
    key: str = ""
    authority: str = ""

    def __bool__(self) -> bool:
        return bool(self.certificate or self.key or self.authority)

    def server_credentials(self):
        import grpc

        cert, key, ca = (_pem_bytes(self.certificate), _pem_bytes(self.key),
                         _pem_bytes(self.authority))
        if not (cert and key):
            # half-configured TLS must fail loudly, never fall back to
            # plaintext (same stance as build_tls_context)
            raise ValueError(
                "gRPC TLS needs both certificate and key on the server side")
        return grpc.ssl_server_credentials(
            [(key, cert)], root_certificates=ca,
            require_client_auth=ca is not None)

    def channel_credentials(self):
        import grpc

        cert, key, ca = (_pem_bytes(self.certificate), _pem_bytes(self.key),
                         _pem_bytes(self.authority))
        if (cert is None) != (key is None):
            raise ValueError(
                "gRPC client TLS needs certificate and key together")
        return grpc.ssl_channel_credentials(
            root_certificates=ca, private_key=key, certificate_chain=cert)


# Reconnect backoff cap shared by every forward-plane dialer (local
# client AND proxy destinations). Load-bearing for the HA design: grpc's
# default backoff climbs past 20s after an outage, which would keep a
# freshly-restored global looking dead for whole flush intervals /
# probe rounds — recovery must land at probe speed, and both tiers must
# agree on it.
RECONNECT_BACKOFF_OPTIONS = (
    ("grpc.initial_reconnect_backoff_ms", 250),
    ("grpc.min_reconnect_backoff_ms", 250),
    ("grpc.max_reconnect_backoff_ms", 2000),
)


def secure_or_insecure_channel(address: str, tls: Optional[GrpcTLS],
                               **kwargs):
    """Dial helper shared by the forward client and proxy destinations."""
    import grpc

    if tls:
        return grpc.secure_channel(address, tls.channel_credentials(),
                                   **kwargs)
    return grpc.insecure_channel(address, **kwargs)
