"""Durable carryover spill: a bounded on-disk spool of forward intervals.

In-memory carryover (util/resilience.py) is bounded to
`carryover_max_intervals` because an unbounded merge would grow without
limit under a long global-tier outage — but past the bound it SHEDS, and
shed counter deltas are permanently lost. Because every forwarded family
merges associatively and commutatively (counters sum, t-digests
recompress, HLL/llhist registers max/add — the bit-exactness the forward
interop tests pin), a failed interval's state is just as valid delivered
minutes later from disk as seconds later from memory. This module is
that escape hatch: when carryover hits its bound, the merged
ForwardableState is serialized to metricpb wire bytes (the SAME encoding
a forward send uses, `forward.convert.forwardable_to_wire`) and appended
to a bounded directory spool instead of shed.

Segments are drained oldest-first by the forward client once the
destination recovers (each segment body is already a valid
SendMetrics V1 MetricList framing), and a process restart (including
PR 3's SIGUSR2 handoff) simply re-scans the directory — a crash mid-
outage loses nothing that reached disk.

Bounded loudly, like everything else in the resilience layer: past
`max_segments` or `max_bytes` the OLDEST segments are dropped (counted,
logged) so the newest state — the most likely to still matter — wins.

stdlib-only; no jax, no grpc (the caller hands in pre-serialized wire
bytes and gets them back).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import uuid
from typing import List, Optional, Tuple

logger = logging.getLogger("veneur_tpu.util.spool")

_SEGMENT_SUFFIX = ".vspool"
_HEADER_MAX = 4096  # sanity bound on the JSON header line


def frame_metrics(metrics: List[bytes]) -> bytes:
    """Concatenated MetricList `metrics` entries (field 1,
    length-delimited): the V1 forward body framing, inlined here so the
    spool stays grpc-free."""
    out = []
    for b in metrics:
        n = len(b)
        out.append(b"\x0a")
        while n >= 0x80:
            out.append(bytes((n & 0x7F | 0x80,)))
            n >>= 7
        out.append(bytes((n,)))
        out.append(b)
    return b"".join(out)


def unframe_metrics(body: bytes) -> List[bytes]:
    """Inverse of frame_metrics: split a MetricList body back into
    per-Metric wire bytes. Raises ValueError on malformed framing (a
    truncated segment from a crash mid-write never reaches the sender —
    append() is write-tmp-then-rename, so this only fires on external
    corruption)."""
    out: List[bytes] = []
    i, n = 0, len(body)
    while i < n:
        if body[i] != 0x0A:
            raise ValueError(f"bad MetricList frame tag at {i}")
        i += 1
        size = shift = 0
        while True:
            if i >= n:
                raise ValueError("truncated frame length")
            byte = body[i]
            i += 1
            size |= (byte & 0x7F) << shift
            if not byte & 0x80:
                break
            shift += 7
            if shift > 35:
                raise ValueError("frame length varint overflow")
        if i + size > n:
            raise ValueError("truncated frame body")
        out.append(body[i:i + size])
        i += size
    return out


class SpoolSegment:
    """One on-disk spill: a JSON header line + a MetricList body."""

    __slots__ = ("path", "created_unix", "count", "nbytes")

    def __init__(self, path: str, created_unix: float, count: int,
                 nbytes: int):
        self.path = path
        self.created_unix = created_unix
        self.count = count
        self.nbytes = nbytes

    def read_metrics(self) -> List[bytes]:
        with open(self.path, "rb") as f:
            f.readline()  # header
            return unframe_metrics(f.read())


class CarryoverSpool:
    """Bounded directory spool of spilled forward intervals.

    Thread-safe. `append` is called from whatever thread trips the
    carryover bound (the forward thread or the flush loop); `oldest`/
    `pop` from the forward thread's drain; counters from the telemetry
    scraper."""

    def __init__(self, directory: str,
                 max_bytes: int = 256 * 1024 * 1024,
                 max_segments: int = 1024,
                 dwell_hist=None, ledger=None):
        self.directory = directory
        self.max_bytes = max(0, int(max_bytes))
        self.max_segments = max(1, int(max_segments))
        # flow ledger (core/ledger.py): the spool is an inventory stock
        # of the forward conservation identity; bound sheds and
        # quarantines stamp forward.shed so a dropped segment is
        # explained loss, never unexplained imbalance. Notes fire
        # outside self._lock.
        self.ledger = ledger
        # optional latency-observatory llhist: spill->drain dwell rides
        # the shared queue.dwell telemetry under the caller's queue name
        self._dwell_hist = dwell_hist
        self._lock = threading.Lock()
        # serializes whole append() bodies: seq assignment, the disk
        # write, and the publish must be one atomic unit or concurrent
        # spills (forward thread + flush loop both stash) could order
        # _segments out of seq order — and the bound shed would then
        # evict a NEWER segment while believing it took the oldest
        self._append_lock = threading.Lock()
        self._segments: List[SpoolSegment] = []
        self._seq = 0
        self.spilled_total = 0          # segments written
        self.spilled_metrics_total = 0  # metrics across them
        self.drained_total = 0          # segments delivered and removed
        self.drained_metrics_total = 0
        self.shed_total = 0             # segments dropped at the bound
        self.shed_metrics_total = 0
        self.replayed_total = 0         # segments recovered at startup
        os.makedirs(directory, exist_ok=True)
        self._scan()

    # -- startup replay --------------------------------------------------

    def _scan(self) -> None:
        """Recover segments left by a previous process (crash or SIGUSR2
        handoff mid-outage). Unreadable files are quarantined aside, not
        deleted — loud beats silent for data that exists because of a
        failure."""
        found: List[Tuple[str, SpoolSegment]] = []
        for name in os.listdir(self.directory):
            if not name.endswith(_SEGMENT_SUFFIX):
                continue
            path = os.path.join(self.directory, name)
            seg = self._read_header(path)
            if seg is None:
                bad = path + ".corrupt"
                logger.error("spool segment %s unreadable; set aside as %s",
                             path, bad)
                try:
                    os.replace(path, bad)
                except OSError:
                    pass
                continue
            found.append((name, seg))
        found.sort(key=lambda pair: pair[0])  # seq-prefixed names: oldest first
        # seed the sequence PAST everything on disk: a fresh process
        # restarting at seq 1 would interleave its segment names with a
        # predecessor's, breaking the oldest-first drain/shed ordering
        # the zero-padded prefix exists to give
        max_seq = 0
        for name, _seg in found:
            try:
                max_seq = max(max_seq, int(name.split("-")[1]))
            except (IndexError, ValueError):
                pass
        with self._lock:
            self._segments = [seg for _, seg in found]
            self._seq = max(self._seq, max_seq)
            self.replayed_total = len(found)
        if found:
            logger.warning(
                "carryover spool: replaying %d segment(s) (%d metrics) "
                "left by a previous process", len(found),
                sum(seg.count for _, seg in found))

    @staticmethod
    def _read_header(path: str) -> Optional[SpoolSegment]:
        try:
            with open(path, "rb") as f:
                header = f.readline(_HEADER_MAX)
                meta = json.loads(header)
                nbytes = os.fstat(f.fileno()).st_size
            return SpoolSegment(path, float(meta["created_unix"]),
                                int(meta["count"]), nbytes)
        except (OSError, ValueError, KeyError):
            return None

    # -- state -----------------------------------------------------------

    @property
    def depth(self) -> int:
        with self._lock:
            return len(self._segments)

    @property
    def nbytes(self) -> int:
        with self._lock:
            return sum(seg.nbytes for seg in self._segments)

    @property
    def pending_metrics(self) -> int:
        """Metric rows across all live segments — the ledger's stock."""
        with self._lock:
            return sum(seg.count for seg in self._segments)

    def _note_shed(self, n: int, key: str) -> None:
        led = self.ledger
        if led is not None and n:
            led.note("forward.shed", n, key=key)

    # -- spill -----------------------------------------------------------

    def append(self, metrics: List[bytes]) -> int:
        """Spill one interval's serialized metrics as a new segment;
        returns the count written. Atomic (tmp + rename) so a crash
        mid-spill leaves either a whole segment or none."""
        if not metrics:
            return 0
        with self._append_lock:
            return self._append_locked(metrics)

    def _append_locked(self, metrics: List[bytes]) -> int:
        body = frame_metrics(metrics)
        created = time.time()
        header = json.dumps({"created_unix": round(created, 3),
                             "count": len(metrics)}).encode() + b"\n"
        with self._lock:
            self._seq += 1
            name = f"spill-{self._seq:08d}-{uuid.uuid4().hex[:8]}"
        path = os.path.join(self.directory, name + _SEGMENT_SUFFIX)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(header)
            f.write(body)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        # the rename itself must reach disk too, or a power loss leaves
        # a segment that was counted "spilled" (not shed) yet vanishes
        # from the restart scan — the durability the spool exists for
        try:
            dirfd = os.open(self.directory, os.O_RDONLY)
            try:
                os.fsync(dirfd)
            finally:
                os.close(dirfd)
        except OSError:
            pass  # non-POSIX dir-fsync (or odd fs): best effort
        seg = SpoolSegment(path, created, len(metrics),
                           len(header) + len(body))
        shed: List[SpoolSegment] = []
        with self._lock:
            self._segments.append(seg)
            self.spilled_total += 1
            self.spilled_metrics_total += len(metrics)
            total = sum(s.nbytes for s in self._segments)
            while (len(self._segments) > self.max_segments
                   or (self.max_bytes and total > self.max_bytes)) \
                    and len(self._segments) > 1:
                victim = self._segments.pop(0)
                total -= victim.nbytes
                shed.append(victim)
                self.shed_total += 1
                self.shed_metrics_total += victim.count
        for victim in shed:
            logger.error(
                "carryover spool over bound: shedding oldest segment %s "
                "(%d metrics — counter deltas in it are permanently lost)",
                victim.path, victim.count)
            self._note_shed(victim.count, "spool_bound")
            try:
                os.unlink(victim.path)
            except OSError:
                pass
        return len(metrics)

    # -- drain -----------------------------------------------------------

    def live_paths(self) -> set:
        with self._lock:
            return {seg.path for seg in self._segments}

    def oldest(self) -> Optional[SpoolSegment]:
        with self._lock:
            return self._segments[0] if self._segments else None

    def pop(self, seg: SpoolSegment) -> None:
        """Remove a successfully-delivered segment and observe its
        spill->drain dwell."""
        with self._lock:
            try:
                self._segments.remove(seg)
            except ValueError:
                return
            self.drained_total += 1
            self.drained_metrics_total += seg.count
        if self._dwell_hist is not None:
            self._dwell_hist.observe(max(0.0, time.time() - seg.created_unix))
        try:
            os.unlink(seg.path)
        except OSError:
            logger.warning("could not unlink drained spool segment %s",
                           seg.path)

    def discard(self, seg: SpoolSegment) -> None:
        """Drop an undeliverable (corrupt) segment without counting it
        drained."""
        with self._lock:
            try:
                self._segments.remove(seg)
            except ValueError:
                return
            self.shed_total += 1
            self.shed_metrics_total += seg.count
        self._note_shed(seg.count, "spool_quarantine")
        bad = seg.path + ".corrupt"
        try:
            os.replace(seg.path, bad)
        except OSError:
            pass

    # -- telemetry -------------------------------------------------------

    def telemetry_rows(self) -> List[tuple]:
        with self._lock:
            depth = len(self._segments)
            nbytes = sum(s.nbytes for s in self._segments)
            rows = [
                ("carryover.spool.depth", "gauge", float(depth), ()),
                ("carryover.spool.bytes", "gauge", float(nbytes), ()),
                ("carryover.spool.spilled", "counter",
                 float(self.spilled_metrics_total), ()),
                ("carryover.spool.drained", "counter",
                 float(self.drained_metrics_total), ()),
                ("carryover.spool.shed", "counter",
                 float(self.shed_metrics_total), ()),
                ("carryover.spool.replayed", "counter",
                 float(self.replayed_total), ()),
            ]
        return rows
