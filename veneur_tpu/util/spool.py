"""Durable interval WAL: a bounded on-disk log of forward intervals.

Two modes share one on-disk format:

* **Carryover spill** (the original role): in-memory carryover
  (util/resilience.py) is bounded to `carryover_max_intervals`; past the
  bound the merged ForwardableState is serialized to metricpb wire bytes
  (the SAME encoding a forward send uses) and appended here instead of
  shed. Because every forwarded family merges associatively and
  commutatively (counters sum, t-digests recompress, HLL/llhist
  registers max/add), a failed interval's state is just as valid
  delivered minutes later from disk as seconds later from memory.
* **Write-ahead log** (`forward_wal: true`): EVERY forwardable interval
  snapshot is appended BEFORE its send attempt, stamped with the
  interval-start timestamp, and removed only once the receiver acked
  it. A crash (`kill -9`) at any point between the append and the ack
  replays the interval at restart — and because each segment's
  idempotency token derives from its on-disk name (stable across
  restarts), a segment whose send landed but whose ack was lost is
  dropped by the receiver's token dedupe, not merged twice.

Segments carry their interval-start timestamp in the JSON header (and
the drain stamps it onto the send as `x-veneur-interval` metadata), so
the receiving tier can bucket a replayed interval under its ORIGINAL
interval instead of folding hours-stale state into the current flush —
the difference between backfilled history and a false traffic spike.

Segments are drained oldest-first by the forward client once the
destination is reachable (each segment body is already a valid
SendMetrics V1 MetricList framing), and a process restart (including
PR 3's SIGUSR2 handoff) simply re-scans the directory — a crash mid-
outage loses nothing that reached disk. Appends are atomic
(tmp + rename + fsync, then a directory fsync) so a crash mid-spill
leaves either a whole segment or none.

Bounded loudly, like everything else in the resilience layer: past
`max_segments` or `max_bytes` the OLDEST segments are dropped (counted,
logged) so the newest state — the most likely to still matter — wins.
Undeliverable segments move to a bounded `quarantine/` subdirectory
(an inventory stock the flow ledger books, not a silent aside); past
the quarantine bound the oldest quarantined segments are purged and
their metrics booked as explained shed.

stdlib-only; no jax, no grpc (the caller hands in pre-serialized wire
bytes and gets them back).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import uuid
from typing import List, Optional, Tuple

logger = logging.getLogger("veneur_tpu.util.spool")

_SEGMENT_SUFFIX = ".vspool"
_HEADER_MAX = 4096  # sanity bound on the JSON header line
QUARANTINE_DIR = "quarantine"


def frame_metrics(metrics: List[bytes]) -> bytes:
    """Concatenated MetricList `metrics` entries (field 1,
    length-delimited): the V1 forward body framing, inlined here so the
    spool stays grpc-free."""
    out = []
    for b in metrics:
        n = len(b)
        out.append(b"\x0a")
        while n >= 0x80:
            out.append(bytes((n & 0x7F | 0x80,)))
            n >>= 7
        out.append(bytes((n,)))
        out.append(b)
    return b"".join(out)


def unframe_metrics(body: bytes) -> List[bytes]:
    """Inverse of frame_metrics: split a MetricList body back into
    per-Metric wire bytes. Raises ValueError on malformed framing (a
    truncated segment from a crash mid-write never reaches the sender —
    append() is write-tmp-then-rename, so this only fires on external
    corruption)."""
    out: List[bytes] = []
    i, n = 0, len(body)
    while i < n:
        if body[i] != 0x0A:
            raise ValueError(f"bad MetricList frame tag at {i}")
        i += 1
        size = shift = 0
        while True:
            if i >= n:
                raise ValueError("truncated frame length")
            byte = body[i]
            i += 1
            size |= (byte & 0x7F) << shift
            if not byte & 0x80:
                break
            shift += 7
            if shift > 35:
                raise ValueError("frame length varint overflow")
        if i + size > n:
            raise ValueError("truncated frame body")
        out.append(body[i:i + size])
        i += size
    return out


class SpoolSegment:
    """One on-disk interval: a JSON header line + a MetricList body.
    `interval_unix` is the interval-start timestamp the snapshot covers
    (0.0 for pre-WAL segments written without a stamp)."""

    __slots__ = ("path", "created_unix", "count", "nbytes",
                 "interval_unix", "extra")

    def __init__(self, path: str, created_unix: float, count: int,
                 nbytes: int, interval_unix: float = 0.0,
                 extra: Optional[dict] = None):
        self.path = path
        self.created_unix = created_unix
        self.count = count
        self.nbytes = nbytes
        self.interval_unix = interval_unix
        # caller-owned header metadata (the reshard WAL stamps its cell
        # bounds and cutover token here); None for plain segments
        self.extra = extra

    def read_metrics(self) -> List[bytes]:
        with open(self.path, "rb") as f:
            f.readline()  # header
            return unframe_metrics(f.read())


class CarryoverSpool:
    """Bounded directory WAL of forward intervals.

    Thread-safe. `append` is called from whatever thread owns the
    interval (the forward thread, or the flush loop tripping the
    carryover bound); `oldest`/`pop` from the forward thread's drain;
    counters from the telemetry scraper."""

    def __init__(self, directory: str,
                 max_bytes: int = 256 * 1024 * 1024,
                 max_segments: int = 1024,
                 quarantine_max_bytes: int = 64 * 1024 * 1024,
                 quarantine_max_segments: int = 256,
                 dwell_hist=None, ledger=None):
        self.directory = directory
        self.max_bytes = max(0, int(max_bytes))
        self.max_segments = max(1, int(max_segments))
        self.quarantine_max_bytes = max(0, int(quarantine_max_bytes))
        self.quarantine_max_segments = max(1, int(quarantine_max_segments))
        # flow ledger (core/ledger.py): the spool is an inventory stock
        # of the forward conservation identity; bound sheds and
        # quarantine purges stamp forward.shed so a dropped segment is
        # explained loss, never unexplained imbalance. A quarantined
        # segment is NOT shed — it moves into the spool_quarantine
        # stock (set aside on disk, still inventoried) until the
        # quarantine bound purges it. Notes fire outside self._lock.
        self.ledger = ledger
        # optional latency-observatory llhist: spill->drain dwell rides
        # the shared queue.dwell telemetry under the caller's queue name
        self._dwell_hist = dwell_hist
        self._lock = threading.Lock()
        # serializes whole append() bodies: seq assignment, the disk
        # write, and the publish must be one atomic unit or concurrent
        # spills (forward thread + flush loop both stash) could order
        # _segments out of seq order — and the bound shed would then
        # evict a NEWER segment while believing it took the oldest
        self._append_lock = threading.Lock()
        self._segments: List[SpoolSegment] = []
        # quarantined segments, oldest first (path, count, nbytes);
        # count is 0 when the header was unreadable (those never
        # entered the books, so their purge sheds nothing)
        self._quarantined: List[Tuple[str, int, int]] = []
        self._seq = 0
        self.spilled_total = 0          # segments written
        self.spilled_metrics_total = 0  # metrics across them
        self.drained_total = 0          # segments delivered and removed
        self.drained_metrics_total = 0
        self.shed_total = 0             # segments dropped at the bound
        self.shed_metrics_total = 0
        self.quarantined_total = 0      # segments set aside undeliverable
        self.quarantine_purged_total = 0        # segments purged at bound
        self.quarantine_purged_metrics_total = 0
        self.replayed_total = 0         # segments recovered at startup
        os.makedirs(directory, exist_ok=True)
        os.makedirs(self.quarantine_path, exist_ok=True)
        self._scan()

    @property
    def quarantine_path(self) -> str:
        return os.path.join(self.directory, QUARANTINE_DIR)

    # -- startup replay --------------------------------------------------

    def _scan(self) -> None:
        """Recover segments left by a previous process (crash or SIGUSR2
        handoff mid-outage). Unreadable files are quarantined aside, not
        deleted — loud beats silent for data that exists because of a
        failure. The quarantine directory is re-scanned too, so its
        stock (and bound) survives restarts."""
        found: List[Tuple[str, SpoolSegment]] = []
        for name in os.listdir(self.directory):
            if not name.endswith(_SEGMENT_SUFFIX):
                continue
            path = os.path.join(self.directory, name)
            seg = self._read_header(path)
            if seg is None:
                logger.error("spool segment %s unreadable; quarantined",
                             path)
                self._quarantine_file(path, 0)
                continue
            found.append((name, seg))
        found.sort(key=lambda pair: pair[0])  # seq-prefixed names: oldest first
        # seed the sequence PAST everything on disk — including the
        # quarantine: a fresh process restarting at seq 1 would
        # interleave its segment names with a predecessor's, breaking
        # the oldest-first drain/shed ordering the zero-padded prefix
        # exists to give (and a re-quarantined name must never collide)
        max_seq = 0
        for name, _seg in found:
            max_seq = max(max_seq, _name_seq(name))
        quarantined: List[Tuple[str, int, int]] = []
        qdir = self.quarantine_path
        try:
            qnames = sorted(os.listdir(qdir))
        except OSError:
            qnames = []
        for name in qnames:
            if not name.endswith(_SEGMENT_SUFFIX):
                continue
            qpath = os.path.join(qdir, name)
            max_seq = max(max_seq, _name_seq(name))
            seg = self._read_header(qpath)
            try:
                nbytes = os.stat(qpath).st_size
            except OSError:
                continue
            quarantined.append((qpath, seg.count if seg else 0, nbytes))
        with self._lock:
            self._segments = [seg for _, seg in found]
            self._quarantined = quarantined
            self._seq = max(self._seq, max_seq)
            self.replayed_total = len(found)
        if found:
            logger.warning(
                "durable spool: replaying %d segment(s) (%d metrics) "
                "left by a previous process", len(found),
                sum(seg.count for _, seg in found))
        self._enforce_quarantine_bound()

    @staticmethod
    def _read_header(path: str) -> Optional[SpoolSegment]:
        try:
            with open(path, "rb") as f:
                header = f.readline(_HEADER_MAX)
                meta = json.loads(header)
                nbytes = os.fstat(f.fileno()).st_size
            return SpoolSegment(path, float(meta["created_unix"]),
                                int(meta["count"]), nbytes,
                                float(meta.get("interval_unix", 0.0)),
                                extra=meta.get("extra"))
        except (OSError, ValueError, KeyError):
            return None

    # -- state -----------------------------------------------------------

    @property
    def depth(self) -> int:
        with self._lock:
            return len(self._segments)

    @property
    def nbytes(self) -> int:
        with self._lock:
            return sum(seg.nbytes for seg in self._segments)

    @property
    def pending_metrics(self) -> int:
        """Metric rows across all live segments — the ledger's stock."""
        with self._lock:
            return sum(seg.count for seg in self._segments)

    @property
    def quarantined_metrics(self) -> int:
        """Metric rows set aside in the quarantine directory — the
        spool_quarantine inventory stock the ledger books (a quarantined
        segment left the drainable spool but not the node's disk)."""
        with self._lock:
            return sum(count for _p, count, _b in self._quarantined)

    @property
    def quarantined_bytes(self) -> int:
        with self._lock:
            return sum(b for _p, _c, b in self._quarantined)

    @property
    def quarantine_depth(self) -> int:
        with self._lock:
            return len(self._quarantined)

    def _note_shed(self, n: int, key: str) -> None:
        led = self.ledger
        if led is not None and n:
            led.note("forward.shed", n, key=key)

    # -- spill / WAL append ----------------------------------------------

    def append(self, metrics: List[bytes],
               interval_unix: float = 0.0,
               extra: Optional[dict] = None) -> int:
        """Append one interval's serialized metrics as a new segment;
        returns the count written. `interval_unix` is the interval-start
        timestamp the snapshot covers (stamped into the header and onto
        every drain of this segment as x-veneur-interval metadata); 0
        keeps the pre-WAL unstamped behavior. Atomic (tmp + rename +
        fsync) so a crash mid-spill leaves either a whole segment or
        none."""
        if not metrics:
            return 0
        with self._append_lock:
            return self._append_locked(metrics, interval_unix, extra)

    def _append_locked(self, metrics: List[bytes],
                       interval_unix: float,
                       extra: Optional[dict] = None) -> int:
        body = frame_metrics(metrics)
        created = time.time()
        header_fields = {"created_unix": round(created, 3),
                         "count": len(metrics)}
        if interval_unix:
            header_fields["interval_unix"] = round(float(interval_unix), 3)
        if extra:
            # caller metadata (reshard WAL cell bounds / cutover token);
            # must stay small — the whole header line is bounded by
            # _HEADER_MAX at replay
            header_fields["extra"] = extra
        header = json.dumps(header_fields).encode() + b"\n"
        with self._lock:
            self._seq += 1
            name = f"spill-{self._seq:08d}-{uuid.uuid4().hex[:8]}"
        path = os.path.join(self.directory, name + _SEGMENT_SUFFIX)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(header)
            f.write(body)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        # the rename itself must reach disk too, or a power loss leaves
        # a segment that was counted "spilled" (not shed) yet vanishes
        # from the restart scan — the durability the spool exists for
        self._fsync_dir(self.directory)
        seg = SpoolSegment(path, created, len(metrics),
                           len(header) + len(body), float(interval_unix),
                           extra=extra)
        shed: List[SpoolSegment] = []
        with self._lock:
            self._segments.append(seg)
            self.spilled_total += 1
            self.spilled_metrics_total += len(metrics)
            total = sum(s.nbytes for s in self._segments)
            while (len(self._segments) > self.max_segments
                   or (self.max_bytes and total > self.max_bytes)) \
                    and len(self._segments) > 1:
                victim = self._segments.pop(0)
                total -= victim.nbytes
                shed.append(victim)
                self.shed_total += 1
                self.shed_metrics_total += victim.count
        for victim in shed:
            logger.error(
                "durable spool over bound: shedding oldest segment %s "
                "(%d metrics — counter deltas in it are permanently lost)",
                victim.path, victim.count)
            self._note_shed(victim.count, "spool_bound")
            try:
                os.unlink(victim.path)
            except OSError:
                pass
        return len(metrics)

    @staticmethod
    def _fsync_dir(directory: str) -> None:
        try:
            dirfd = os.open(directory, os.O_RDONLY)
            try:
                os.fsync(dirfd)
            finally:
                os.close(dirfd)
        except OSError:
            pass  # non-POSIX dir-fsync (or odd fs): best effort

    # -- drain -----------------------------------------------------------

    def live_paths(self) -> set:
        with self._lock:
            return {seg.path for seg in self._segments}

    def oldest(self) -> Optional[SpoolSegment]:
        with self._lock:
            return self._segments[0] if self._segments else None

    def segments(self) -> List[SpoolSegment]:
        """Snapshot of the live segments, oldest first — the drain
        iterates this so it can reorder (fresh-before-stale in WAL mode)
        without holding the spool lock across sends."""
        with self._lock:
            return list(self._segments)

    def pop(self, seg: SpoolSegment) -> None:
        """Remove a successfully-delivered segment and observe its
        spill->drain dwell."""
        with self._lock:
            try:
                self._segments.remove(seg)
            except ValueError:
                return
            self.drained_total += 1
            self.drained_metrics_total += seg.count
        if self._dwell_hist is not None:
            self._dwell_hist.observe(max(0.0, time.time() - seg.created_unix))
        try:
            os.unlink(seg.path)
        except OSError:
            logger.warning("could not unlink drained spool segment %s",
                           seg.path)

    # -- quarantine ------------------------------------------------------

    def discard(self, seg: SpoolSegment) -> None:
        """Move an undeliverable (corrupt) segment into the bounded
        quarantine directory. The metrics shift from the forward_spool
        stock to the spool_quarantine stock — set aside, not shed; only
        a quarantine-bound purge books them as lost."""
        with self._lock:
            try:
                self._segments.remove(seg)
            except ValueError:
                return
        self._quarantine_file(seg.path, seg.count)

    def _quarantine_file(self, path: str, count: int) -> None:
        qpath = os.path.join(self.quarantine_path,
                             os.path.basename(path))
        try:
            # the subdir may have been removed out from under us (an
            # operator cleanup, an aggressive tmp reaper) — recreate
            os.makedirs(self.quarantine_path, exist_ok=True)
            os.replace(path, qpath)
            nbytes = os.stat(qpath).st_size
        except OSError:
            # cannot set the segment aside: its metrics have already
            # left the forward_spool stock, so book them as explained
            # shed and remove the file — leaving it in the main dir
            # would re-adopt (and re-fail) it on every restart
            logger.error("could not quarantine spool segment %s; "
                         "shedding it", path)
            self._note_shed(count, "quarantine_failed")
            try:
                os.unlink(path)
            except OSError:
                pass
            return
        with self._lock:
            self._quarantined.append((qpath, count, nbytes))
            self.quarantined_total += 1
        self._enforce_quarantine_bound()

    def _enforce_quarantine_bound(self) -> None:
        purged: List[Tuple[str, int, int]] = []
        with self._lock:
            total = sum(b for _p, _c, b in self._quarantined)
            while (len(self._quarantined) > self.quarantine_max_segments
                   or (self.quarantine_max_bytes
                       and total > self.quarantine_max_bytes)) \
                    and self._quarantined:
                victim = self._quarantined.pop(0)
                total -= victim[2]
                purged.append(victim)
                self.quarantine_purged_total += 1
                self.quarantine_purged_metrics_total += victim[1]
        for qpath, count, _nbytes in purged:
            logger.error(
                "spool quarantine over bound: purging oldest segment %s "
                "(%d metrics permanently lost)", qpath, count)
            self._note_shed(count, "quarantine_purged")
            try:
                os.unlink(qpath)
            except OSError:
                pass

    # -- telemetry -------------------------------------------------------

    def telemetry_rows(self) -> List[tuple]:
        with self._lock:
            depth = len(self._segments)
            nbytes = sum(s.nbytes for s in self._segments)
            q_metrics = sum(c for _p, c, _b in self._quarantined)
            q_bytes = sum(b for _p, _c, b in self._quarantined)
            rows = [
                ("carryover.spool.depth", "gauge", float(depth), ()),
                ("carryover.spool.bytes", "gauge", float(nbytes), ()),
                ("carryover.spool.spilled", "counter",
                 float(self.spilled_metrics_total), ()),
                ("carryover.spool.drained", "counter",
                 float(self.drained_metrics_total), ()),
                ("carryover.spool.shed", "counter",
                 float(self.shed_metrics_total), ()),
                ("carryover.spool.replayed", "counter",
                 float(self.replayed_total), ()),
                ("carryover.spool.quarantined", "gauge",
                 float(q_metrics), ()),
                ("carryover.spool.quarantined_bytes", "gauge",
                 float(q_bytes), ()),
                ("carryover.spool.quarantine_purged", "counter",
                 float(self.quarantine_purged_metrics_total), ()),
            ]
        return rows


def _name_seq(name: str) -> int:
    """The zero-padded sequence prefix of a segment file name (0 when
    unparseable) — the total order drains follow."""
    try:
        return int(name.split("-")[1])
    except (IndexError, ValueError):
        return 0
