"""Name/tag matchers for sink routing and tag stripping.

Semantic parity with reference util/matcher/matcher.go: name kinds
any/exact/prefix/regex; tag kinds exact/prefix/regex with an `unset` flag
meaning the tag must NOT be present; a rule matches when the name matches
and every tag matcher is satisfied; a rule list matches if any rule does.
"""

from __future__ import annotations

import re
from typing import Dict, List, Sequence


class NameMatcher:
    def __init__(self, kind: str = "any", value: str = ""):
        self.kind = kind
        self.value = value
        if kind == "regex":
            self._regex = re.compile(value)
        elif kind not in ("any", "exact", "prefix"):
            raise ValueError(f'unknown matcher kind "{kind}"')

    @staticmethod
    def from_config(cfg: Dict) -> "NameMatcher":
        cfg = cfg or {}
        return NameMatcher(cfg.get("kind", "any"), cfg.get("value", ""))

    def match(self, name: str) -> bool:
        if self.kind == "any":
            return True
        if self.kind == "exact":
            return name == self.value
        if self.kind == "prefix":
            return name.startswith(self.value)
        return self._regex.search(name) is not None


class TagMatcher:
    def __init__(self, kind: str = "exact", value: str = "", unset: bool = False):
        self.kind = kind
        self.value = value
        self.unset = unset
        if kind == "regex":
            self._regex = re.compile(value)
        elif kind not in ("exact", "prefix"):
            raise ValueError(f'unknown matcher kind "{kind}"')

    @staticmethod
    def from_config(cfg: Dict) -> "TagMatcher":
        cfg = cfg or {}
        return TagMatcher(cfg.get("kind", "exact"), cfg.get("value", ""),
                          bool(cfg.get("unset", False)))

    def match(self, tag: str) -> bool:
        if self.kind == "exact":
            return tag == self.value
        if self.kind == "prefix":
            return tag.startswith(self.value)
        return self._regex.search(tag) is not None


class Matcher:
    def __init__(self, name: NameMatcher, tags: List[TagMatcher]):
        self.name = name
        self.tags = tags

    @staticmethod
    def from_config(cfg: Dict) -> "Matcher":
        cfg = cfg or {}
        return Matcher(
            NameMatcher.from_config(cfg.get("name", {})),
            [TagMatcher.from_config(t) for t in cfg.get("tags", []) or []])

    def match(self, name: str, tags: Sequence[str]) -> bool:
        if not self.name.match(name):
            return False
        for tm in self.tags:
            found = any(tm.match(tag) for tag in tags)
            if found and tm.unset:
                return False
            if not found and not tm.unset:
                return False
        return True


def match_any(matchers: Sequence[Matcher], name: str,
              tags: Sequence[str]) -> bool:
    return any(rule.match(name, tags) for rule in matchers)


class SinkRoutingMatcher:
    """One metric_sink_routing entry: rules -> matched/not_matched sink
    lists (reference SinkRoutingConfig, flusher.go:97-113)."""

    def __init__(self, routing_config):
        self.name = routing_config.name
        self.matchers = [Matcher.from_config(c)
                         for c in routing_config.match]
        self.matched = list(routing_config.matched)
        self.not_matched = list(routing_config.not_matched)

    def route(self, name: str, tags: Sequence[str]) -> List[str]:
        if match_any(self.matchers, name, tags):
            return self.matched
        return self.not_matched
