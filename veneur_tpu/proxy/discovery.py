"""Service discovery for the proxy's destination pool.

Parity with reference discovery/ (discoverer.go:5-7, consul/consul.go:30-47,
kubernetes/kubernetes.go:90-108): a Discoverer maps a service name to the
current list of healthy destination addresses. Built-ins:

- StaticDiscoverer: a fixed list (the common config-driven case).
- DnsDiscoverer: resolve an A/AAAA name each refresh; every returned
  address (with a fixed port) is a destination.
- HttpJsonDiscoverer: poll an HTTP endpoint returning a JSON array of
  addresses — the shape any custom controller can serve (tests use a
  local HTTP fake, like the reference's consul testdata).
- ConsulDiscoverer: the Consul health API (passing-only), returning
  Node.Address:Service.Port like the reference
  (consul/consul.go:30-47).
- KubernetesDiscoverer: list pods by label from the kube-apiserver and
  extract grpc/http/TCP container ports from running pods
  (kubernetes/kubernetes.go:34-130), using in-cluster service-account
  credentials by default.
"""

from __future__ import annotations

import abc
import json
import logging
import os
import socket
import ssl
import urllib.parse
import urllib.request
from typing import List, Optional

logger = logging.getLogger("veneur_tpu.proxy.discovery")


class Discoverer(abc.ABC):
    @abc.abstractmethod
    def get_destinations_for_service(self, service: str) -> List[str]: ...


class StaticDiscoverer(Discoverer):
    def __init__(self, destinations: List[str]):
        self._destinations = list(destinations)

    def get_destinations_for_service(self, service: str) -> List[str]:
        return list(self._destinations)


class DnsDiscoverer(Discoverer):
    """`service` is "host:port"; each resolved address becomes a
    destination at that port."""

    def get_destinations_for_service(self, service: str) -> List[str]:
        host, _, port = service.rpartition(":")
        if not host:
            raise ValueError(f"dns discovery needs host:port, got {service!r}")
        infos = socket.getaddrinfo(host, int(port), proto=socket.IPPROTO_TCP)
        # IPv6 literals need brackets to be dialable gRPC targets
        return sorted({
            (f"[{info[4][0]}]:{port}" if info[0] == socket.AF_INET6
             else f"{info[4][0]}:{port}")
            for info in infos})


class HttpJsonDiscoverer(Discoverer):
    """GET `url_template.format(service=...)`, expecting a JSON array of
    "host:port" strings (or of objects with Address/Port keys, the shape
    of a Consul health response)."""

    def __init__(self, url_template: str, timeout: float = 5.0):
        self.url_template = url_template
        self.timeout = timeout

    def get_destinations_for_service(self, service: str) -> List[str]:
        url = self.url_template.format(service=service)
        with urllib.request.urlopen(url, timeout=self.timeout) as resp:
            payload = json.load(resp)
        out = []
        for entry in payload:
            if isinstance(entry, str):
                out.append(entry)
            elif isinstance(entry, dict):
                # Consul-style: {"Service": {"Address": ..., "Port": ...}}
                svc = entry.get("Service", entry)
                addr = svc.get("Address") or entry.get("Node", {}).get(
                    "Address")
                port = svc.get("Port")
                if addr and port:
                    out.append(f"{addr}:{port}")
        return out


class ConsulDiscoverer(Discoverer):
    """Healthy service instances from the Consul HTTP health API
    (reference discovery/consul/consul.go:30-47): destinations are
    "<node address>:<service port>" of passing entries only; an empty
    result is an error, matching the reference's "received no hosts"."""

    def __init__(self, base_url: str = "http://127.0.0.1:8500",
                 token: str = "", timeout: float = 5.0):
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.timeout = timeout

    def get_destinations_for_service(self, service: str) -> List[str]:
        url = (f"{self.base_url}/v1/health/service/"
               f"{urllib.parse.quote(service)}?passing=true")
        req = urllib.request.Request(url)
        if self.token:
            req.add_header("X-Consul-Token", self.token)
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            entries = json.load(resp)
        if not entries:
            raise RuntimeError("received no hosts from Consul")
        hosts = []
        for entry in entries:
            node_addr = entry.get("Node", {}).get("Address")
            svc = entry.get("Service", {})
            port = svc.get("Port")
            if node_addr and port:
                hosts.append(f"{node_addr}:{port}")
        return hosts


class KubernetesDiscoverer(Discoverer):
    """Pod-list discovery against the kube-apiserver (reference
    discovery/kubernetes/kubernetes.go:90-130): list pods matching
    `label_selector`, keep Running pods, and pick the forward port per
    pod. Only container ports named "grpc" become destinations: the
    reference also emitted "http://"-prefixed destinations for http/TCP
    ports (its retired legacy-HTTP import), but this framework forwards
    over gRPC only, so such pods are skipped with a warning instead of
    claiming ring keyspace they could never serve.

    By default reads in-cluster credentials (KUBERNETES_SERVICE_HOST /
    _PORT, the service-account token and CA bundle); every piece can be
    overridden, which is also how tests point it at a fake API server."""

    SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

    def __init__(self, api_base: Optional[str] = None,
                 token: Optional[str] = None,
                 ca_file: Optional[str] = None,
                 label_selector: str = "app=veneur-global",
                 timeout: float = 10.0):
        if api_base is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise RuntimeError(
                    "not in a Kubernetes cluster (KUBERNETES_SERVICE_HOST "
                    "unset) and no api_base given")
            api_base = f"https://{host}:{port}"
        self.api_base = api_base.rstrip("/")
        if token is None:
            token_path = os.path.join(self.SA_DIR, "token")
            token = (open(token_path).read().strip()
                     if os.path.exists(token_path) else "")
        self.token = token
        if ca_file is None:
            ca_path = os.path.join(self.SA_DIR, "ca.crt")
            ca_file = ca_path if os.path.exists(ca_path) else None
        self._ctx = None
        if self.api_base.startswith("https"):
            self._ctx = ssl.create_default_context(cafile=ca_file)
        self.label_selector = label_selector
        self.timeout = timeout

    def _destination_from_pod(self, pod: dict) -> str:
        status = pod.get("status", {})
        if status.get("phase") != "Running":
            return ""
        name = pod.get("metadata", {}).get("name", "?")
        forward_port = ""
        saw_legacy = False
        for container in pod.get("spec", {}).get("containers", []):
            for port in container.get("ports", []):
                if port.get("name") == "grpc":
                    forward_port = str(port.get("containerPort", ""))
                    break
                if (port.get("name") == "http"
                        or port.get("protocol") == "TCP"):
                    saw_legacy = True
            else:
                continue
            break
        pod_ip = status.get("podIP", "")
        if forward_port in ("", "0"):
            if saw_legacy:
                # the reference forwarded these over its legacy HTTP
                # import; this build is gRPC-only, so they are not
                # dialable destinations
                logger.warning(
                    "pod %s exposes only http/TCP ports; skipping "
                    "(gRPC-only forward plane)", name)
            else:
                logger.error("pod %s: no grpc port for forwarding", name)
            return ""
        if not pod_ip:
            logger.error("pod %s: no podIP for forwarding", name)
            return ""
        return f"{pod_ip}:{forward_port}"

    def get_destinations_for_service(self, service: str) -> List[str]:
        selector = urllib.parse.quote(self.label_selector)
        url = f"{self.api_base}/api/v1/pods?labelSelector={selector}"
        req = urllib.request.Request(url)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        with urllib.request.urlopen(
                req, timeout=self.timeout, context=self._ctx) as resp:
            payload = json.load(resp)
        out = []
        for pod in payload.get("items", []):
            dest = self._destination_from_pod(pod)
            if dest:
                out.append(dest)
        return out
