"""Service discovery for the proxy's destination pool.

Parity with reference discovery/ (discoverer.go:5-7, consul/consul.go:30-47,
kubernetes/kubernetes.go:90-108): a Discoverer maps a service name to the
current list of healthy destination addresses. Built-ins:

- StaticDiscoverer: a fixed list (the common config-driven case).
- DnsDiscoverer: resolve an A/AAAA name each refresh; every returned
  address (with a fixed port) is a destination.
- HttpJsonDiscoverer: poll an HTTP endpoint returning a JSON array of
  addresses — the shape a Consul health API proxy or any custom
  controller can serve (tests use a local HTTP fake, like the
  reference's consul testdata).

Kubernetes pod-watch discovery requires a cluster client and is out of
scope for this build; HttpJsonDiscoverer against the kube-apiserver's
endpoints API covers the same topology.
"""

from __future__ import annotations

import abc
import json
import logging
import socket
import urllib.request
from typing import List

logger = logging.getLogger("veneur_tpu.proxy.discovery")


class Discoverer(abc.ABC):
    @abc.abstractmethod
    def get_destinations_for_service(self, service: str) -> List[str]: ...


class StaticDiscoverer(Discoverer):
    def __init__(self, destinations: List[str]):
        self._destinations = list(destinations)

    def get_destinations_for_service(self, service: str) -> List[str]:
        return list(self._destinations)


class DnsDiscoverer(Discoverer):
    """`service` is "host:port"; each resolved address becomes a
    destination at that port."""

    def get_destinations_for_service(self, service: str) -> List[str]:
        host, _, port = service.rpartition(":")
        if not host:
            raise ValueError(f"dns discovery needs host:port, got {service!r}")
        infos = socket.getaddrinfo(host, int(port), proto=socket.IPPROTO_TCP)
        # IPv6 literals need brackets to be dialable gRPC targets
        return sorted({
            (f"[{info[4][0]}]:{port}" if info[0] == socket.AF_INET6
             else f"{info[4][0]}:{port}")
            for info in infos})


class HttpJsonDiscoverer(Discoverer):
    """GET `url_template.format(service=...)`, expecting a JSON array of
    "host:port" strings (or of objects with Address/Port keys, the shape
    of a Consul health response)."""

    def __init__(self, url_template: str, timeout: float = 5.0):
        self.url_template = url_template
        self.timeout = timeout

    def get_destinations_for_service(self, service: str) -> List[str]:
        url = self.url_template.format(service=service)
        with urllib.request.urlopen(url, timeout=self.timeout) as resp:
            payload = json.load(resp)
        out = []
        for entry in payload:
            if isinstance(entry, str):
                out.append(entry)
            elif isinstance(entry, dict):
                # Consul-style: {"Service": {"Address": ..., "Port": ...}}
                svc = entry.get("Service", entry)
                addr = svc.get("Address") or entry.get("Node", {}).get(
                    "Address")
                port = svc.get("Port")
                if addr and port:
                    out.append(f"{addr}:{port}")
        return out
