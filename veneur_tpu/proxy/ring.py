"""Consistent-hash ring for sharding metrics across global instances.

Semantics parity with the reference's vendored stathat/consistent ring
(used at proxy/destinations/destinations.go:127-141): members are placed
at many virtual points on a ring; `get(key)` walks clockwise from the
key's hash to the first member, so adding/removing one member only remaps
~1/N of keys. Hash is fnv1a-64 (our host keying hash) rather than the
reference's crc32 — both give uniform placement; only intra-cluster
consistency matters, and every veneur-tpu proxy uses the same function.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional

from veneur_tpu.util import fnv

DEFAULT_REPLICAS = 20


class EmptyRingError(LookupError):
    pass


class ConsistentRing:
    def __init__(self, replicas: int = DEFAULT_REPLICAS):
        self.replicas = replicas
        self._lock = threading.RLock()
        self._points: List[int] = []  # sorted hash points
        self._owner: Dict[int, str] = {}  # point -> member
        self._members: set = set()

    def _point(self, member: str, i: int) -> int:
        return fnv.fnv1a_64(f"{i}{member}".encode())

    def add(self, member: str) -> None:
        with self._lock:
            if member in self._members:
                return
            self._members.add(member)
            for i in range(self.replicas):
                pt = self._point(member, i)
                if pt in self._owner:
                    continue  # vanishing chance of 64-bit collision
                self._owner[pt] = member
                bisect.insort(self._points, pt)

    def remove(self, member: str) -> None:
        with self._lock:
            if member not in self._members:
                return
            self._members.discard(member)
            for i in range(self.replicas):
                pt = self._point(member, i)
                if self._owner.get(pt) == member:
                    del self._owner[pt]
                    idx = bisect.bisect_left(self._points, pt)
                    if idx < len(self._points) and self._points[idx] == pt:
                        del self._points[idx]

    def set_members(self, members: List[str]) -> None:
        with self._lock:
            for member in list(self._members - set(members)):
                self.remove(member)
            for member in members:
                self.add(member)

    def members(self) -> List[str]:
        with self._lock:
            return sorted(self._members)

    def __len__(self) -> int:
        return len(self._members)

    def get(self, key: str) -> str:
        return self.get_at(self.point_of(key))

    @staticmethod
    def point_of(key: str) -> int:
        """The key's ring point. Membership-independent, so callers on
        a hot path may cache it per key and skip re-hashing (the Python
        fnv loop dominates a lookup); get_at(point) must give the same
        member get(key) would."""
        return fnv.fnv1a_64(key.encode())

    def get_at(self, point: int) -> str:
        with self._lock:
            if not self._points:
                raise EmptyRingError("empty consistent-hash ring")
            idx = bisect.bisect_right(self._points, point)
            if idx == len(self._points):
                idx = 0
            return self._owner[self._points[idx]]

    def walk_at(self, point: int, max_members: int) -> List[str]:
        """Up to `max_members` DISTINCT members clockwise from `point`,
        primary first — the deterministic failover order: every proxy
        with the same membership walks the same sequence, so a key whose
        primary is sick lands on the same healthy node cluster-wide."""
        with self._lock:
            if not self._points:
                raise EmptyRingError("empty consistent-hash ring")
            out: List[str] = []
            seen = set()
            idx = bisect.bisect_right(self._points, point)
            n = len(self._points)
            for step in range(n):
                member = self._owner[self._points[(idx + step) % n]]
                if member not in seen:
                    seen.add(member)
                    out.append(member)
                    if len(out) >= max_members:
                        break
            return out

    def get_two(self, key: str) -> tuple:
        """The owner and the next distinct member clockwise (for
        replicated sends; reference ring offers Get/GetTwo/GetN)."""
        with self._lock:
            point = self.point_of(key)
            first = self.get_at(point)
            if len(self._members) < 2:
                return first, first
            idx = bisect.bisect_right(self._points, point)
            n = len(self._points)
            for step in range(n):
                member = self._owner[self._points[(idx + step) % n]]
                if member != first:
                    return first, member
            return first, first


def parse_shard_suffix(address: str):
    """Split an optional shard-group suffix off a discovered address:
    ``host:port#3`` -> (``host:port``, 3); plain addresses give
    (address, None) and fall back to hash assignment."""
    base, sep, group = address.rpartition("#")
    if sep and group.isdigit():
        return base, int(group)
    return address, None


class ShardGroupRing:
    """Shard-aware consistent hashing: the 64-bit key-digest space is
    split into G contiguous ranges, each owned by a *shard group* — the
    set of global instances that hold that key range's device shards —
    with an independent ConsistentRing inside every group.

    This is the proxy-tier mirror of the serving mesh's digest-home
    routing (parallel/sharded_server.py): a key's digest picks its
    group exactly the way it picks its home shard on a local's mesh, so
    a global instance only ever receives keys whose partitioned state
    it actually serves. The payoff is failure confinement — ejecting
    one member re-shards ONLY its group's key range onto the group's
    survivors (~1/|group| of 1/G of the keyspace), while every other
    group's assignment is untouched; readmission restores it exactly
    (same virtual points, same ring). Only when a group loses its last
    member does its range spill clockwise to the next non-empty group
    (loud, counted by the caller) — shedding a whole key range at the
    door would be worse than merging it on the wrong shard group.

    Group membership comes from the caller: an explicit `#<g>` suffix
    on the discovered address, or a stable hash of the address. The
    assignment is remembered across remove/add cycles so health
    ejection + readmission can never migrate a member between groups.

    The class is call-compatible with ConsistentRing (`point_of`,
    `get_at`, `walk_at`, `add`, `remove`, `set_members`, `members`), so
    the destination pool and the route caches work unchanged on top of
    either."""

    def __init__(self, groups: int, replicas: int = DEFAULT_REPLICAS):
        if groups < 1:
            raise ValueError("shard group count must be >= 1")
        self.groups = int(groups)
        self._lock = threading.RLock()
        self._rings = [ConsistentRing(replicas) for _ in range(groups)]
        # address -> group, sticky for the address's lifetime (and past
        # it: ejection/readmission must round-trip to the same group)
        self._group_assign: Dict[str, int] = {}
        # member -> RAW `#<g>` discovery-suffix pin (assign()), kept
        # unfolded: a regroup re-derives pins as raw % G' and hash
        # assignments from the address hash, so both stay deterministic
        # functions of (address, G') and a regrouped proxy agrees with
        # a freshly-started one at G'
        self._pinned: Dict[str, int] = {}

    point_of = staticmethod(ConsistentRing.point_of)

    def group_of_point(self, point: int) -> int:
        """Contiguous range partition of the point space: the top bits
        of the 64-bit ring point pick the group, so each group owns one
        digest range (the property that makes 'this group's key range'
        a meaningful unit to re-home or drain)."""
        return (int(point) & 0xFFFFFFFFFFFFFFFF) * self.groups >> 64

    def group_of(self, member: str) -> int:
        with self._lock:
            group = self._group_assign.get(member)
            if group is None:
                group = fnv.fnv1a_64(member.encode()) % self.groups
            return group

    def assign(self, member: str, group: int) -> None:
        """Pin a member to a group (from the `addr#g` discovery suffix).
        Must happen before the member is added; re-pinning a live
        member to a different group is refused (a silent migration
        would leak its old range's keys to the wrong group)."""
        raw = int(group)
        group = raw % self.groups
        with self._lock:
            current = self._group_assign.get(member)
            if current is not None and current != group \
                    and member in self._rings[current]._members:
                raise ValueError(
                    f"{member} is live in shard group {current}; "
                    f"cannot reassign to {group}")
            self._group_assign[member] = group
            self._pinned[member] = raw

    def add(self, member: str) -> None:
        with self._lock:
            group = self.group_of(member)
            self._group_assign[member] = group
            self._rings[group].add(member)

    def remove(self, member: str) -> None:
        with self._lock:
            group = self._group_assign.get(member)
            if group is not None:
                self._rings[group].remove(member)

    def set_members(self, members: List[str]) -> None:
        with self._lock:
            current = set(self.members())
            for member in current - set(members):
                self.remove(member)
            for member in members:
                self.add(member)

    def members(self) -> List[str]:
        with self._lock:
            out: List[str] = []
            for ring in self._rings:
                out.extend(ring.members())
            return sorted(out)

    def group_members(self) -> List[List[str]]:
        """Per-group live membership (ready-state / debug surfaces)."""
        with self._lock:
            return [ring.members() for ring in self._rings]

    def regroup(self, groups: int) -> int:
        """Live G -> G' regroup, the proxy-tier half of an elastic
        reshard (parallel/reshard.py): the serving tier's shard count
        changed, so the door's range partition must follow. Sticky
        pins survive: an explicitly-assigned member re-derives from
        its RAW discovery-suffix pin (raw % G'), a hash-assigned
        member from the same stable address hash — so a G -> G
        round-trip is the identity, and every key whose group's member
        set is unchanged
        keeps its owner EXACTLY (a group's ConsistentRing points are a
        pure function of its membership). Returns the number of
        members whose group id changed."""
        groups = int(groups)
        if groups < 1:
            raise ValueError("shard group count must be >= 1")
        with self._lock:
            live = self.members()
            old_of = {m: self.group_of(m) for m in live}
            replicas = self._rings[0].replicas if self._rings \
                else DEFAULT_REPLICAS
            self.groups = groups
            self._rings = [ConsistentRing(replicas)
                           for _ in range(groups)]
            # re-derive every assignment under the new modulus from
            # its SOURCE (raw suffix pin, or address hash) — both
            # deterministic functions of (address, G'), so a proxy
            # fleet regrouping to the same G' converges on one table
            # without coordination, and a freshly-started proxy at G'
            # agrees with a regrouped one
            moved = 0
            for member in list(self._group_assign):
                pin = self._pinned.get(member)
                if pin is not None:
                    self._group_assign[member] = pin % groups
                else:
                    self._group_assign[member] = \
                        fnv.fnv1a_64(member.encode()) % groups
            for member in live:
                new_group = self._group_assign[member]
                self._rings[new_group].add(member)
                if old_of.get(member) != new_group:
                    moved += 1
            return moved

    def __len__(self) -> int:
        return len(self.members())

    def get(self, key: str) -> str:
        return self.get_at(self.point_of(key))

    def get_at(self, point: int) -> str:
        with self._lock:
            group = self.group_of_point(point)
            for step in range(self.groups):
                ring = self._rings[(group + step) % self.groups]
                try:
                    return ring.get_at(point)
                except EmptyRingError:
                    continue  # whole group down: spill clockwise
            raise EmptyRingError("every shard group is empty")

    def group_siblings(self, member: str, max_members: int) -> List[str]:
        """Deterministic distinct-member walk CONFINED to `member`'s own
        shard group, clockwise from its first virtual point — the hedge
        candidate order. Strictly group-confined because a hedge carries
        a batch of the primary's key range: duplicating it onto another
        group's instance would merge those keys off-range silently.
        Empty when the member has no live group siblings (then don't
        hedge; the breaker/failover path owns recovery). Note the walk
        key is the member's OWN point inside its group's ring — the
        plain walk_at from point_of(member) would start in whatever
        group those point bits land in, not the member's."""
        with self._lock:
            ring = self._rings[self.group_of(member)]
            try:
                walked = ring.walk_at(self.point_of(member), max_members)
            except EmptyRingError:
                return []
            return [m for m in walked if m != member]

    def walk_at(self, point: int, max_members: int) -> List[str]:
        """Deterministic failover order, group-confined first: the
        key's own group's members (primary first), then — only past
        them — neighboring groups clockwise. A sick primary therefore
        re-homes within its shard group, and cross-group spill happens
        only when the walk is allowed to run that deep."""
        with self._lock:
            out: List[str] = []
            group = self.group_of_point(point)
            for step in range(self.groups):
                ring = self._rings[(group + step) % self.groups]
                try:
                    for member in ring.walk_at(
                            point, max_members - len(out)):
                        out.append(member)
                except EmptyRingError:
                    continue
                if len(out) >= max_members:
                    break
            if not out:
                raise EmptyRingError("every shard group is empty")
            return out
