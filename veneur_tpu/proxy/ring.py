"""Consistent-hash ring for sharding metrics across global instances.

Semantics parity with the reference's vendored stathat/consistent ring
(used at proxy/destinations/destinations.go:127-141): members are placed
at many virtual points on a ring; `get(key)` walks clockwise from the
key's hash to the first member, so adding/removing one member only remaps
~1/N of keys. Hash is fnv1a-64 (our host keying hash) rather than the
reference's crc32 — both give uniform placement; only intra-cluster
consistency matters, and every veneur-tpu proxy uses the same function.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional

from veneur_tpu.util import fnv

DEFAULT_REPLICAS = 20


class EmptyRingError(LookupError):
    pass


class ConsistentRing:
    def __init__(self, replicas: int = DEFAULT_REPLICAS):
        self.replicas = replicas
        self._lock = threading.RLock()
        self._points: List[int] = []  # sorted hash points
        self._owner: Dict[int, str] = {}  # point -> member
        self._members: set = set()

    def _point(self, member: str, i: int) -> int:
        return fnv.fnv1a_64(f"{i}{member}".encode())

    def add(self, member: str) -> None:
        with self._lock:
            if member in self._members:
                return
            self._members.add(member)
            for i in range(self.replicas):
                pt = self._point(member, i)
                if pt in self._owner:
                    continue  # vanishing chance of 64-bit collision
                self._owner[pt] = member
                bisect.insort(self._points, pt)

    def remove(self, member: str) -> None:
        with self._lock:
            if member not in self._members:
                return
            self._members.discard(member)
            for i in range(self.replicas):
                pt = self._point(member, i)
                if self._owner.get(pt) == member:
                    del self._owner[pt]
                    idx = bisect.bisect_left(self._points, pt)
                    if idx < len(self._points) and self._points[idx] == pt:
                        del self._points[idx]

    def set_members(self, members: List[str]) -> None:
        with self._lock:
            for member in list(self._members - set(members)):
                self.remove(member)
            for member in members:
                self.add(member)

    def members(self) -> List[str]:
        with self._lock:
            return sorted(self._members)

    def __len__(self) -> int:
        return len(self._members)

    def get(self, key: str) -> str:
        return self.get_at(self.point_of(key))

    @staticmethod
    def point_of(key: str) -> int:
        """The key's ring point. Membership-independent, so callers on
        a hot path may cache it per key and skip re-hashing (the Python
        fnv loop dominates a lookup); get_at(point) must give the same
        member get(key) would."""
        return fnv.fnv1a_64(key.encode())

    def get_at(self, point: int) -> str:
        with self._lock:
            if not self._points:
                raise EmptyRingError("empty consistent-hash ring")
            idx = bisect.bisect_right(self._points, point)
            if idx == len(self._points):
                idx = 0
            return self._owner[self._points[idx]]

    def walk_at(self, point: int, max_members: int) -> List[str]:
        """Up to `max_members` DISTINCT members clockwise from `point`,
        primary first — the deterministic failover order: every proxy
        with the same membership walks the same sequence, so a key whose
        primary is sick lands on the same healthy node cluster-wide."""
        with self._lock:
            if not self._points:
                raise EmptyRingError("empty consistent-hash ring")
            out: List[str] = []
            seen = set()
            idx = bisect.bisect_right(self._points, point)
            n = len(self._points)
            for step in range(n):
                member = self._owner[self._points[(idx + step) % n]]
                if member not in seen:
                    seen.add(member)
                    out.append(member)
                    if len(out) >= max_members:
                        break
            return out

    def get_two(self, key: str) -> tuple:
        """The owner and the next distinct member clockwise (for
        replicated sends; reference ring offers Get/GetTwo/GetN)."""
        with self._lock:
            point = self.point_of(key)
            first = self.get_at(point)
            if len(self._members) < 2:
                return first, first
            idx = bisect.bisect_right(self._points, point)
            n = len(self._points)
            for step in range(n):
                member = self._owner[self._points[(idx + step) % n]]
                if member != first:
                    return first, member
            return first, first
