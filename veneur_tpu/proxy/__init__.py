from veneur_tpu.proxy.proxy import ProxyServer  # noqa: F401
from veneur_tpu.proxy.ring import ConsistentRing, EmptyRingError  # noqa: F401
