"""Destination pool: one buffered gRPC sender per global instance, plus
the consistent-hash ring that maps metric keys onto them.

Parity with reference proxy/destinations/destinations.go:14-152 and
proxy/connect/connect.go: each destination has a bounded send queue
drained by a sender thread that batches metrics into
Forward.SendMetricsV2 client streams; a destination that keeps failing
closes itself and is removed from the ring, so traffic re-shards onto
the survivors until discovery re-adds it.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Callable, Dict, List, Optional

import grpc

from veneur_tpu.forward.protos import metric_pb2
from veneur_tpu.forward.wire import _serialize_metric, send_batch
from veneur_tpu.ops import hll_ref
from veneur_tpu.proxy.ring import ConsistentRing, EmptyRingError
from veneur_tpu.util.grpctls import GrpcTLS, secure_or_insecure_channel
from veneur_tpu.util.resilience import CircuitBreaker

logger = logging.getLogger("veneur_tpu.proxy.destinations")

_EMPTY_DESERIALIZER = lambda _: b""  # noqa: E731


class Destination:
    def __init__(self, address: str,
                 on_close: Callable[["Destination"], None],
                 send_buffer: int = 4096, batch: int = 512,
                 flush_interval: float = 0.5,
                 max_consecutive_failures: int = 3,
                 tls: Optional[GrpcTLS] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 observatory=None):
        self.address = address
        self._on_close = on_close
        # instrumented when the proxy runs a latency observatory: queue
        # depth + enqueue->send dwell ride the shared queue.* telemetry
        self._queue: "queue.Queue" = (
            observatory.instrument_queue(
                f"proxy_dest:{address}", maxsize=send_buffer)
            if observatory is not None
            else queue.Queue(maxsize=send_buffer))
        self._observatory = observatory
        self._batch = batch
        self._flush_interval = flush_interval
        # shared breaker replaces the old ad-hoc _failures counter: the
        # sender thread feeds it; opening it closes the destination
        # (ring removal — traffic re-shards onto the survivors until
        # discovery re-adds the address, reference destinations.go:99)
        self.breaker = breaker or CircuitBreaker(
            failure_threshold=max_consecutive_failures,
            name=f"proxy-dest:{address}")
        self.closed = threading.Event()
        self.sent_total = 0
        self.dropped_total = 0
        self.shed_open_total = 0  # immediate sheds while the breaker is open
        # distinct forwarded metric keys, as a p=14 HLL over the ring-key
        # hash (the proxy's side of the cardinality observatory: which
        # destination is absorbing a key explosion). Fed by note_key on
        # the routing path; cumulative for the destination's lifetime.
        self.key_hll = hll_ref.HLL()
        self._channel = secure_or_insecure_channel(address, tls)
        # batches hold Metric objects (the V2 ingest path) or raw wire
        # bytes (the native V1 re-scatter): the serializer passes both
        self._send_v2 = self._channel.stream_unary(
            "/forwardrpc.Forward/SendMetricsV2",
            request_serializer=_serialize_metric,
            response_deserializer=_EMPTY_DESERIALIZER)
        # bulk path: one unary MetricList per batch instead of a
        # per-metric stream; a reference-style receiver that refuses it
        # pins this destination to V2 (same policy as ForwardClient)
        self._send_v1 = self._channel.unary_unary(
            "/forwardrpc.Forward/SendMetrics",
            request_serializer=lambda b: b,
            response_deserializer=_EMPTY_DESERIALIZER)
        self._v1_ok = True
        self._thread = threading.Thread(
            target=self._run, name=f"proxy-dest-{address}", daemon=True)
        self._thread.start()

    def note_key(self, key_hash: int) -> None:
        """Record one routed metric key (pre-hashed 64-bit). Lock-free
        register max: concurrent updates may lose a race, which can only
        UNDER-estimate by a hair — a counter-style lock on the per-metric
        routing path would cost more than the estimate is worth."""
        self.key_hll.insert_hash(key_hash)

    def send(self, metric: metric_pb2.Metric) -> bool:
        """Non-blocking enqueue first; fall back to a short blocking wait;
        drop if the destination is closed or still saturated (reference
        handlers.go:100-164 semantics).

        The blocking fallback intentionally applies backpressure to the
        caller's stream — matching the reference, where a saturated
        destination channel stalls that gRPC handler goroutine. One sick
        destination therefore slows (but doesn't kill) streams whose
        metrics hash to it; the bound is one flush_interval per metric,
        after which the metric drops.

        A sick destination sheds immediately instead: with the breaker
        OPEN (or the queue full while the destination is mid failure
        streak) there is nothing to apply backpressure FOR — the old
        behavior stalled the gRPC handler a full flush_interval per
        metric that hashed here, for the whole window between the first
        failure and the breaker tripping."""
        if self.closed.is_set():
            self.dropped_total += 1
            return False
        if not self.breaker.is_dispatchable:
            self.dropped_total += 1
            self.shed_open_total += 1
            return False
        try:
            self._queue.put_nowait(metric)
            return True
        except queue.Full:
            pass
        if self.breaker.consecutive_failures > 0:
            # failing-but-not-yet-open: the queue is full because the
            # sender can't drain it — blocking would stall the handler
            # without ever creating room
            self.dropped_total += 1
            self.shed_open_total += 1
            return False
        try:
            self._queue.put(metric, timeout=self._flush_interval)
            return True
        except queue.Full:
            self.dropped_total += 1
            return False

    def _drain_batch(self) -> List[metric_pb2.Metric]:
        out: List[metric_pb2.Metric] = []
        try:
            out.append(self._queue.get(timeout=self._flush_interval))
        except queue.Empty:
            return out
        while len(out) < self._batch:
            try:
                out.append(self._queue.get_nowait())
            except queue.Empty:
                break
        return out

    def _run(self) -> None:
        while not self.closed.is_set():
            batch = self._drain_batch()
            if not batch:
                continue
            try:
                # proxy batches are <= self._batch small metrics, so
                # RESOURCE_EXHAUSTED is far likelier transient receiver
                # overload than an oversized body: retry via V2 but keep
                # preferring V1; only UNIMPLEMENTED pins
                self._v1_ok = send_batch(
                    self._send_v1, self._send_v2, batch, 10.0,
                    self._v1_ok,
                    pin_codes=(grpc.StatusCode.UNIMPLEMENTED,),
                    retry_codes=(grpc.StatusCode.RESOURCE_EXHAUSTED,))
                self.sent_total += len(batch)
                self.breaker.record_success()
            except grpc.RpcError as e:
                self.breaker.record_failure()
                self.dropped_total += len(batch)
                code = e.code() if hasattr(e, "code") else None
                logger.warning("send to %s failed (%s), breaker %s",
                               self.address, code, self.breaker.state)
                if not self.breaker.is_dispatchable:
                    self.close(notify=True)
                    return

    def close(self, notify: bool = False) -> None:
        if self.closed.is_set():
            return
        self.closed.set()
        if self._observatory is not None:
            # retire the queue telemetry with the destination, or
            # discovery churn would grow the observatory unboundedly
            self._observatory.unregister_queue(
                f"proxy_dest:{self.address}")
        if notify:
            self._on_close(self)
        try:
            self._channel.close()
        except Exception:
            pass


class Destinations:
    """The live pool: address -> Destination plus the ring."""

    def __init__(self, send_buffer: int = 4096, batch: int = 512,
                 flush_interval: float = 0.5,
                 tls: Optional[GrpcTLS] = None,
                 max_consecutive_failures: int = 3,
                 observatory=None):
        self._lock = threading.RLock()
        self._pool: Dict[str, Destination] = {}
        self.ring = ConsistentRing()
        self._send_buffer = send_buffer
        self._batch = batch
        self._flush_interval = flush_interval
        self._tls = tls
        self._max_failures = max_consecutive_failures
        self._observatory = observatory

    def set_destinations(self, addresses: List[str]) -> None:
        """Reconcile the pool with a fresh discovery result."""
        with self._lock:
            wanted = set(addresses)
            for address in list(self._pool):
                if address not in wanted:
                    self._remove_locked(address)
            for address in addresses:
                if address not in self._pool:
                    self._pool[address] = Destination(
                        address, self._on_destination_closed,
                        send_buffer=self._send_buffer, batch=self._batch,
                        flush_interval=self._flush_interval, tls=self._tls,
                        max_consecutive_failures=self._max_failures,
                        observatory=self._observatory)
                    self.ring.add(address)

    def addresses(self) -> List[str]:
        """Current pool membership (discovery/elasticity observability)."""
        with self._lock:
            return sorted(self._pool)

    def _remove_locked(self, address: str) -> None:
        dest = self._pool.pop(address, None)
        self.ring.remove(address)
        if dest is not None:
            dest.close()

    def _on_destination_closed(self, dest: Destination) -> None:
        """Self-removal on connection failure (destinations.go:99-110);
        discovery re-adds the address when it becomes healthy again."""
        with self._lock:
            if self._pool.get(dest.address) is dest:
                self._pool.pop(dest.address)
                self.ring.remove(dest.address)

    def get(self, key: str) -> Destination:
        return self.get_at(self.ring.point_of(key))

    def get_at(self, point: int) -> Destination:
        """Lookup by pre-computed ring point (ring.point_of): the proxy
        route cache stores points so the per-metric hot path skips the
        Python fnv hash entirely."""
        with self._lock:
            address = self.ring.get_at(point)
            dest = self._pool.get(address)
            if dest is None:
                raise EmptyRingError(f"no destination for {address}")
            return dest

    def size(self) -> int:
        with self._lock:
            return len(self._pool)

    def telemetry_rows(self) -> List[tuple]:
        """(name, kind, value, tags) rows for the proxy's /metrics
        registry: per-destination send/drop/shed totals, queue depth,
        and breaker state."""
        with self._lock:
            pool = list(self._pool.values())
        rows: List[tuple] = []
        for dest in pool:
            tags = [f"destination:{dest.address}"]
            rows.append(("proxy.dest.sent", "counter",
                         float(dest.sent_total), tags))
            rows.append(("proxy.dest.dropped", "counter",
                         float(dest.dropped_total), tags))
            rows.append(("proxy.dest.shed_open", "counter",
                         float(dest.shed_open_total), tags))
            rows.append(("proxy.dest.queue_depth", "gauge",
                         float(dest._queue.qsize()), tags))
            rows.append(("proxy.dest.forwarded_keys", "gauge",
                         dest.key_hll.estimate(), tags))
            rows.append(("resilience.breaker_state", "gauge",
                         float(dest.breaker.state_code), tags))
        return rows

    def clear(self) -> None:
        with self._lock:
            for address in list(self._pool):
                self._remove_locked(address)

    def flush_wait(self, timeout: float = 5.0) -> None:
        """Best-effort wait until queued metrics drain (for tests and
        graceful shutdown)."""
        import time
        deadline = time.monotonic() + timeout
        with self._lock:
            pool = list(self._pool.values())
        for dest in pool:
            while (not dest._queue.empty()
                   and time.monotonic() < deadline):
                time.sleep(0.01)
