"""Destination pool: one buffered gRPC sender per global instance, plus
the consistent-hash ring that maps metric keys onto them.

Parity with reference proxy/destinations/destinations.go:14-152 and
proxy/connect/connect.go: each destination has a bounded send queue
drained by a sender thread that batches metrics into
Forward.SendMetricsV2 client streams; a destination that keeps failing
closes itself and is removed from the ring, so traffic re-shards onto
the survivors until discovery re-adds it.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional

import grpc

from veneur_tpu.forward.protos import metric_pb2
from veneur_tpu.forward.wire import (_frame_v1, _serialize_metric,
                                     combine_metadata, decode_flow_counts,
                                     interval_metadata, send_batch,
                                     token_metadata, trace_metadata)
from veneur_tpu.ops import hll_ref
from veneur_tpu.proxy.ring import (ConsistentRing, EmptyRingError,
                                   ShardGroupRing, parse_shard_suffix)
from veneur_tpu.util import chaos as chaos_mod
from veneur_tpu.util.chaos import ChaosError
from veneur_tpu.util.grpctls import GrpcTLS, secure_or_insecure_channel
from veneur_tpu.util.resilience import CircuitBreaker

logger = logging.getLogger("veneur_tpu.proxy.destinations")

_EMPTY_DESERIALIZER = lambda _: b""  # noqa: E731


class Destination:
    def __init__(self, address: str,
                 on_close: Callable[["Destination"], None],
                 send_buffer: int = 4096, batch: int = 512,
                 flush_interval: float = 0.5,
                 max_consecutive_failures: int = 3,
                 tls: Optional[GrpcTLS] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 observatory=None,
                 hedge_after: float = 0.0,
                 hedge_peer: Optional[Callable[[], Optional["Destination"]]]
                 = None, ledger=None, trace_source=None, trace_plane=None):
        self.address = address
        self._on_close = on_close
        # cross-tier self-tracing: trace_source() -> (trace_id,
        # parent_span_id, exemplar_blob) — the routing tier's latest
        # active lineage (latest-wins per pool; batches and RPCs don't
        # align 1:1, and one local's interval batch dominates a flush).
        # Each outgoing batch opens a proxy.dest.send span under it and
        # re-injects (trace_id, send_span_id) + the exemplar sidecar as
        # gRPC metadata, hedged duplicates carrying the SAME span id so
        # the global's token dedupe keeps exactly one tree.
        self._trace_source = trace_source
        self._trace_plane = trace_plane
        # proxy flow ledger: successful sends reconcile against the
        # receiver's FlowCounts response (proxy_tier identity); the
        # enqueue/sent/drop counters below feed the proxy_egress
        # identity via Destinations.flow_totals()
        self.ledger = ledger
        # hedged sends: when a batch's primary send exceeds
        # `hedge_after` seconds, the SAME batch (same idempotency token)
        # fires at the next healthy ring member via `hedge_peer`; the
        # import server's token dedupe keeps a late-landing primary from
        # double-merging on ITS node. 0 disables hedging.
        self._hedge_after = max(0.0, float(hedge_after))
        self._hedge_peer = hedge_peer
        self.hedge_fired_total = 0
        self.hedge_wins_total = 0
        # idempotency token namespace for this sender's batches
        self._token_id = uuid.uuid4().hex[:12]
        self._token_seq = 0
        # instrumented when the proxy runs a latency observatory: queue
        # depth + enqueue->send dwell ride the shared queue.* telemetry
        self._queue: "queue.Queue" = (
            observatory.instrument_queue(
                f"proxy_dest:{address}", maxsize=send_buffer)
            if observatory is not None
            else queue.Queue(maxsize=send_buffer))
        self._observatory = observatory
        self._batch = batch
        self._flush_interval = flush_interval
        # shared breaker replaces the old ad-hoc _failures counter: the
        # sender thread feeds it; opening it closes the destination
        # (ring removal — traffic re-shards onto the survivors until
        # discovery re-adds the address, reference destinations.go:99)
        self.breaker = breaker or CircuitBreaker(
            failure_threshold=max_consecutive_failures,
            name=f"proxy-dest:{address}")
        self.closed = threading.Event()
        # sent_total is written by this sender's thread AND (on a hedge
        # win) by a hedging peer's thread; += is not atomic, and the
        # soaks pin exact accounting
        self._counter_lock = threading.Lock()
        self.sent_total = 0
        self.dropped_total = 0
        self.shed_open_total = 0  # immediate sheds while the breaker is open
        # flow-ledger stage counters: metrics that made it INTO the
        # queue, and the subset of dropped_total lost AFTER enqueue
        # (batch send failures, close-time drains) — together with the
        # live queue depth they satisfy enqueued == sent +
        # dropped_enqueued + queued, pool-wide (hedge wins credit the
        # delivering peer's sent_total, so the identity holds only in
        # aggregate — and in aggregate is how the ledger checks it)
        self.enqueued_total = 0
        self.dropped_enqueued_total = 0
        # metrics drained from the queue but not yet accounted sent/
        # dropped — an inventory stock, so a ledger close landing
        # mid-send still balances
        self.inflight_batch = 0
        # distinct forwarded metric keys, as a p=14 HLL over the ring-key
        # hash (the proxy's side of the cardinality observatory: which
        # destination is absorbing a key explosion). Fed by note_key on
        # the routing path; cumulative for the destination's lifetime.
        self.key_hll = hll_ref.HLL()
        # shared backoff cap: a readmitted member must be dialable the
        # moment its probes pass, not whenever grpc's post-outage
        # backoff (20s+) next fires
        from veneur_tpu.util.grpctls import RECONNECT_BACKOFF_OPTIONS
        self._channel = secure_or_insecure_channel(
            address, tls, options=list(RECONNECT_BACKOFF_OPTIONS))
        # batches hold Metric objects (the V2 ingest path) or raw wire
        # bytes (the native V1 re-scatter): the serializer passes both
        self._send_v2 = self._channel.stream_unary(
            "/forwardrpc.Forward/SendMetricsV2",
            request_serializer=_serialize_metric,
            response_deserializer=_EMPTY_DESERIALIZER)
        # bulk path: one unary MetricList per batch instead of a
        # per-metric stream; a reference-style receiver that refuses it
        # pins this destination to V2 (same policy as ForwardClient)
        self._send_v1 = self._channel.unary_unary(
            "/forwardrpc.Forward/SendMetrics",
            request_serializer=lambda b: b,
            response_deserializer=_EMPTY_DESERIALIZER)
        self._v1_ok = True
        self._thread = threading.Thread(
            target=self._run, name=f"proxy-dest-{address}", daemon=True)
        self._thread.start()

    def note_key(self, key_hash: int) -> None:
        """Record one routed metric key (pre-hashed 64-bit). Lock-free
        register max: concurrent updates may lose a race, which can only
        UNDER-estimate by a hair — a counter-style lock on the per-metric
        routing path would cost more than the estimate is worth."""
        self.key_hll.insert_hash(key_hash)

    def send(self, metric: metric_pb2.Metric,
             interval: float = 0.0) -> bool:
        """Non-blocking enqueue first; fall back to a short blocking wait;
        drop if the destination is closed or still saturated (reference
        handlers.go:100-164 semantics).

        `interval` is the sender's ``x-veneur-interval`` stamp (0.0 =
        live traffic): it rides the queue with the metric and is
        re-attached as metadata on the outgoing batch, so a WAL replay
        routed THROUGH the proxy still backfills into its original
        interval on the global instead of folding into the live flush.

        The blocking fallback intentionally applies backpressure to the
        caller's stream — matching the reference, where a saturated
        destination channel stalls that gRPC handler goroutine. One sick
        destination therefore slows (but doesn't kill) streams whose
        metrics hash to it; the bound is one flush_interval per metric,
        after which the metric drops.

        A sick destination sheds immediately instead: with the breaker
        OPEN (or the queue full while the destination is mid failure
        streak) there is nothing to apply backpressure FOR — the old
        behavior stalled the gRPC handler a full flush_interval per
        metric that hashed here, for the whole window between the first
        failure and the breaker tripping."""
        if self.closed.is_set():
            with self._counter_lock:
                self.dropped_total += 1
            return False
        if not self.breaker.is_dispatchable:
            with self._counter_lock:
                self.dropped_total += 1
                self.shed_open_total += 1
            return False
        entry = (metric, float(interval))
        try:
            self._queue.put_nowait(entry)
            with self._counter_lock:
                self.enqueued_total += 1
            return True
        except queue.Full:
            pass
        if self.breaker.consecutive_failures > 0:
            # failing-but-not-yet-open: the queue is full because the
            # sender can't drain it — blocking would stall the handler
            # without ever creating room
            with self._counter_lock:
                self.dropped_total += 1
                self.shed_open_total += 1
            return False
        try:
            self._queue.put(entry, timeout=self._flush_interval)
            with self._counter_lock:
                self.enqueued_total += 1
            return True
        except queue.Full:
            with self._counter_lock:
                self.dropped_total += 1
            return False

    def _drain_batch(self) -> List[tuple]:
        """Up to one batch of (metric, interval) queue entries."""
        out: List[tuple] = []
        try:
            out.append(self._queue.get(timeout=self._flush_interval))
        except queue.Empty:
            return out
        while len(out) < self._batch:
            try:
                out.append(self._queue.get_nowait())
            except queue.Empty:
                break
        return out

    def _trace_open(self, batch_len: int):
        """(extra metadata, proxy.dest.send span) for one batch — both
        None when no lineage is active (untraced traffic costs two
        attribute reads per BATCH, never per metric)."""
        src = self._trace_source
        if src is None:
            return None, None
        trace_id, parent_sid, blob = src()
        if not trace_id:
            return None, None
        span = None
        if self._trace_plane is not None:
            span = self._trace_plane.span(
                "proxy.dest.send", trace_id, parent_id=parent_sid,
                tags={"destination": self.address,
                      "metrics": str(batch_len)})
        parts = [trace_metadata(
            trace_id, span.id if span is not None else parent_sid)]
        if blob:
            from veneur_tpu.trace.store import EXEMPLAR_KEY
            parts.append(((EXEMPLAR_KEY, blob),))
        return combine_metadata(*parts), span

    def _run(self) -> None:
        while not self.closed.is_set():
            entries = self._drain_batch()
            if not entries:
                continue
            # one RPC per interval stamp: queue entries carry the
            # sender's x-veneur-interval (0.0 = live), and metadata is
            # per-RPC, so a drained batch mixing a WAL replay with live
            # traffic splits on the stamp boundaries (order preserved —
            # consecutive runs, no reordering)
            # every drained entry must be accounted (sent or dropped)
            # before this thread exits, or the proxy_egress identity
            # (enqueued == sent + dropped_enqueued + queued) leaks: a
            # mid-batch abort books the not-yet-attempted remainder as
            # dropped — close() only drains what is still queued
            start = 0
            while start < len(entries):
                interval = entries[start][1]
                end = start
                while end < len(entries) and entries[end][1] == interval:
                    end += 1
                batch = [m for m, _iv in entries[start:end]]
                start = end
                if not self._send_one(batch, interval):
                    leftover = len(entries) - start
                    if leftover:
                        with self._counter_lock:
                            self.dropped_total += leftover
                            self.dropped_enqueued_total += leftover
                    return

    def _send_one(self, batch: List, interval: float) -> bool:
        """One batch's send attempt (breaker, hedging, accounting);
        returns False when the destination closed itself (breaker
        open) and the sender thread must exit."""
        self.inflight_batch = len(batch)
        self._token_seq += 1
        token = f"dest:{self._token_id}:{self._token_seq}"
        extra_md, send_span = self._trace_open(len(batch))
        if interval:
            extra_md = combine_metadata(extra_md,
                                        interval_metadata(interval))
        try:
            hedge_won = False
            if self._hedge_after > 0 and self._hedge_peer is not None:
                # the chaos seam runs INSIDE the hedge-timed window
                # (chaos_forward_latency_ms makes THIS the slow
                # primary the budget fires against)
                hedge_won = self._send_hedged(batch, token,
                                              extra_md=extra_md)
            else:
                # the forward_send chaos seam covers proxy senders
                # too: injected errors exercise the breaker and
                # ejection paths deterministically
                chaos_mod.inject("forward_send")
                self.send_now(batch, token, extra_md=extra_md)
            if send_span is not None and hedge_won:
                send_span.set_tag("hedged", "true")
            if hedge_won:
                # the PEER delivered (and was credited inside
                # _send_hedged); the blown budget is a failure
                # signal for THIS node — a destination that never
                # completes inside the budget must eventually trip
                # its breaker so routing fails over instead of
                # paying hedge_after + a doubled RPC forever. No
                # close() here: probes/half-open own recovery.
                self.breaker.record_failure()
            else:
                # credit + in-flight clear under ONE lock hold so a
                # concurrent flow_totals() never sees the batch as
                # both sent and in flight
                with self._counter_lock:
                    self.sent_total += len(batch)
                    self.inflight_batch = 0
                self.breaker.record_success()
        except (grpc.RpcError, ChaosError) as e:
            if send_span is not None:
                send_span.error()
            self.breaker.record_failure()
            with self._counter_lock:
                self.dropped_total += len(batch)
                self.dropped_enqueued_total += len(batch)
                self.inflight_batch = 0
            code = e.code() if hasattr(e, "code") else None
            logger.warning("send to %s failed (%s), breaker %s",
                           self.address, code, self.breaker.state)
            if not self.breaker.is_dispatchable:
                self.inflight_batch = 0
                if send_span is not None:
                    send_span.finish()
                self.close(notify=True)
                return False
        finally:
            self.inflight_batch = 0
            if send_span is not None:
                send_span.finish()
        return True

    def send_now(self, batch, token: str, timeout: float = 10.0,
                 extra_md=None):
        """One blocking batch send with the idempotency token attached —
        also the entry point a PEER uses to deliver a hedged batch
        through this destination's channel. Raises grpc.RpcError on
        failure (the caller owns breaker/drop accounting). Returns the
        raw response bytes (the receiver's FlowCounts, when upgraded),
        already reconciled into the proxy's flow ledger. `extra_md`
        carries the trace lineage + exemplar sidecar, identical across
        a hedge pair.

        Proxy batches are <= self._batch small metrics, so
        RESOURCE_EXHAUSTED is far likelier transient receiver overload
        than an oversized body: retry via V2 but keep preferring V1;
        only UNIMPLEMENTED pins."""
        self._v1_ok, resp = send_batch(
            self._send_v1, self._send_v2, batch, timeout,
            self._v1_ok,
            pin_codes=(grpc.StatusCode.UNIMPLEMENTED,),
            retry_codes=(grpc.StatusCode.RESOURCE_EXHAUSTED,),
            metadata=combine_metadata(token_metadata(token), extra_md))
        self._note_tier(len(batch), resp)
        return resp

    def _note_tier(self, sent: int, resp) -> None:
        """Reconcile one acked batch against the receiver's FlowCounts
        (the proxy_tier identity); empty response = un-upgraded peer."""
        led = self.ledger
        if led is None or not sent:
            return
        counts = decode_flow_counts(resp)
        if counts is None:
            return
        led.note("dest.acked_reported", sent)
        if counts["duplicate"]:
            led.note("dest.remote_deduped", sent)
            return
        merged = int(counts["merged"])
        received = int(counts["received"])
        led.note("dest.remote_merged", merged)
        if received > merged:
            led.note("dest.remote_rejected", received - merged)

    def _send_hedged(self, batch, token: str,
                     timeout: float = 10.0, extra_md=None) -> bool:
        """Primary send with a latency budget: past `hedge_after`
        seconds the same batch (same token) fires at the next healthy
        ring member. First success wins; the loser is cancelled. The
        token makes a retry/hedge landing twice on ONE node merge once;
        see the README's hedging caveats for the cross-node window.
        Returns True when the PEER delivered the batch (the caller
        treats that as a failure signal for this node's breaker).

        The forward_send chaos seam runs inside the budget window, so
        chaos_forward_latency_ms >= the budget deterministically fires
        the hedge (the knob's reason to exist)."""
        budget_start = time.monotonic()
        chaos_mod.inject("forward_send")
        md = combine_metadata(token_metadata(token), extra_md)
        was_v1 = self._v1_ok
        if was_v1:
            body = b"".join(_frame_v1(m) for m in batch)
            fut = self._send_v1.future(body, timeout=timeout, metadata=md)
        else:
            fut = self._send_v2.future(iter(batch), timeout=timeout,
                                       metadata=md)
        remaining = max(0.0, self._hedge_after
                        - (time.monotonic() - budget_start))
        try:
            self._note_tier(len(batch), fut.result(timeout=remaining))
            return False
        except grpc.FutureTimeoutError:
            pass  # primary slow: hedge below
        except grpc.RpcError as e:
            code = e.code() if hasattr(e, "code") else None
            if was_v1 and code in (grpc.StatusCode.UNIMPLEMENTED,
                                   grpc.StatusCode.RESOURCE_EXHAUSTED):
                # V1 refusal: re-send through the SHARED transport
                # helper (send_now -> wire.send_batch) so the pin/retry
                # fallback policy lives in exactly one place; the token
                # makes the repeat attempt duplicate-safe
                self.send_now(batch, token, timeout=timeout,
                              extra_md=extra_md)
                return False
            raise
        peer = None
        try:
            peer = self._hedge_peer()
        except Exception:
            logger.exception("hedge peer selection failed")
        if peer is None or peer is self or peer.closed.is_set():
            # nobody to hedge to: wait out the primary
            self._note_tier(len(batch), fut.result())
            return False
        self.hedge_fired_total += 1
        logger.info("hedging slow send to %s via %s (budget %.3fs)",
                    self.address, peer.address, self._hedge_after)
        try:
            # the SAME lineage (and span id) rides the hedge: whichever
            # attempt the global accepts continues one connected tree,
            # the loser is dropped whole by its token
            peer.send_now(batch, token, timeout=timeout,
                          extra_md=extra_md)
        except (grpc.RpcError, ChaosError):
            # hedge lost too: the primary is the last hope (may raise)
            self._note_tier(len(batch), fut.result())
            return False
        self.hedge_wins_total += 1
        # delivery is credited to the node that actually absorbed it
        with peer._counter_lock:
            peer.sent_total += len(batch)
        fut.cancel()
        return True

    def close(self, notify: bool = False) -> None:
        if self.closed.is_set():
            return
        self.closed.set()
        # final drain BEFORE retiring the queue telemetry: items still
        # queued at shutdown will never send — get()-ing them records
        # their dwell into the (still-registered) observatory series and
        # counts them as drops, instead of silently discarding both the
        # samples and the accounting with the unregister
        drained = 0
        while True:
            try:
                self._queue.get_nowait()
                drained += 1
            except queue.Empty:
                break
        if drained:
            with self._counter_lock:
                self.dropped_total += drained
                self.dropped_enqueued_total += drained
            logger.info("destination %s closed with %d undelivered "
                        "metrics (counted dropped)", self.address, drained)
        if self._observatory is not None:
            # retire the queue telemetry with the destination, or
            # discovery churn would grow the observatory unboundedly
            self._observatory.unregister_queue(
                f"proxy_dest:{self.address}")
        if notify:
            self._on_close(self)
        try:
            self._channel.close()
        except Exception:
            pass


class Destinations:
    """The live pool: address -> Destination plus the ring."""

    def __init__(self, send_buffer: int = 4096, batch: int = 512,
                 flush_interval: float = 0.5,
                 tls: Optional[GrpcTLS] = None,
                 max_consecutive_failures: int = 3,
                 observatory=None,
                 hedge_after: float = 0.0,
                 failover_walk: int = 2,
                 ledger=None, trace_plane=None,
                 shard_groups: int = 0):
        self._ledger = ledger
        # latest active trace lineage: (trace_id, parent_span_id,
        # exemplar_blob), set by the routing handlers per RPC (plain
        # tuple assignment — GIL-atomic) and read by every sender at
        # batch-send time; (0, 0, None) = untraced traffic
        self._trace_plane = trace_plane
        self._active_trace = (0, 0, None)
        self._lock = threading.RLock()
        self._pool: Dict[str, Destination] = {}
        # shard-aware ring (shard_groups > 1): the key-digest space
        # splits into G contiguous ranges, each with its own consistent
        # ring of the global instances serving that range's device
        # shards — ejection re-homes only the sick member's key range
        # within its group (proxy/ring.py ShardGroupRing)
        self.shard_groups = max(0, int(shard_groups))
        self.ring = (ShardGroupRing(self.shard_groups)
                     if self.shard_groups > 1 else ConsistentRing())
        self.group_spill_total = 0
        self._send_buffer = send_buffer
        self._batch = batch
        self._flush_interval = flush_interval
        self._tls = tls
        self._max_failures = max_consecutive_failures
        self._observatory = observatory
        self._hedge_after = max(0.0, float(hedge_after))
        # bounded failover: how many ADDITIONAL ring members past the
        # primary a sick key's lookup may walk; deterministic, so every
        # proxy re-homes the key to the same survivor
        self._failover_walk = max(0, int(failover_walk))
        # health-ejected members: kept in the POOL (their sender drains
        # and probes keep targeting them) but out of the RING, so no new
        # keys hash there — and discovery re-adding the address must not
        # sneak it back into the ring before the prober readmits it
        self._ejected: set = set()
        self.failover_routed_total = 0
        # point -> (survivor address, stamp): while a primary is sick
        # but not yet health-ejected, every routed metric would re-walk
        # the ring for the same answer — memoized for a short TTL (the
        # window itself ends at ejection, which removes the node from
        # the ring and makes normal hashing correct again)
        self._failover_cache: Dict[int, tuple] = {}
        # counters of destinations that left the pool (self-closed on
        # breaker open, or dropped by discovery): without this fold the
        # pool's lifetime sent/dropped accounting silently resets on
        # churn — exactly when an operator is trying to balance a loss.
        # The flow-ledger stage counters (enqueued, dropped-after-
        # enqueue, hedge outcomes) fold too, so /debug/ledger totals
        # survive ring membership changes instead of going negative at
        # the next probe delta.
        self.retired_sent_total = 0
        self.retired_dropped_total = 0
        self.retired_shed_open_total = 0
        self.retired_enqueued_total = 0
        self.retired_dropped_enqueued_total = 0
        self.retired_hedge_fired_total = 0
        self.retired_hedge_wins_total = 0

    def set_destinations(self, addresses: List[str]) -> None:
        """Reconcile the pool with a fresh discovery result. With shard
        groups enabled, a discovered address may pin its group with an
        ``addr#<g>`` suffix (stripped before dialing); unsuffixed
        addresses hash to a group stably."""
        with self._lock:
            parsed = []
            for raw_addr in addresses:
                address, group = parse_shard_suffix(raw_addr)
                if group is not None \
                        and isinstance(self.ring, ShardGroupRing):
                    try:
                        self.ring.assign(address, group)
                    except ValueError:
                        logger.warning(
                            "discovery tried to move live member %s to "
                            "shard group %d; keeping its current group",
                            address, group)
                parsed.append(address)
            addresses = parsed
            wanted = set(addresses)
            for address in list(self._pool):
                if address not in wanted:
                    self._remove_locked(address)
            for address in addresses:
                if address not in self._pool:
                    self._pool[address] = Destination(
                        address, self._on_destination_closed,
                        send_buffer=self._send_buffer, batch=self._batch,
                        flush_interval=self._flush_interval, tls=self._tls,
                        max_consecutive_failures=self._max_failures,
                        observatory=self._observatory,
                        hedge_after=self._hedge_after,
                        hedge_peer=(lambda a=address:
                                    self.hedge_peer_for(a)),
                        ledger=self._ledger,
                        trace_source=self._trace_context,
                        trace_plane=self._trace_plane)
                    if address not in self._ejected:
                        self.ring.add(address)

    def regroup(self, shard_groups: int) -> int:
        """Follow a serving-tier elastic reshard (parallel/reshard.py):
        re-partition the door's digest-range groups to the new shard
        count. Sticky assignments survive (proxy/ring.py regroup), so
        every key whose group membership didn't change keeps its owner
        exactly; ejected members stay out of the ring and rejoin their
        (re-derived) group at readmission. Returns the number of
        members whose group changed."""
        with self._lock:
            if not isinstance(self.ring, ShardGroupRing):
                raise ValueError(
                    "shard groups are not enabled on this pool")
            moved = self.ring.regroup(int(shard_groups))
            self.shard_groups = int(shard_groups)
            # memoized failover survivors reference old-group walks
            self._failover_cache.clear()
            return moved

    def addresses(self) -> List[str]:
        """Current pool membership (discovery/elasticity observability)."""
        with self._lock:
            return sorted(self._pool)

    def note_trace(self, trace_id: int, parent_span_id: int,
                   exemplar_blob) -> None:
        """Latch the routing tier's active lineage (latest-wins); the
        senders re-inject it on their next batch. (0, 0, None) clears."""
        self._active_trace = (int(trace_id), int(parent_span_id),
                              exemplar_blob)

    def _trace_context(self):
        return self._active_trace

    def _retire_locked(self, dest: Destination) -> None:
        self.retired_sent_total += dest.sent_total
        self.retired_dropped_total += dest.dropped_total
        self.retired_shed_open_total += dest.shed_open_total
        self.retired_enqueued_total += dest.enqueued_total
        self.retired_dropped_enqueued_total += dest.dropped_enqueued_total
        self.retired_hedge_fired_total += dest.hedge_fired_total
        self.retired_hedge_wins_total += dest.hedge_wins_total

    def _remove_locked(self, address: str) -> None:
        dest = self._pool.pop(address, None)
        self.ring.remove(address)
        # discovery dropped the member outright: clear its ejection so a
        # future re-add starts fresh in the ring
        self._ejected.discard(address)
        if dest is not None:
            dest.close()
            # close() drained the queue into dropped_total, so the fold
            # runs after it — nothing in flight escapes the accounting
            self._retire_locked(dest)

    def _on_destination_closed(self, dest: Destination) -> None:
        """Self-removal on connection failure (destinations.go:99-110);
        discovery re-adds the address when it becomes healthy again."""
        with self._lock:
            if self._pool.get(dest.address) is dest:
                self._pool.pop(dest.address)
                self.ring.remove(dest.address)
                self._retire_locked(dest)

    # -- health ejection (proxy/health.py drives these) ------------------

    def eject(self, address: str) -> None:
        """Take a member out of the RING (keys re-shard onto survivors)
        while keeping its pool entry alive for probes and queue drain."""
        with self._lock:
            self._ejected.add(address)
            self.ring.remove(address)

    def readmit(self, address: str) -> None:
        """Restore an ejected member's ring points — identical virtual
        points recompute from the same address, so every key it owned
        returns to it exactly."""
        with self._lock:
            self._ejected.discard(address)
            if address in self._pool:
                self.ring.add(address)

    def ejected_addresses(self) -> List[str]:
        with self._lock:
            return sorted(self._ejected)

    def group_table(self) -> List[dict]:
        """Per-shard-group membership/health snapshot (ready-state and
        /debug surfaces); empty when shard groups are disabled. A group
        with live=[] has lost its whole key range to clockwise spill —
        the degraded-mesh runbook's page-now condition."""
        if self.shard_groups <= 1:
            return []
        with self._lock:
            live = self.ring.group_members()
            ejected_by_group: List[List[str]] = [
                [] for _ in range(self.shard_groups)]
            for address in sorted(self._ejected):
                ejected_by_group[self.ring.group_of(address)].append(
                    address)
        return [{"group": g, "live": live[g],
                 "ejected": ejected_by_group[g]}
                for g in range(self.shard_groups)]

    def _note_group_spill(self, point: int, address: str) -> None:
        """Count a metric routed onto a member outside its key's shard
        group (caller holds _lock). Every return path of get_at runs
        this — primary hop, failover cache hit, failover walk — so
        proxy.ring.group_spill is the complete off-range routing count,
        not just the empty-group clockwise spill."""
        if (self.shard_groups > 1
                and self.ring.group_of(address)
                != self.ring.group_of_point(point)):
            self.group_spill_total += 1

    def hedge_peer_for(self, address: str) -> Optional[Destination]:
        """The next healthy DISTINCT ring member clockwise from
        `address`'s own first virtual point — the deterministic hedge
        target for a slow primary. With shard groups the candidates are
        confined to `address`'s OWN group (a hedge duplicates a batch
        of the primary's key range; landing it on another group would
        merge those keys off-range silently) — a group with no healthy
        sibling simply doesn't hedge."""
        with self._lock:
            if isinstance(self.ring, ShardGroupRing):
                candidates = self.ring.group_siblings(
                    address, len(self._pool) or 1)
            else:
                try:
                    candidates = self.ring.walk_at(
                        self.ring.point_of(address), len(self._pool) or 1)
                except EmptyRingError:
                    return None
            for candidate in candidates:
                if candidate == address:
                    continue
                dest = self._pool.get(candidate)
                if (dest is not None and not dest.closed.is_set()
                        and dest.breaker.likely_dispatchable):
                    return dest
            return None

    def get(self, key: str) -> Destination:
        return self.get_at(self.ring.point_of(key))

    def get_at(self, point: int) -> Destination:
        """Lookup by pre-computed ring point (ring.point_of): the proxy
        route cache stores points so the per-metric hot path skips the
        Python fnv hash entirely.

        Failover: a healthy primary answers directly (ejected members
        are already out of the ring, so this is the common path). A
        primary whose breaker is open or whose sender closed re-homes
        the key with a bounded deterministic walk to the next healthy
        member — mergeable state keeps flowing through a partial outage
        instead of shedding at the sick node's door."""
        with self._lock:
            address = self.ring.get_at(point)
            # a routed member outside the key's shard group means its
            # range spilled — whole group empty at the primary hop, or
            # a failover walk that ran past the group's live members —
            # loud either way: these keys merge on instances that don't
            # serve their device-shard range
            self._note_group_spill(point, address)
            dest = self._pool.get(address)
            # likely_dispatchable: lock-free in the common healthy case
            # — this runs per routed metric, and send() re-checks the
            # breaker authoritatively anyway
            if (dest is not None and not dest.closed.is_set()
                    and dest.breaker.likely_dispatchable):
                return dest
            now = time.monotonic()
            cached = self._failover_cache.get(point)
            if cached is not None and now - cached[1] < 1.0:
                alt = self._pool.get(cached[0])
                if (alt is not None and not alt.closed.is_set()
                        and alt.breaker.likely_dispatchable):
                    self.failover_routed_total += 1
                    self._note_group_spill(point, cached[0])
                    return alt
            for candidate in self.ring.walk_at(
                    point, self._failover_walk + 1)[1:]:
                alt = self._pool.get(candidate)
                if (alt is not None and not alt.closed.is_set()
                        and alt.breaker.likely_dispatchable):
                    self.failover_routed_total += 1
                    self._note_group_spill(point, candidate)
                    if len(self._failover_cache) > 100_000:
                        self._failover_cache.clear()
                    self._failover_cache[point] = (candidate, now)
                    return alt
            # every walked member is sick: keep the primary's accounting
            # (its send() sheds and counts) rather than inventing a drop
            if dest is None:
                raise EmptyRingError(f"no destination for {address}")
            return dest

    def size(self) -> int:
        with self._lock:
            return len(self._pool)

    def flow_totals(self) -> Dict[str, float]:
        """Pool-wide cumulative flow counters (live + retired) plus the
        live queue depth — the proxy ledger's probe/stock source. The
        retired folds make every figure monotonic across ring churn,
        which is what lets the ledger treat them as counters."""
        with self._lock:
            pool = list(self._pool.values())
            out = {
                "enqueued": float(self.retired_enqueued_total),
                "sent": float(self.retired_sent_total),
                "dropped_enqueued":
                    float(self.retired_dropped_enqueued_total),
                "queued": 0.0,
            }
        for dest in pool:
            # one lock hold per destination: the sender clears its
            # in-flight stock under the same lock it credits sent/
            # dropped, so this read can't see a batch on both sides
            with dest._counter_lock:
                out["enqueued"] += dest.enqueued_total
                out["sent"] += dest.sent_total
                out["dropped_enqueued"] += dest.dropped_enqueued_total
                out["queued"] += dest._queue.qsize() + dest.inflight_batch
        return out

    def telemetry_rows(self) -> List[tuple]:
        """(name, kind, value, tags) rows for the proxy's /metrics
        registry: per-destination send/drop/shed totals, queue depth,
        and breaker state."""
        with self._lock:
            pool = list(self._pool.values())
            failover = self.failover_routed_total
            retired = (self.retired_sent_total, self.retired_dropped_total,
                       self.retired_shed_open_total,
                       self.retired_enqueued_total,
                       self.retired_dropped_enqueued_total,
                       self.retired_hedge_fired_total,
                       self.retired_hedge_wins_total)
        rows: List[tuple] = [
            ("proxy.ring.failover_routed", "counter", float(failover), ()),
            ("proxy.ring.shard_groups", "gauge",
             float(self.shard_groups), ()),
            ("proxy.ring.group_spill", "counter",
             float(self.group_spill_total), ()),
            # churn-proof totals: per-destination rows below reset when a
            # destination is replaced; these fold in the retired ones
            ("proxy.dest.retired_sent", "counter", float(retired[0]), ()),
            ("proxy.dest.retired_dropped", "counter", float(retired[1]), ()),
            ("proxy.dest.retired_shed_open", "counter",
             float(retired[2]), ()),
            ("proxy.dest.retired_enqueued", "counter",
             float(retired[3]), ()),
            ("proxy.dest.retired_dropped_enqueued", "counter",
             float(retired[4]), ()),
            ("proxy.dest.retired_hedge_fired", "counter",
             float(retired[5]), ()),
            ("proxy.dest.retired_hedge_wins", "counter",
             float(retired[6]), ()),
        ]
        for dest in pool:
            tags = [f"destination:{dest.address}"]
            rows.append(("forward.hedge.fired", "counter",
                         float(dest.hedge_fired_total), tags))
            rows.append(("forward.hedge.wins", "counter",
                         float(dest.hedge_wins_total), tags))
            rows.append(("proxy.dest.sent", "counter",
                         float(dest.sent_total), tags))
            rows.append(("proxy.dest.enqueued", "counter",
                         float(dest.enqueued_total), tags))
            rows.append(("proxy.dest.dropped", "counter",
                         float(dest.dropped_total), tags))
            rows.append(("proxy.dest.dropped_enqueued", "counter",
                         float(dest.dropped_enqueued_total), tags))
            rows.append(("proxy.dest.shed_open", "counter",
                         float(dest.shed_open_total), tags))
            rows.append(("proxy.dest.queue_depth", "gauge",
                         float(dest._queue.qsize()), tags))
            rows.append(("proxy.dest.forwarded_keys", "gauge",
                         dest.key_hll.estimate(), tags))
            rows.append(("resilience.breaker_state", "gauge",
                         float(dest.breaker.state_code), tags))
        return rows

    def clear(self) -> None:
        with self._lock:
            for address in list(self._pool):
                self._remove_locked(address)

    def flush_wait(self, timeout: float = 5.0) -> None:
        """Best-effort wait until queued metrics drain (for tests and
        graceful shutdown)."""
        import time
        deadline = time.monotonic() + timeout
        with self._lock:
            pool = list(self._pool.values())
        for dest in pool:
            while (not dest._queue.empty()
                   and time.monotonic() < deadline):
                time.sleep(0.01)
