"""Active ring-membership health checks for the proxy's destination pool.

Before this module the ring only reacted to a dead global instance
PASSIVELY: keys kept hashing at it until enough sends failed to open its
breaker (one full failure streak of real traffic, lost). The prober
turns that around — a dedicated loop probes every destination on a fixed
cadence, EJECTS a node from the hash ring after `unhealthy_after`
consecutive failures (traffic re-shards onto the survivors immediately,
~1/N of keys move), and READMITS it after `healthy_after` consecutive
passes (the original assignment is restored exactly, because ejection
never forgets the member's virtual points — they are recomputed from the
same address).

Probe kinds:

- ``tcp`` (default): a TCP connect to the destination's gRPC address —
  cheap, no HTTP surface needed on the import server, and exactly the
  reachability the sender cares about.
- ``http``: GET `url_template.format(host=..., port=...)` expecting 200
  — for deployments whose globals expose /healthcheck on a known port
  (template e.g. ``http://{host}:8127/healthcheck``), this is the
  richer readiness signal: a global that is listening but SHEDDING
  answers 503 and gets ejected before it blackholes merges.

Membership is re-resolved every probe round (`refresh` callback → the
proxy's discovery refresh): a DNS/SRV-backed discoverer re-resolves on
that cadence, so scale-ups surface at probe speed, not discovery speed.

The `health_probe` chaos seam (util/chaos.py) runs before every probe:
an injected fault fails the probe deterministically, which is how the
ejection/readmission machinery is tested without killing real sockets.
"""

from __future__ import annotations

import logging
import socket
import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional

from veneur_tpu.util import chaos as chaos_mod
from veneur_tpu.util.chaos import ChaosError

logger = logging.getLogger("veneur_tpu.proxy.health")


class _MemberHealth:
    __slots__ = ("failures", "passes", "ejected", "last_ok")

    def __init__(self):
        self.failures = 0
        self.passes = 0
        self.ejected = False
        self.last_ok = True


class RingHealth:
    """The probe loop. Owns per-member streak state; ejection/readmission
    act through the Destinations pool (which keeps the member OUT of the
    ring while ejected, even across discovery re-adds)."""

    def __init__(self, destinations, interval: float = 2.0,
                 timeout: float = 1.0, unhealthy_after: int = 3,
                 healthy_after: int = 2, probe: str = "tcp",
                 http_url_template: str = "",
                 refresh: Optional[Callable[[], None]] = None,
                 on_event: Optional[Callable[..., None]] = None):
        self.destinations = destinations
        self.interval = max(0.05, float(interval))
        self.timeout = max(0.05, float(timeout))
        self.unhealthy_after = max(1, int(unhealthy_after))
        self.healthy_after = max(1, int(healthy_after))
        if probe not in ("tcp", "http"):
            raise ValueError(f"unknown probe kind {probe!r}")
        if probe == "http" and not http_url_template:
            raise ValueError("http probe needs a url template")
        self.probe = probe
        self.http_url_template = http_url_template
        self._refresh = refresh
        self._on_event = on_event
        self._lock = threading.Lock()
        self._members: Dict[str, _MemberHealth] = {}
        self._shutdown = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.probes_total = 0
        self.probe_failures_total = 0
        self.ejections_total = 0
        self.readmissions_total = 0

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="ring-health", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._shutdown.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.timeout + self.interval)

    def _loop(self) -> None:
        while not self._shutdown.wait(self.interval):
            try:
                self.run_round()
            except Exception:
                logger.exception("health probe round failed")

    # -- one round -------------------------------------------------------

    def run_round(self) -> None:
        """Refresh membership, probe every pool member, apply streaks.
        Public so tests (and the soak driver) can step it
        deterministically without the timer thread."""
        if self._refresh is not None:
            try:
                self._refresh()
            except Exception:
                logger.exception("membership refresh failed; probing "
                                 "current pool")
        addresses = self.destinations.addresses()
        with self._lock:
            # forget members discovery dropped entirely
            for address in list(self._members):
                if address not in addresses:
                    del self._members[address]
        if self._shutdown.is_set():
            return
        # probe concurrently: serial probing would make detection (and
        # stop()) latency scale as dead_members x timeout — with 5 of 20
        # globals down at a 1s timeout, a "2s" round would really take
        # ~5s. A straggler past the join bound counts as a failed probe.
        results: Dict[str, bool] = {}
        workers = []
        for address in addresses:
            t = threading.Thread(
                target=lambda a=address: results.__setitem__(
                    a, self._probe(a)),
                name=f"ring-probe-{address}", daemon=True)
            t.start()
            workers.append(t)
        # ONE wall-clock deadline for the whole round: per-thread join
        # budgets would let k hung probes (e.g. an unbounded
        # getaddrinfo) stretch a round to k x timeout
        round_deadline = time.monotonic() + self.timeout + 0.25
        for t in workers:
            t.join(timeout=max(0.0, round_deadline - time.monotonic()))
        pool_ejected = set(self.destinations.ejected_addresses())
        for address in addresses:
            if self._shutdown.is_set():
                return
            self._apply(address, results.get(address, False),
                        pool_ejected=address in pool_ejected)

    def _probe(self, address: str) -> bool:
        # runs on per-round probe threads: counters go under the lock
        with self._lock:
            self.probes_total += 1
        try:
            chaos_mod.inject("health_probe")
            if self.probe == "tcp":
                host, _, port = address.rpartition(":")
                host = host.strip("[]") or "127.0.0.1"
                with socket.create_connection((host, int(port)),
                                              timeout=self.timeout):
                    return True
            host, _, port = address.rpartition(":")
            bare = host.strip("[]")
            # an IPv6 literal must be re-bracketed inside a URL
            url = self.http_url_template.format(
                host=f"[{bare}]" if ":" in bare else bare, port=port)
            with urllib.request.urlopen(url, timeout=self.timeout) as resp:
                return 200 <= resp.status < 300
        except Exception:
            with self._lock:
                self.probe_failures_total += 1
            return False

    def _apply(self, address: str, ok: bool,
               pool_ejected: bool = False) -> None:
        eject = readmit = False
        with self._lock:
            mh = self._members.get(address)
            if mh is None:
                # a first-seen member may already be pool-ejected (our
                # streak state was pruned during a discovery blip while
                # the pool's ejection survived): seed it ejected so
                # passing probes readmit it instead of leaving a
                # healthy node out of the ring forever
                mh = self._members[address] = _MemberHealth()
                mh.ejected = pool_ejected
            mh.last_ok = ok
            if ok:
                mh.failures = 0
                mh.passes += 1
                if mh.ejected and mh.passes >= self.healthy_after:
                    mh.ejected = False
                    readmit = True
            else:
                mh.passes = 0
                mh.failures += 1
                if not mh.ejected and mh.failures >= self.unhealthy_after:
                    mh.ejected = True
                    eject = True
        if eject:
            self.ejections_total += 1
            self.destinations.eject(address)
            logger.warning("ring: ejected %s after %d failed probes",
                           address, self.unhealthy_after)
            self._event("ring_ejection", destination=address,
                        consecutive_failures=self.unhealthy_after)
        elif mh.ejected:
            # re-assert a standing ejection every round (idempotent):
            # a discovery drop-and-re-add between rounds clears the
            # pool's ejection mark and puts the member back in the ring
            # — without this, a still-dead node could serve keys while
            # this table reports it ejected
            self.destinations.eject(address)
        elif readmit:
            self.readmissions_total += 1
            self.destinations.readmit(address)
            logger.info("ring: readmitted %s after %d passing probes",
                        address, self.healthy_after)
            self._event("ring_readmission", destination=address,
                        consecutive_passes=self.healthy_after)

    def _event(self, kind: str, **fields) -> None:
        if self._on_event is not None:
            try:
                self._on_event(kind, **fields)
            except Exception:
                pass

    # -- state -----------------------------------------------------------

    def member_table(self) -> List[dict]:
        """Per-member health snapshot (the /healthcheck/ready body and
        /debug surfaces)."""
        with self._lock:
            return [{"address": address,
                     "ejected": mh.ejected,
                     "last_probe_ok": mh.last_ok,
                     "consecutive_failures": mh.failures,
                     "consecutive_passes": mh.passes}
                    for address, mh in sorted(self._members.items())]

    def ejected_count(self) -> int:
        with self._lock:
            return sum(1 for mh in self._members.values() if mh.ejected)

    def telemetry_rows(self) -> List[tuple]:
        with self._lock:
            ejected = sum(1 for mh in self._members.values() if mh.ejected)
            tracked = len(self._members)
        return [
            ("proxy.ring.members", "gauge", float(tracked - ejected), ()),
            ("proxy.ring.ejected", "gauge", float(ejected), ()),
            ("proxy.ring.ejections", "counter",
             float(self.ejections_total), ()),
            ("proxy.ring.readmissions", "counter",
             float(self.readmissions_total), ()),
            ("proxy.ring.probes", "counter", float(self.probes_total), ()),
            ("proxy.ring.probe_failures", "counter",
             float(self.probe_failures_total), ()),
        ]
