"""veneur-proxy: consistent-hash gRPC router in front of the global tier.

Parity with reference proxy/proxy.go:33-120 and
proxy/handlers/handlers.go:40-164: a gRPC server accepting
Forward.SendMetrics (unary MetricList) and SendMetricsV2 (metric stream);
each metric is keyed by name + type + tags (minus configured ignored
tags), mapped through the consistent-hash ring to a destination, and
enqueued on that destination's buffered sender. A discovery loop
refreshes the destination pool every `discovery_interval`; the
healthcheck fails while the ring is empty (handlers.go:30-38).
"""

from __future__ import annotations

import logging
import threading
from concurrent import futures
from typing import Dict, List, Optional

import grpc

from veneur_tpu.forward.protos import forward_pb2, metric_pb2
from veneur_tpu.ops import hll_ref
from veneur_tpu.proxy.destinations import Destinations
from veneur_tpu.proxy.discovery import Discoverer, StaticDiscoverer
from veneur_tpu.proxy.ring import EmptyRingError
from veneur_tpu.util.grpcstats import RpcStats
from veneur_tpu.util.grpctls import GrpcTLS
from veneur_tpu.util.matcher import TagMatcher

logger = logging.getLogger("veneur_tpu.proxy")


class ProxyServer:
    def __init__(self, discoverer: Discoverer,
                 forward_service: str = "veneur-global",
                 listen_address: str = "127.0.0.1:0",
                 discovery_interval: float = 10.0,
                 ignore_tags: Optional[List[TagMatcher]] = None,
                 send_buffer: int = 4096, batch: int = 512,
                 max_workers: int = 8,
                 tls: Optional[GrpcTLS] = None,
                 tls_listen_address: str = "",
                 destination_tls: Optional[GrpcTLS] = None,
                 max_consecutive_failures: int = 3,
                 latency_observatory: bool = True,
                 health_check_interval: float = 2.0,
                 health_check_timeout: float = 1.0,
                 health_unhealthy_after: int = 3,
                 health_healthy_after: int = 2,
                 health_probe: str = "tcp",
                 health_http_url_template: str = "",
                 hedge_after: float = 0.0,
                 failover_walk: int = 2,
                 shard_groups: int = 0,
                 telemetry=None,
                 ledger_enabled: bool = True,
                 ledger_strict: bool = False,
                 trace_self_sample_rate: float = 1.0,
                 trace_store_traces: int = 128,
                 trace_store_spans: int = 256):
        self.discoverer = discoverer
        self.forward_service = forward_service
        self.discovery_interval = discovery_interval
        self.shutdown_grace = 1.0  # stop() grace; the CLI overrides it
        # from shutdown_timeout
        self._ignore = list(ignore_tags or [])
        # flight recorder: ejection/readmission (and any future proxy
        # events) land here; the CLI shares this instance with its
        # /metrics registry so the events surface at /debug/events
        if telemetry is None:
            from veneur_tpu.core.telemetry import Telemetry
            telemetry = Telemetry()
        self.telemetry = telemetry
        # latency observatory (core/latency.py): per-destination queue
        # dwell/depth — the proxy side of the queue.* telemetry; the
        # same latency_observatory knob the server honors turns it off
        from veneur_tpu.core.latency import LatencyObservatory
        self.latency = LatencyObservatory(enabled=latency_observatory)
        # cross-tier self-tracing (trace/store.py): the proxy follows
        # whatever interval traces its locals sampled — incoming trace
        # metadata is adopted, continued with proxy.route /
        # proxy.dest.send spans into the bounded store behind this
        # tier's /debug/traces, and re-injected on every destination
        # send (hedges included) so the global can keep the thread.
        # sample_rate here gates only the RECORDING of adopted traces
        # (an overload escape hatch); it never gates pass-through.
        from veneur_tpu.trace.store import SelfTracePlane
        self.trace_plane = SelfTracePlane(
            service="veneur-proxy",
            sample_rate=trace_self_sample_rate,
            max_traces=trace_store_traces,
            max_spans=trace_store_spans)
        # flow ledger (core/ledger.py), the proxy's side of the
        # conservation books: routing (received == routed + dropped +
        # no-destination), the destination pool (enqueued == sent +
        # dropped-after-enqueue + queued, retired folds included), and
        # the tier reconciliation against receivers' FlowCounts.
        # Intervals close on the discovery cadence (the proxy has no
        # flush loop).
        from veneur_tpu.core.ledger import FlowLedger
        self.ledger = FlowLedger(
            enabled=ledger_enabled, strict=ledger_strict,
            on_event=self.telemetry.record_event)
        self.ledger.declare(
            "proxy_route", inputs=("proxy.received",),
            outputs=("proxy.routed", "proxy.dropped",
                     "proxy.no_destination"))
        self.ledger.declare(
            "proxy_egress", inputs=("dest.enqueued",),
            outputs=("dest.sent", "dest.dropped_enqueued"),
            stocks=("dest_queues",))
        self.ledger.declare(
            "proxy_tier", inputs=("dest.acked_reported",),
            outputs=("dest.remote_merged", "dest.remote_rejected",
                     "dest.remote_deduped"))
        self.destinations = Destinations(
            send_buffer=send_buffer, batch=batch, tls=destination_tls,
            max_consecutive_failures=max_consecutive_failures,
            observatory=self.latency,
            hedge_after=hedge_after, failover_walk=failover_walk,
            ledger=self.ledger if self.ledger.enabled else None,
            trace_plane=self.trace_plane,
            # shard-aware ring (proxy/ring.py ShardGroupRing): keys
            # shard by digest range onto the shard group serving that
            # range; health ejection re-homes only within the group
            shard_groups=shard_groups)
        # probe the pool's monotonic flow totals (retired folds make
        # them churn-proof) and its live queue depth as a stock. ONE
        # flow_totals() snapshot per close, shared by all four readers:
        # close_interval evaluates probes in registration order and
        # stocks after them, so refreshing on the first (enqueued) read
        # keeps the identity's sides from tearing against each other
        dests = self.destinations
        snap_box = {"snap": None, "t": 0.0}

        def _flow(field: str, refresh: bool = False) -> float:
            import time as _time
            now = _time.monotonic()
            # 1s freshness bound: a /metrics scrape between closes
            # still reads near-live stock levels, while the close's
            # back-to-back reads stay on one consistent snapshot
            if (refresh or snap_box["snap"] is None
                    or now - snap_box["t"] > 1.0):
                snap_box["snap"] = dests.flow_totals()
                snap_box["t"] = now
            return snap_box["snap"][field]

        self.ledger.probe("dest.enqueued",
                          lambda: _flow("enqueued", refresh=True))
        self.ledger.probe("dest.sent", lambda: _flow("sent"))
        self.ledger.probe("dest.dropped_enqueued",
                          lambda: _flow("dropped_enqueued"))
        self.ledger.stock("dest_queues", lambda: _flow("queued"))
        # active ring health: probes every pool member each round,
        # ejecting/readmitting through the destination pool; membership
        # (DNS/SRV et al) re-resolves on the same cadence via the
        # discovery refresh hook. 0 disables the loop (tests drive
        # run_round() by hand).
        self.ring_health = None
        if health_check_interval > 0:
            from veneur_tpu.proxy.health import RingHealth
            self.ring_health = RingHealth(
                self.destinations,
                interval=health_check_interval,
                timeout=health_check_timeout,
                unhealthy_after=health_unhealthy_after,
                healthy_after=health_healthy_after,
                probe=health_probe,
                http_url_template=health_http_url_template,
                refresh=self._refresh_destinations,
                on_event=self.telemetry.record_event)
        # per-RPC latency/error aggregates (reference proxy/grpcstats)
        self.rpc_stats = RpcStats()
        self.stats: Dict[str, int] = {
            "received_total": 0, "routed_total": 0,
            "no_destination_total": 0, "dropped_total": 0,
            "duplicates_dropped_total": 0,
        }
        # idempotency-token dedupe at the PROXY boundary: a local's
        # retry whose first attempt already routed here would otherwise
        # be re-routed with fresh per-destination tokens the global
        # tier can't catch — the exactly-once-per-node property must
        # hold at whichever tier terminates the sender's RPC
        from veneur_tpu.forward.wire import TokenDeduper
        self._deduper = TokenDeduper()
        # identity-key bytes -> (ring POINT, 64-bit key hash): forward
        # streams repeat the same keys every interval, so ring-key
        # derivation (tag filtering, type naming, joining), its ring
        # hash, AND the HLL key hash (per-destination forwarded-key
        # cardinality) are paid once per key lifetime. Points are
        # membership-independent, so the cache survives discovery churn.
        self._route_cache: Dict[bytes, tuple] = {}
        # the upb/V2 path's equivalent, keyed by the derived ring-key
        # string (kept separate: identity-key bytes and derived strings
        # are different namespaces)
        self._v2_route_cache: Dict[str, tuple] = {}
        # handle_metric runs on up to max_workers gRPC threads; python
        # dict += is not atomic, so counter accuracy needs a lock
        self._stats_lock = threading.Lock()
        # routing counters feed the proxy_route identity as probes
        # (per-interval deltas of the already-exact stats table)
        for stage, key in (("proxy.received", "received_total"),
                           ("proxy.routed", "routed_total"),
                           ("proxy.dropped", "dropped_total"),
                           ("proxy.no_destination", "no_destination_total"),
                           ("proxy.deduped", "duplicates_dropped_total")):
            self.ledger.probe(stage, lambda k=key: self._read_stat(k))
        self._shutdown = threading.Event()
        self._discovery_thread: Optional[threading.Thread] = None

        # the forward client's V1 bulk body scales with key count
        # (~36 MB at 50k digest keys); the 4 MB gRPC default would
        # bounce it at exactly the scale the bulk path exists for
        self._grpc = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            # metadata cap raised past the 8 KiB default for the trace
            # + exemplar sidecars (see forward/server.py)
            options=[("grpc.max_receive_message_length", 256 << 20),
                     ("grpc.max_metadata_size", 64 << 10)])
        # responses carry FlowCounts (received/routed/duplicate) for
        # the sender's flow-ledger tier reconciliation (forward/wire.py)
        serialize_resp = (lambda b: b if isinstance(b, (bytes, bytearray))
                          else b"")
        handler = grpc.method_handlers_generic_handler("forwardrpc.Forward", {
            "SendMetricsV2": grpc.stream_unary_rpc_method_handler(
                self.rpc_stats.timed("SendMetricsV2", self._send_metrics_v2),
                request_deserializer=metric_pb2.Metric.FromString,
                response_serializer=serialize_resp),
            "SendMetrics": grpc.unary_unary_rpc_method_handler(
                self.rpc_stats.timed("SendMetrics", self._send_metrics_v1),
                # raw bytes: the native route parser re-scatters the
                # body without deserializing; upb is the fallback
                request_deserializer=lambda b: b,
                response_serializer=serialize_resp),
        })
        self._grpc.add_generic_rpc_handlers((handler,))
        # listener layout mirrors the reference v2 proxy (proxy/proxy.go
        # grpc_address + grpc_tls_address): with a dedicated
        # tls_listen_address the server binds BOTH a plaintext port on
        # listen_address and a TLS port there; with tls but no dedicated
        # address, TLS terminates on the single listener (legacy shape);
        # authority => mutual auth either way
        self.tls_port = 0
        if tls_listen_address and not tls:
            # half-configured TLS must fail loudly, never fall back to
            # plaintext (same stance as util/grpctls.py)
            raise ValueError(
                "grpc_tls_address requires tls_certificate/tls_key")
        if tls and tls_listen_address:
            self.tls_port = self._grpc.add_secure_port(
                tls_listen_address, tls.server_credentials())
            if self.tls_port == 0:
                raise RuntimeError(
                    f"could not bind proxy TLS to {tls_listen_address}")
            self.port = self._grpc.add_insecure_port(listen_address)
        elif tls:
            self.port = self._grpc.add_secure_port(
                listen_address, tls.server_credentials())
        else:
            self.port = self._grpc.add_insecure_port(listen_address)
        if self.port == 0:
            raise RuntimeError(f"could not bind proxy to {listen_address}")
        self._listen_host = listen_address.rpartition(":")[0]

    @property
    def address(self) -> str:
        # report the bound host; loopback only for wildcard/empty binds
        # (those aren't dialable as-is)
        host = self._listen_host
        if host in ("", "0.0.0.0", "[::]", "::"):
            host = "127.0.0.1"
        return f"{host}:{self.port}"

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        self._refresh_destinations()
        self._grpc.start()
        self._discovery_thread = threading.Thread(
            target=self._discovery_loop, name="proxy-discovery", daemon=True)
        self._discovery_thread.start()
        if self.ring_health is not None:
            self.ring_health.start()
        logger.info("proxy listening on %s (%d destinations)",
                    self.address, self.destinations.size())

    def stop(self, grace: float = 1.0) -> None:
        self._shutdown.set()
        if self.ring_health is not None:
            self.ring_health.stop()
        self._grpc.stop(grace)
        self.destinations.flush_wait(timeout=grace)
        self.destinations.clear()

    def healthy(self) -> bool:
        """False while no destinations are connected (handlers.go:30-38)."""
        return self.destinations.size() > 0

    def ready_state(self):
        """(ready, body) for the proxy's /healthcheck/ready: 503 while
        the ring is empty OR more than half its members are ejected —
        the mirror of the server's shedding semantics (an instance that
        would blackhole most of the keyspace should stop receiving
        traffic). The body always carries the member table so the
        operator sees WHICH globals are sick from the probe itself."""
        members = (self.ring_health.member_table()
                   if self.ring_health is not None else [])
        if not members:
            # no probe round has run yet (or probing is disabled): fall
            # back to pool membership so a healthy just-started proxy
            # doesn't answer 503 for a whole probe interval
            members = [{"address": a, "ejected": False}
                       for a in self.destinations.addresses()]
        total = len(members)
        ejected = sum(1 for m in members if m.get("ejected"))
        body = {"destinations": total, "ejected": ejected,
                "members": members}
        groups = self.destinations.group_table()
        if groups:
            body["shard_groups"] = groups
        if total == 0:
            body["reason"] = "no destinations connected"
            return False, body
        if ejected * 2 > total:
            body["reason"] = (f"{ejected}/{total} ring members ejected "
                              "(>50%)")
            return False, body
        # a shard group with no live members has lost its whole key
        # range to clockwise spill: those keys merge on instances that
        # don't serve their device-shard range — degraded enough that
        # orchestrators should stop routing here
        dead = [g["group"] for g in groups if not g["live"]]
        if dead:
            body["reason"] = (f"shard group(s) {dead} have no live "
                              "members (key ranges spilling)")
            return False, body
        return True, body

    def telemetry_rows(self) -> List[tuple]:
        """Scrape-time rows for /metrics: routing counters plus the
        per-destination pool/breaker rows (proxy.dest.*,
        resilience.breaker_state)."""
        with self._stats_lock:
            rows = [(f"proxy.{key}", "counter", float(value), ())
                    for key, value in self.stats.items()]
        rows.append(("proxy.destinations", "gauge",
                     float(self.destinations.size()), ()))
        rows.extend(self.destinations.telemetry_rows())
        if self.ring_health is not None:
            rows.extend(self.ring_health.telemetry_rows())
        rows.extend(self.latency.telemetry_rows())
        rows.extend(self.ledger.telemetry_rows())
        rows.extend(self.trace_plane.telemetry_rows())
        return rows

    def cardinality_report(self, top: int = 20, name: str = "") -> dict:
        """/debug/cardinality on the proxy tier: per-destination
        forwarded-key HLL estimates (cumulative per destination
        lifetime), so an operator can see which global instance a key
        explosion hashes onto. `name` filters to one destination
        address; `top` bounds the list (largest key count first)."""
        import time
        with self.destinations._lock:
            pool = list(self.destinations._pool.values())
        dests = []
        for dest in pool:
            if name and dest.address != name:
                continue
            dests.append({
                "address": dest.address,
                "forwarded_keys_estimate": int(dest.key_hll.estimate()),
                "sent_total": dest.sent_total,
                "dropped_total": dest.dropped_total,
                "queue_depth": dest._queue.qsize(),
                "breaker_state": dest.breaker.state,
            })
        dests.sort(key=lambda d: d["forwarded_keys_estimate"],
                   reverse=True)
        with self._stats_lock:
            stats = dict(self.stats)
        return {
            "generated_unix": round(time.time(), 3),
            "routing": stats,
            "route_cache_size": (len(self._route_cache)
                                 + len(self._v2_route_cache)),
            "destinations": dests[:max(0, top)],
        }

    def _read_stat(self, key: str) -> float:
        with self._stats_lock:
            return float(self.stats.get(key, 0))

    # -- discovery -------------------------------------------------------

    def _discovery_loop(self) -> None:
        while not self._shutdown.wait(self.discovery_interval):
            self._refresh_destinations()
            # ledger intervals ride the discovery cadence — the proxy
            # has no flush loop, and ~10s matches the server's interval
            from veneur_tpu.core.ledger import LedgerImbalance
            try:
                self.ledger.close_interval()
            except LedgerImbalance:
                # strict mode on a live proxy: the imbalance is loud
                # (ERROR + traceback + the ledger_imbalance event the
                # close already recorded) but must not kill the
                # discovery/health-refresh thread it shares
                logger.exception("proxy flow-ledger conservation breach "
                                 "(ledger_strict)")
            except Exception:
                logger.exception("proxy ledger close failed")

    def _refresh_destinations(self) -> None:
        try:
            addresses = self.discoverer.get_destinations_for_service(
                self.forward_service)
        except Exception:
            logger.exception("discovery failed for %s; keeping current pool",
                             self.forward_service)
            return
        if not addresses:
            # an empty result is treated as a discovery outage: keep
            # forwarding to the known pool rather than dropping everything
            logger.warning("discovery returned no destinations for %s",
                           self.forward_service)
            return
        self.destinations.set_destinations(addresses)

    ROUTE_CACHE_MAX = 1_000_000

    # -- cross-tier self-tracing -----------------------------------------

    def _trace_begin(self, ctx):
        """Continue a local's interval trace through the routing tier:
        adopt the incoming id (sample-gated for RECORDING only), open
        the proxy.route span, and hand the lineage + exemplar sidecar
        to the destination pool so the next batch each sender ships
        re-injects them toward the global. An untraced RPC clears the
        pool's pending lineage so stale ids never ride later batches.
        Runs only after token dedupe passed — a retry whose first
        attempt landed here never opens a second proxy.route span."""
        from veneur_tpu.forward.wire import extract_trace, metadata_value
        from veneur_tpu.trace.store import EXEMPLAR_KEY
        trace_id, span_id = extract_trace(ctx)
        if not trace_id:
            self.destinations.note_trace(0, 0, None)
            return None
        blob = metadata_value(ctx, EXEMPLAR_KEY)
        span = (self.trace_plane.span("proxy.route", trace_id,
                                      parent_id=span_id)
                if self.trace_plane.follow(trace_id) else None)
        # downstream parent: the route span when recorded here, else
        # the sender's span (pass-through keeps the chain connected
        # even when this tier declines to record)
        self.destinations.note_trace(
            trace_id, span.id if span is not None else span_id, blob)
        return span

    @staticmethod
    def _trace_end(span, received: int, routed: int, ok: bool) -> None:
        if span is None:
            return
        span.set_tag("received", received)
        span.set_tag("routed", routed)
        if not ok:
            span.error()
        span.finish()

    # -- handlers --------------------------------------------------------

    def _send_metrics_v1(self, body, ctx):
        from veneur_tpu.forward.wire import encode_flow_counts
        token, disposition = self._deduper.begin(ctx)
        if disposition == "done":
            with self._stats_lock:
                self.stats["duplicates_dropped_total"] += 1
            return encode_flow_counts(0, 0, duplicate=True)
        if disposition == "inflight":
            ctx.abort(grpc.StatusCode.UNAVAILABLE,
                      "duplicate send racing its first attempt")
        ok = False
        tspan = None
        received = routed = 0
        try:
            # inside the try: a _trace_begin failure past _deduper.begin
            # must still reach _deduper.end, or the token wedges
            # in-flight and every retry is refused
            tspan = self._trace_begin(ctx)
            # satellite of the WAL/backfill plane: a replayed interval's
            # x-veneur-interval stamp must survive the routing hop, or
            # the global folds hours-stale history into its live flush
            from veneur_tpu.forward.wire import extract_interval
            interval = extract_interval(ctx)
            res = self._route_native(body, interval=interval)
            if res is None:
                metric_list = forward_pb2.MetricList.FromString(body)
                for pbm in metric_list.metrics:
                    received += 1
                    if self.handle_metric(pbm, interval=interval):
                        routed += 1
            else:
                received, routed = res
            ok = True
        finally:
            self._deduper.end(token, ok)
            self._trace_end(tspan, received, routed, ok)
        # FlowCounts back to the local: received metrics this handler
        # parsed, "merged" = routed onto a destination queue (drops and
        # no-destination are this proxy's accounted loss)
        return encode_flow_counts(received, routed)

    def _route_native(self, body, interval: float = 0.0
                      ) -> Optional[tuple]:
        """Re-scatter a V1 body without deserializing: the native walk
        (vnt_route_parse) yields each metric's identity key + raw bytes;
        the ring key derives from the identity key once per key lifetime
        (the route cache) and destinations forward the raw bytes — both
        V1 framing and the V2 stream serializer pass bytes through.
        Returns (received, routed) for the FlowCounts response, or None
        when the native walker is unavailable."""
        from veneur_tpu import native

        parsed = native.route_parse(body)
        if parsed is None:
            return None
        keys, raws = parsed
        cache = self._route_cache
        fast = routed = dropped = no_dest = 0
        slow = slow_routed = 0
        try:
            for key, raw in zip(keys, raws):
                if not key:
                    # wide open enum: the upb path decides (and raises
                    # the same way the stream path would); it also does
                    # its own received/routed accounting
                    slow += 1
                    if self.handle_metric(
                            metric_pb2.Metric.FromString(raw)):
                        slow_routed += 1
                    continue
                fast += 1
                cached = cache.get(key)
                if cached is None:
                    # strict decode: invalid utf-8 raises here, and the
                    # upb re-parse below surfaces the same rejection the
                    # old whole-body deserializer gave — the poisoned
                    # metric never reaches a destination batch
                    try:
                        mtype, _scope, name, tags = \
                            native.decode_import_key(key)
                        type_name = metric_pb2.Type.Name(mtype).lower()
                    except (ValueError, IndexError):
                        fast -= 1  # slow path does its own accounting
                        slow += 1
                        if self.handle_metric(
                                metric_pb2.Metric.FromString(raw)):
                            slow_routed += 1
                        continue
                    tags = [t for t in tags
                            if not any(mm.match(t) for mm in self._ignore)]
                    ring_key = "%s%s%s" % (name, type_name, ",".join(tags))
                    point = self.destinations.ring.point_of(ring_key)
                    if len(cache) >= self.ROUTE_CACHE_MAX:
                        cache.clear()
                    # HLL key hash over the DERIVED ring key — the same
                    # basis handle_metric hashes, so forwarded-key
                    # estimates agree across ingest paths — paid once
                    # per key lifetime
                    cached = cache[key] = (
                        point, hll_ref.hash_member(ring_key.encode()))
                point, key_hash = cached
                try:
                    dest = self.destinations.get_at(point)
                except EmptyRingError:
                    no_dest += 1
                    continue
                dest.note_key(key_hash)
                if dest.send(raw, interval=interval):
                    routed += 1
                else:
                    dropped += 1
        finally:
            # flushed even when a slow-path metric raises mid-batch so
            # already-forwarded metrics stay counted
            with self._stats_lock:
                self.stats["received_total"] += fast
                self.stats["routed_total"] += routed
                self.stats["dropped_total"] += dropped
                self.stats["no_destination_total"] += no_dest
        return fast + slow, routed + slow_routed

    def _send_metrics_v2(self, request_iterator, ctx):
        from veneur_tpu.forward.wire import encode_flow_counts
        token, disposition = self._deduper.begin(ctx)
        if disposition == "done":
            with self._stats_lock:
                self.stats["duplicates_dropped_total"] += 1
            for _ in request_iterator:  # complete the sender's stream
                pass
            return encode_flow_counts(0, 0, duplicate=True)
        if disposition == "inflight":
            ctx.abort(grpc.StatusCode.UNAVAILABLE,
                      "duplicate send racing its first attempt")
        ok = False
        tspan = None
        received = routed = 0
        try:
            tspan = self._trace_begin(ctx)  # see _send_metrics_v1
            from veneur_tpu.forward.wire import extract_interval
            interval = extract_interval(ctx)  # see _send_metrics_v1
            for pbm in request_iterator:
                received += 1
                if self.handle_metric(pbm, interval=interval):
                    routed += 1
            ok = True
        finally:
            self._deduper.end(token, ok)
            self._trace_end(tspan, received, routed, ok)
        return encode_flow_counts(received, routed)

    def handle_metric(self, pbm: metric_pb2.Metric,
                      interval: float = 0.0) -> bool:
        """Route one metric (handlers.go:100-164): hash key is
        name + lowercase type + joined tags minus ignored tags.
        Returns True when the metric landed on a destination queue
        (the FlowCounts "merged" figure for this tier). `interval`
        carries the sender's x-veneur-interval stamp through to the
        destination batch (WAL replay timestamp fidelity)."""
        with self._stats_lock:
            self.stats["received_total"] += 1
        tags = [t for t in pbm.tags
                if not any(matcher.match(t) for matcher in self._ignore)]
        key = "%s%s%s" % (pbm.name,
                          metric_pb2.Type.Name(pbm.type).lower(),
                          ",".join(tags))
        # same once-per-key-lifetime amortization as the native path:
        # the ring hash and the HLL key hash are both pure-Python and
        # both repeat every interval for a steady key stream
        cached = self._v2_route_cache.get(key)
        if cached is None:
            if len(self._v2_route_cache) >= self.ROUTE_CACHE_MAX:
                self._v2_route_cache.clear()
            cached = self._v2_route_cache[key] = (
                self.destinations.ring.point_of(key),
                hll_ref.hash_member(key.encode()))
        point, key_hash = cached
        try:
            dest = self.destinations.get_at(point)
        except EmptyRingError:
            with self._stats_lock:
                self.stats["no_destination_total"] += 1
            return False
        dest.note_key(key_hash)
        routed = dest.send(pbm, interval=interval)
        with self._stats_lock:
            self.stats["routed_total" if routed else "dropped_total"] += 1
        return routed


def create_static_proxy(destination_addresses: List[str],
                        **kwargs) -> ProxyServer:
    return ProxyServer(StaticDiscoverer(destination_addresses), **kwargs)
