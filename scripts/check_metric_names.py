#!/usr/bin/env python
"""Static self-metric inventory check.

Walks every ``statsd.count/gauge/timing`` call site in ``veneur_tpu/``
(AST, not regex, so formatting never fools it) and fails if an emitted
metric name is missing from the README's self-metric inventory table —
the docs and the code can't silently drift apart.

Literal names must appear verbatim in the table. Names built from
f-strings (e.g. ``f"{prefix}.count"`` in util/grpcstats.py) are matched
as patterns: each formatted field becomes a wildcard, and at least one
documented name must match.

Registry-collector rows are covered too: ANY literal 4-tuple whose
second element is ``"counter"`` or ``"gauge"`` — the
``(name, kind, value, tags)`` shape every telemetry collector emits
(resilience breaker gauges, forward client counters, proxy destination
rows, the columnstore/cardinality capacity rows) — is checked exactly
like a statsd call site, wherever it appears: ``rows.append(...)``,
``rows.extend([...])``, list-literal returns, and comprehensions all
count. F-string names become wildcard patterns, like statsd sites.

Latency-observatory llhist series are covered as well: any module-level
``HIST_ROWS = ("name", ...)`` tuple (core/latency.py declares its
histogram inventory that way) expands each base name to the
``.p50``/``.p99``/``.max``/``.count`` rows the observatory renders into
/metrics, and every expanded name must be documented.

Flow-ledger rows ride the same contract: core/ledger.py declares its
dynamically-rendered series (imbalance gauges, stage totals) in a
module-level ``LEDGER_ROWS = ("name", ...)`` tuple; each name is linted
verbatim against the inventory.

Usage: python scripts/check_metric_names.py [--repo DIR]
Exit codes: 0 ok, 1 undocumented metrics found, 2 could not parse docs.
"""

from __future__ import annotations

import argparse
import ast
import pathlib
import re
import sys

EMIT_METHODS = {"count", "gauge", "timing", "observe"}
# receiver spellings that denote a ScopedClient self-metrics client or
# the pull-side registry itself (resilience/chaos rows write there
# directly, bypassing statsd)
STATSD_RECEIVERS = {"statsd", "stats", "stats_client", "_statsd",
                    "registry"}

DOC_SECTION = "Self-metric inventory"

# suffixes every observatory llhist series (a HIST_ROWS entry) renders
# into /metrics — see core/latency.py LatencyHist / telemetry_rows
HIST_ROW_SUFFIXES = (".p50", ".p99", ".max", ".count")


def statsd_receiver(node: ast.AST) -> bool:
    """True when `node` is how the codebase spells its statsd client:
    a bare name like `statsd`/`stats`, or `self.statsd` / `api.statsd`."""
    if isinstance(node, ast.Name):
        return node.id in STATSD_RECEIVERS
    if isinstance(node, ast.Attribute):
        return node.attr in STATSD_RECEIVERS
    return False


def emitted_names(root: pathlib.Path):
    """Yield (path, lineno, name, is_pattern) per statsd emission."""
    for path in sorted(root.rglob("*.py")):
        if "protos" in path.parts:
            continue
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError as e:
            print(f"warning: could not parse {path}: {e}", file=sys.stderr)
            continue
        for node in ast.walk(tree):
            # declared inventories: HIST_ROWS = ("base", ...) expands
            # to the .p50/.p99/.max/.count rows the observatory
            # renders; LEDGER_ROWS = ("name", ...) names the flow
            # ledger's dynamically-rendered rows verbatim
            if isinstance(node, ast.Assign):
                names = {t.id for t in node.targets
                         if isinstance(t, ast.Name)}
                if names & {"HIST_ROWS", "LEDGER_ROWS"} \
                        and isinstance(node.value, (ast.Tuple, ast.List)):
                    suffixes = (HIST_ROW_SUFFIXES if "HIST_ROWS" in names
                                else ("",))
                    for el in node.value.elts:
                        if isinstance(el, ast.Constant) \
                                and isinstance(el.value, str):
                            for suffix in suffixes:
                                yield (path, node.lineno,
                                       el.value + suffix, False)
                continue
            # collector-row shape, wherever the tuple literal appears
            # (append/extend args, list literals, comprehensions):
            # ("name", "counter"|"gauge", value, tags)
            if isinstance(node, ast.Tuple) and len(node.elts) == 4:
                name_el, kind_el = node.elts[:2]
                if (isinstance(kind_el, ast.Constant)
                        and kind_el.value in ("counter", "gauge")):
                    resolved = _name_or_pattern(name_el)
                    if resolved is not None:
                        yield (path, node.lineno) + resolved
                continue
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            if not (node.func.attr in EMIT_METHODS
                    and statsd_receiver(node.func.value)
                    and node.args):
                continue
            resolved = _name_or_pattern(node.args[0])
            if resolved is not None:
                yield (path, node.lineno) + resolved
            # a bare variable name can't be resolved statically; the
            # call site it was built at is already covered above


def _name_or_pattern(arg: ast.AST):
    """(name, is_pattern) for a literal string or f-string metric-name
    node; None when the name can't be resolved statically."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value, False
    if isinstance(arg, ast.JoinedStr):
        parts = []
        for piece in arg.values:
            if isinstance(piece, ast.Constant):
                parts.append(re.escape(str(piece.value)))
            else:
                parts.append(r"[^|]+")
        return "".join(parts), True
    return None


def documented_names(readme: pathlib.Path):
    """Backticked names from the README's self-metric inventory table."""
    text = readme.read_text()
    match = re.search(rf"^##+ .*{DOC_SECTION}.*?$(.*?)(?=^## |\Z)", text,
                      re.MULTILINE | re.DOTALL)
    if match is None:
        return None
    return set(re.findall(r"`([a-zA-Z0-9_.*]+)`", match.group(1)))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--repo", default=None,
                        help="repo root (default: this script's parent)")
    args = parser.parse_args(argv)
    repo = pathlib.Path(args.repo or pathlib.Path(__file__).parent.parent)

    docs = documented_names(repo / "README.md")
    if docs is None:
        print(f"error: README.md has no '{DOC_SECTION}' section",
              file=sys.stderr)
        return 2

    missing = []
    checked = 0
    for path, lineno, name, is_pattern in emitted_names(repo / "veneur_tpu"):
        checked += 1
        if is_pattern:
            pat = re.compile(f"^{name}$")
            if not any(pat.match(doc) for doc in docs):
                missing.append((path, lineno, f"<pattern> {name}"))
        elif name not in docs:
            missing.append((path, lineno, name))

    if missing:
        print(f"{len(missing)} emitted self-metric(s) missing from the "
              f"README '{DOC_SECTION}' table:")
        for path, lineno, name in missing:
            print(f"  {path.relative_to(repo)}:{lineno}  {name}")
        return 1
    print(f"ok: {checked} statsd call sites, all documented "
          f"({len(docs)} names in the table)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
