#!/usr/bin/env python
"""Alert drill: drive one rule through pending -> firing -> resolved
against a live server and verify the whole observable trail.

The drill sends breaching samples at the server's statsd port until
`GET /alerts` shows the rule firing (through its `for:` hold-down),
then stops and waits for the breach to clear (the next flush resets
the live generation, so a quiet metric un-breaches by itself). It then
asserts the trail every operator surface should carry:

  * `/alerts` walked the states in order (pending seen, firing seen,
    then idle again with `transitions` incremented);
  * `/debug/events?kind=alert_transition` recorded each transition,
    every event stamped with an interval trace id;
  * `/metrics` exports the `alert.firing{rule:...}` page feed.

Self-contained by default — it boots an in-process server on loopback
with a drill rule and tears it down after:

    python scripts/alert_drill.py

Or aim it at a running server whose config already carries the rule
(the drill only sends samples and reads HTTP, so it is safe against a
dev instance):

    python scripts/alert_drill.py \
        --http 127.0.0.1:8127 --statsd udp://127.0.0.1:8126 \
        --rule drill-p99 --metric drill.latency --breach 250 --wire ms

Exit codes: 0 drill passed, 1 a stage or assertion failed.
"""

from __future__ import annotations

import argparse
import json
import socket
import sys
import time
import urllib.request


def fetch(http: str, path: str):
    with urllib.request.urlopen(f"http://{http}{path}", timeout=10) as r:
        return json.loads(r.read())


def fetch_text(http: str, path: str) -> str:
    with urllib.request.urlopen(f"http://{http}{path}", timeout=10) as r:
        return r.read().decode()


def rule_row(http: str, rule_id: str):
    report = fetch(http, "/alerts")
    for row in report.get("rules", ()):
        if row["id"] == rule_id:
            return row
    return None


def wait_state(http: str, rule_id: str, states, timeout_s: float,
               seen: set, breach=None) -> str:
    """Poll /alerts until the rule reaches one of `states` (recording
    every state observed on the way in `seen`); optionally keep the
    breach generator running between polls."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if breach is not None:
            breach()
        row = rule_row(http, rule_id)
        if row is None:
            raise AssertionError(f"rule {rule_id!r} not in /alerts")
        seen.add(row["state"])
        if row["state"] in states:
            return row["state"]
        time.sleep(0.1)
    raise AssertionError(
        f"rule {rule_id!r} never reached {states} in {timeout_s:.0f}s "
        f"(saw {sorted(seen)})")


def run_drill(http: str, statsd: tuple, rule_id: str, metric: str,
              breach_value: float, wire: str, hold_margin_s: float,
              resolve_timeout_s: float) -> int:
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    payload = ("%s:%g|%s" % (metric, breach_value, wire)).encode()

    def breach(n: int = 20):
        for _ in range(n):
            sock.sendto(payload, statsd)

    row = rule_row(http, rule_id)
    if row is None:
        print(f"FAIL: rule {rule_id!r} not present in /alerts")
        return 1
    transitions_before = row.get("transitions", 0)
    print(f"drill: rule {rule_id!r} starts {row['state']} "
          f"(op {row['op']} {row['threshold']}, for {row['for_s']}s)")

    seen: set = set()
    # phase 1: breach until the state machine walks to firing. A rule
    # with for: 0 jumps straight there; otherwise pending shows first.
    state = wait_state(http, rule_id, ("firing",),
                       row["for_s"] + hold_margin_s, seen, breach=breach)
    print(f"drill: reached {state} (path: {sorted(seen)})")
    if row["for_s"] > 0 and "pending" not in seen:
        print("FAIL: hold-down rule fired without a pending phase")
        return 1

    # phase 2: stop breaching; the next flush resets the live
    # generation, the metric stops resolving, and the rule un-fires
    state = wait_state(http, rule_id, ("idle",), resolve_timeout_s, seen)
    print(f"drill: resolved back to {state}")

    # trail assertion 1: /alerts transition counter moved
    row = rule_row(http, rule_id)
    if row.get("transitions", 0) < transitions_before + 2:
        print(f"FAIL: transitions counter {row.get('transitions')} "
              f"did not advance past {transitions_before}")
        return 1

    # trail assertion 2: the flight recorder holds the transition
    # events for this rule, each stamped with an interval trace id
    events = fetch(http, "/debug/events?kind=alert_transition&n=512")
    mine = [e for e in events.get("events", ())
            if e.get("rule") == rule_id]
    to_states = [e.get("to_state") for e in mine]
    missing = [s for s in ("firing", "resolved") if s not in to_states]
    if missing:
        print(f"FAIL: /debug/events missing transitions {missing} "
              f"(saw {to_states})")
        return 1
    unstamped = [e for e in mine if not e.get("trace_id")]
    if unstamped:
        print(f"FAIL: {len(unstamped)} transition event(s) missing an "
              f"interval trace id")
        return 1

    # trail assertion 3: the page feed exported through /metrics
    metrics_text = fetch_text(http, "/metrics")
    if "veneur_alert_firing" not in metrics_text:
        print("FAIL: /metrics has no alert.firing gauge")
        return 1

    print(f"PASS: {rule_id!r} walked pending -> firing -> resolved; "
          f"{len(mine)} transition events recorded, all trace-stamped")
    return 0


def self_contained(args) -> int:
    """Boot a loopback server with a drill rule, run the drill, tear
    down. The rule breaches on the drill timer's p99 with a short
    hold-down so the pending phase is observable."""
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:  # runnable straight from a checkout
        sys.path.insert(0, repo)
    from veneur_tpu.config import Config
    from veneur_tpu.core.server import Server

    cfg = Config()
    cfg.interval = args.interval
    cfg.hostname = "alert-drill"
    cfg.statsd_listen_addresses = ["udp://127.0.0.1:0"]
    cfg.http_address = "127.0.0.1:0"
    cfg.flush_on_shutdown = False
    cfg.alerts.interval = 0.2
    cfg.alerts.rules = [{
        "id": args.rule, "metric": args.metric, "kind": "quantile",
        "q": 0.99, "op": ">", "threshold": 100.0, "for": 0.6,
    }]
    cfg.apply_defaults()
    server = Server(cfg)
    server.start()
    try:
        http = "%s:%d" % server.http_api.address
        statsd = server.local_addr("udp")
        print(f"drill: self-contained server on http={http} "
              f"statsd={statsd[0]}:{statsd[1]}")
        return run_drill(http, statsd, args.rule, args.metric,
                         args.breach, args.wire,
                         hold_margin_s=args.interval + 10.0,
                         resolve_timeout_s=args.interval * 2 + 10.0)
    finally:
        server.shutdown()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="alert_drill", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--http", default="",
                    help="operator API host:port of a running server "
                         "(omit for a self-contained drill)")
    ap.add_argument("--statsd", default="udp://127.0.0.1:8126",
                    help="statsd ingest address of that server")
    ap.add_argument("--rule", default="drill-p99",
                    help="rule id to drive (must exist in the server's "
                         "alerts: block)")
    ap.add_argument("--metric", default="drill.latency",
                    help="metric the rule watches")
    ap.add_argument("--breach", type=float, default=250.0,
                    help="sample value that breaches the threshold")
    ap.add_argument("--wire", default="ms", choices=["ms", "h", "g", "c"],
                    help="wire type of the breach samples")
    ap.add_argument("--hold-margin", type=float, default=30.0,
                    dest="hold_margin",
                    help="extra seconds past for: to wait for firing")
    ap.add_argument("--resolve-timeout", type=float, default=60.0,
                    dest="resolve_timeout",
                    help="seconds to wait for the resolve after quiet")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="flush interval of the self-contained server")
    args = ap.parse_args(argv)

    if not args.http:
        return self_contained(args)
    host, _, port = args.statsd.rpartition("://")[-1].rpartition(":")
    return run_drill(args.http, (host or "127.0.0.1", int(port)),
                     args.rule, args.metric, args.breach, args.wire,
                     hold_margin_s=args.hold_margin,
                     resolve_timeout_s=args.resolve_timeout)


if __name__ == "__main__":
    sys.exit(main())
