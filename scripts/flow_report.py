#!/usr/bin/env python
"""Pretty-print a flow-ledger conservation report.

Reads ``GET /debug/ledger`` from a live veneur-tpu server or proxy —
or a saved JSON file — and renders the conservation books as text: one
identity table (inputs / outputs / stocks / net unexplained imbalance),
the lifetime stage totals, and a per-interval waterfall of the last N
closed intervals with their imbalances flagged.

Usage:
    python scripts/flow_report.py http://127.0.0.1:8127/debug/ledger
    python scripts/flow_report.py http://host:8127 --intervals 8
    python scripts/flow_report.py saved-ledger.json

Exit codes: 0 = every identity balanced (net unexplained == 0),
1 = nonzero unexplained imbalance somewhere, 2 = could not read input.

stdlib-only (urllib) so it runs anywhere the operator has Python.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List

BAL = 1e-6


def _fmt(v) -> str:
    if v is None:
        return "-"
    f = float(v)
    return str(int(f)) if f.is_integer() else f"{f:g}"


def load_report(source: str, intervals: int = 0) -> dict:
    """Fetch the report from a URL (``/debug/ledger`` appended when the
    path is missing) or read it from a JSON file."""
    if source.startswith(("http://", "https://")):
        from urllib.request import urlopen
        url = source
        if "/debug/ledger" not in url:
            url = url.rstrip("/") + "/debug/ledger"
        if intervals:
            sep = "&" if "?" in url else "?"
            url = f"{url}{sep}intervals={intervals}"
        with urlopen(url, timeout=10) as resp:
            return json.loads(resp.read())
    with open(source) as f:
        return json.loads(f.read())


def format_report(report: dict, intervals: int = 0) -> str:
    """The full text rendering (separated from main for the smoke
    test: feed it a server's ledger.report() and eyeball the table)."""
    lines: List[str] = []
    add = lines.append
    add("flow ledger — conservation report")
    add(f"  intervals closed: {report.get('intervals_closed', 0)}"
        f"   strict: {report.get('strict', False)}"
        f"   enabled: {report.get('enabled', True)}")
    add("")
    idents = report.get("identities", {})
    add("identities (inflow + opening == outflow + closing):")
    for name in sorted(idents):
        spec = idents[name]
        net = float(spec.get("imbalance_net", 0.0))
        total = float(spec.get("unexplained_total", 0.0))
        flag = "  OK" if total <= BAL else "  ** UNEXPLAINED **"
        add(f"  {name}: net {_fmt(net)}  "
            f"unexplained {_fmt(total)}{flag}")
        add(f"    in:     {' + '.join(spec.get('inputs', [])) or '-'}")
        add(f"    out:    {' + '.join(spec.get('outputs', [])) or '-'}")
        if spec.get("stocks"):
            add(f"    stocks: {', '.join(spec['stocks'])}")
    add("")
    stocks = report.get("stocks", {})
    if stocks:
        add("live stocks:")
        for name in sorted(stocks):
            line = f"  {name}: {_fmt(stocks[name])}"
            if name == "spool_quarantine" and float(stocks[name] or 0) > 0:
                # quarantined WAL segments are inventoried, not lost —
                # but an operator should know they exist (restore or
                # purge them; see the README backfill runbook)
                line += "  ** quarantined segments on disk **"
            add(line)
        add("")
    totals = report.get("stage_totals", {})
    if totals:
        add("lifetime stage totals:")
        for stage in sorted(totals):
            per_key = totals[stage]
            detail = ", ".join(
                f"{k or 'total'}={_fmt(v)}"
                for k, v in sorted(per_key.items()))
            add(f"  {stage}: {detail}")
        add("")
    history = report.get("intervals", [])
    if intervals:
        history = history[-intervals:]
    if history:
        add(f"last {len(history)} interval(s), oldest first:")
        for rec in history:
            imb = rec.get("imbalance", {})
            bad = {k: v for k, v in imb.items() if abs(float(v)) > BAL}
            mark = f"  ** {bad} **" if bad else "  ok"
            # the interval's self-trace id cross-links a finding to
            # GET /debug/traces?trace_id=<id> on every tier it crossed
            trace = (f"  trace={rec['trace_id']}"
                     if rec.get("trace_id") else "")
            add(f"  #{rec.get('interval')}  "
                f"closed={_fmt(rec.get('closed_unix'))}{trace}{mark}")
            for stage in sorted(rec.get("stages", {})):
                per_key = rec["stages"][stage]
                total = sum(float(v) for v in per_key.values())
                add(f"      {stage}: {_fmt(total)}")
    return "\n".join(lines)


def net_unexplained(report: dict) -> float:
    """Cumulative unexplained imbalance across identities — the
    lifetime |imbalance| sum, NOT the net (two opposite-sign leaks must
    not self-cancel into a clean exit code)."""
    return sum(float(spec.get("unexplained_total", 0.0))
               for spec in report.get("identities", {}).values())


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("source",
                        help="ledger URL (http://host:port[/debug/ledger])"
                             " or a saved JSON file")
    parser.add_argument("--intervals", type=int, default=0,
                        help="show only the last N intervals")
    args = parser.parse_args(argv)
    try:
        report = load_report(args.source, args.intervals)
    except Exception as e:
        print(f"error: could not read {args.source}: {e}", file=sys.stderr)
        return 2
    print(format_report(report, args.intervals))
    return 0 if net_unexplained(report) <= BAL else 1


if __name__ == "__main__":
    sys.exit(main())
